"""Multi-host (multi-process) runtime: initialization + hybrid DCN/ICI mesh.

The reference is a single process with no communication backend (SURVEY.md
§2 "Parallelism strategies": no NCCL/MPI/Gloo anywhere in G2Vec.py). This
framework's comm backend is JAX's: one process per host, all chips of all
hosts in one global device list, XLA collectives compiled from sharding
annotations — riding ICI inside a slice and DCN between slices. This module
owns the two pieces a multi-host launch needs:

1. ``initialize()`` — a thin, env-var-aware wrapper over
   ``jax.distributed.initialize``. On TPU pods the coordinator/process
   topology is auto-detected from the TPU metadata, so a bare
   ``initialize()`` suffices; on CPU/GPU fleets (or forced topologies) pass
   ``coordinator/process_id/num_processes`` or set ``G2VEC_COORDINATOR``,
   ``G2VEC_PROCESS_ID``, ``G2VEC_NUM_PROCESSES``.

2. ``make_global_mesh(data, model)`` — a ('data', 'model') mesh over ALL
   global devices. When the mesh spans multiple slices/hosts it is built
   with ``mesh_utils.create_hybrid_device_mesh`` so the *model* axis (the
   gene-sharded W_ih contraction, which psums every step — see
   parallel/mesh.py) stays inside a slice on ICI, and the *data* axis (one
   gradient psum per step) crosses DCN. That assignment is this workload's
   whole bandwidth story: activations-heavy collectives on the fast fabric,
   gradient reduction on the slow one.

Single-host virtual testing: ``XLA_FLAGS=--xla_force_host_platform_device_count=N``
with ``make_global_mesh`` exercises the identical code path (SURVEY.md §4
item 5); the driver's ``dryrun_multichip`` does exactly that.
"""
from __future__ import annotations

import os
from typing import Optional, Tuple

from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS, MeshContext

_ENV_COORD = "G2VEC_COORDINATOR"
_ENV_PID = "G2VEC_PROCESS_ID"
_ENV_NPROC = "G2VEC_NUM_PROCESSES"

_initialized = False

# Structured records of initialize() outcomes that matter operationally,
# queued until a metrics stream exists to receive them (initialize runs
# before the pipeline opens --metrics-jsonl). pipeline.run drains this.
_pending_events: list = []


def initialize(coordinator: Optional[str] = None,
               process_id: Optional[int] = None,
               num_processes: Optional[int] = None) -> None:
    """Join (or bootstrap) the multi-process JAX runtime. Idempotent.

    Argument > environment > auto-detection (TPU metadata). Must run before
    the first jax backend use in the process. After :func:`shutdown` the
    module is re-initializable — an in-process supervisor restart can tear
    the runtime down and rejoin.
    """
    global _initialized
    if _initialized:
        return
    import jax

    coordinator = coordinator or os.environ.get(_ENV_COORD)
    if process_id is None and os.environ.get(_ENV_PID):
        process_id = int(os.environ[_ENV_PID])
    if num_processes is None and os.environ.get(_ENV_NPROC):
        num_processes = int(os.environ[_ENV_NPROC])
    kwargs = {}
    if coordinator:
        kwargs["coordinator_address"] = coordinator
    if process_id is not None:
        kwargs["process_id"] = process_id
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    try:
        jax.distributed.initialize(**kwargs)
    except ValueError:
        if kwargs:
            raise
        # Off-TPU with nothing specified there is no cluster auto-detection;
        # bootstrap a single-process "cluster" on localhost so --distributed
        # is a no-op rather than an error (useful for smoke tests). LOUD:
        # on a misconfigured fleet launch every process would land here
        # believing it is process 0 and write the same outputs (ADVICE.md
        # round 1) — so besides the stderr warning, a structured
        # ``single_process_fallback`` event is queued for the metrics
        # stream, where post-hoc tooling actually looks.
        import sys

        print("g2vec_tpu: WARNING: --distributed found no coordinator "
              "(no TPU metadata, no G2VEC_COORDINATOR/PROCESS_ID/"
              "NUM_PROCESSES); bootstrapping a SINGLE-process localhost "
              "runtime. If this is one process of a multi-host launch, "
              "its peers were NOT found — check the launch flags.",
              file=sys.stderr)
        import socket

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        jax.distributed.initialize(
            coordinator_address=f"127.0.0.1:{port}",
            num_processes=1, process_id=0)
        _pending_events.append({
            "event": "single_process_fallback",
            "reason": "no coordinator: no TPU metadata and no "
                      "G2VEC_COORDINATOR/PROCESS_ID/NUM_PROCESSES",
            "coordinator": f"127.0.0.1:{port}"})
    _initialized = True


def drain_pending_events() -> list:
    """Hand queued initialize() events to the caller (the pipeline emits
    them into the metrics stream); the queue empties."""
    out, _pending_events[:] = list(_pending_events), []
    return out


def shutdown() -> None:
    """Tear down the distributed runtime and make :func:`initialize`
    callable again (reset-safe ``_initialized``). Safe to call when never
    initialized. An in-process supervisor restart uses this to rejoin
    after a runtime teardown instead of silently reusing dead state."""
    global _initialized
    if not _initialized:
        return
    import jax

    try:
        jax.distributed.shutdown()
    except Exception as e:  # noqa: BLE001 — a dead runtime must not block
        import warnings

        warnings.warn(f"jax.distributed.shutdown failed ({e!r}); "
                      "resetting the module flag anyway", RuntimeWarning)
    _initialized = False


def cpu_fleet() -> bool:
    """True in a multi-process run on the CPU backend — where XLA cannot
    compile cross-process computations (``Multiprocess computations aren't
    implemented on the CPU backend``), so device stages run replicated on
    process-local meshes and every host-data collective rides the
    coordination-service KV transport (parallel/hostcomm.py)."""
    import jax

    return jax.process_count() > 1 and jax.default_backend() == "cpu"


def host_allgather(name: str, arr) -> "np.ndarray":  # noqa: F821
    """Backend-aware host-array allgather: ``[nproc, *arr.shape]``.

    COLLECTIVE. CPU fleets use the KV transport (deadline-aware, names
    missing ranks); backends with real cross-process XLA use
    ``multihost_utils.process_allgather`` under the fleet watchdog, so a
    dead peer surfaces as PeerTimeoutError instead of an eternal block.
    """
    import jax
    import numpy as np

    arr = np.asarray(arr)
    if jax.process_count() == 1:
        return arr[None]
    from g2vec_tpu.resilience import fleet

    if cpu_fleet():
        from g2vec_tpu.parallel import hostcomm

        return hostcomm.allgather_array(
            name, arr, deadline=fleet.config().watchdog_deadline or None)
    from g2vec_tpu.resilience.faults import fault_point

    fault_point("allgather")
    from jax.experimental import multihost_utils

    return fleet.collective_watchdog(
        name, lambda: np.asarray(multihost_utils.process_allgather(arr)))


def plan_hybrid_mesh(devices, data: int, model: int):
    """Hybrid-mesh factorization for a (possibly) multi-slice topology.

    Pure planning — unit-testable with mock devices carrying
    ``slice_index`` — shared by :func:`make_global_mesh`:

    - single-slice (or no slice metadata): returns None — the caller uses
      ``create_device_mesh``, which picks an ICI-contiguous layout;
    - N > 1 slices: returns ``(per_slice_mesh, dcn_mesh)`` for
      ``create_hybrid_device_mesh``. The MODEL axis (an all-reduce inside
      every forward/backward matmul) stays entirely inside a slice on
      ICI — ``dcn_mesh`` is (n_slices, 1), never sharding model across
      DCN — and the DATA axis (one gradient psum per step) is the one
      that crosses slices, factored as n_slices x (data // n_slices).
      The data axis must divide by the slice count or no such assignment
      exists; the error names the constraint.
    """
    n_slices = len({getattr(d, "slice_index", 0) for d in devices})
    if n_slices <= 1:
        return None
    if data % n_slices:
        raise ValueError(
            f"data axis {data} must be divisible by the slice count "
            f"{n_slices} so the model axis stays on ICI")
    return (data // n_slices, model), (n_slices, 1)


def make_global_mesh(mesh_shape: Tuple[int, int],
                     allow_hybrid: bool = True) -> MeshContext:
    """('data', 'model') MeshContext over all global devices.

    ``mesh_shape=(data, model)`` must multiply to the global device count.
    Multi-slice topologies get a hybrid mesh (model inside a slice on ICI,
    data across slices on DCN — :func:`plan_hybrid_mesh`); single-slice
    falls back to ``create_device_mesh`` which picks an ICI-contiguous
    layout.
    """
    import jax
    from jax.experimental import mesh_utils
    from jax.sharding import Mesh

    data, model = mesh_shape
    devices = jax.devices()
    if data * model != len(devices):
        raise ValueError(
            f"mesh {mesh_shape} needs {data * model} devices; the global "
            f"runtime has {len(devices)} "
            f"(processes: {jax.process_count()})")
    plan = plan_hybrid_mesh(devices, data, model) if allow_hybrid else None
    if plan is not None:
        per_slice, dcn = plan
        grid = mesh_utils.create_hybrid_device_mesh(
            mesh_shape=per_slice, dcn_mesh_shape=dcn, devices=devices)
    else:
        grid = mesh_utils.create_device_mesh((data, model), devices=devices)
    return MeshContext(mesh=Mesh(grid, (DATA_AXIS, MODEL_AXIS)))


def fetch_global(arr) -> "np.ndarray":  # noqa: F821 — np imported lazily
    """Device array -> host numpy, correct across process boundaries.

    ``np.asarray``/``jax.device_get`` raise on a global array whose shards
    live on devices other processes own (e.g. the model-sharded W_ih under
    a multi-host mesh). This gathers the full value on every process — it
    is a COLLECTIVE: all processes must call it, in the same order. The
    gather runs under the fleet watchdog: with a configured
    ``--fleet-watchdog-deadline`` a dead/straggling peer raises
    :class:`~g2vec_tpu.resilience.fleet.PeerTimeoutError` naming the
    suspect rank(s) instead of blocking forever.
    """
    import jax
    import numpy as np

    if getattr(arr, "is_fully_addressable", True):
        return np.asarray(jax.device_get(arr))
    from g2vec_tpu.resilience import fleet
    from g2vec_tpu.resilience.faults import fault_point

    fault_point("allgather")
    from jax.experimental import multihost_utils

    return fleet.collective_watchdog(
        "fetch_global",
        lambda: np.asarray(multihost_utils.process_allgather(arr, tiled=True)))


def sharded_native_path_set(src, dst, w, n_genes: int, *, len_path: int,
                            reps: int, seed: int, n_threads: int = 0
                            ) -> "Set[bytes]":  # noqa: F821
    """Multi-process native walks: host-walks-chip-trains at fleet scale.

    Each process samples a contiguous shard of the flat (repetition x
    start) walker axis with the SAME global stream identities the
    single-host call uses (ops/host_walker.walk_packed_rows), then the
    packed rows are allgathered and unioned — every process returns a set
    bit-identical to the single-host ``generate_path_set_native`` result,
    with the walk work divided ~evenly across hosts.

    COLLECTIVE: all processes must call it with identical arguments. The
    native toolchain is availability-checked across processes FIRST, so a
    host without g++ fails every process with one clear error instead of
    wedging the allgather. All three gathers ride :func:`host_allgather`,
    so they work on CPU fleets (KV transport) and time out with named
    ranks under the fleet watchdog everywhere.
    """
    import jax
    import numpy as np

    from g2vec_tpu.ops.backend import native_walker_available
    from g2vec_tpu.ops.host_walker import walk_packed_rows

    nproc = jax.process_count()
    if nproc == 1:
        from g2vec_tpu.ops.host_walker import generate_path_set_native

        return generate_path_set_native(src, dst, w, n_genes,
                                        len_path=len_path, reps=reps,
                                        seed=seed, n_threads=n_threads)
    avail = host_allgather(
        "native_avail", np.array([native_walker_available()], dtype=bool))
    if not avail.all():
        missing = [int(p) for p in np.nonzero(~avail.reshape(-1))[0]]
        raise RuntimeError(
            f"walker_backend=native needs the C++ sampler on every host; "
            f"process(es) {missing} cannot build it (no toolchain?). "
            f"Pin --walker-backend device, or fix those hosts.")

    total = n_genes * reps
    per = -(-total // nproc)                      # ceil
    pid = jax.process_index()
    lo = min(pid * per, total)
    hi = min(lo + per, total)
    rows = walk_packed_rows(src, dst, w, n_genes, len_path=len_path,
                            reps=reps, seed=seed, n_threads=n_threads,
                            walker_lo=lo, walker_hi=hi)
    nbytes = (n_genes + 7) // 8
    padded = np.zeros((per, nbytes), dtype=np.uint8)
    padded[:rows.shape[0]] = rows
    counts = host_allgather(
        "native_counts", np.array([rows.shape[0]], dtype=np.int64))
    gathered = host_allgather("native_rows", padded)    # [nproc, per, nb]
    counts = counts.reshape(-1)
    out: set = set()
    for p in range(nproc):
        shard = gathered[p, : int(counts[p])]
        out.update(row.tobytes() for row in shard)
    return out


def process_info() -> dict:
    """Who am I in the job — for logs and the metrics stream."""
    import jax

    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_devices": len(jax.local_devices()),
        "global_devices": len(jax.devices()),
    }


def is_coordinator() -> bool:
    """True on the process that should write outputs (process 0).

    The pipeline's three text writers and the console transcript run only
    here; worker processes compute and hold shards but do not write files.
    """
    import jax

    return jax.process_index() == 0
