"""Gene-range and walk-partition sharding for million-node graphs.

ROADMAP item 2: every subsystem before this module assumes the graph's
CSR, the walk volume, and the ``[G, H]`` embedding table fit one host.
This module owns the *partitioning arithmetic and host collectives* that
break that assumption; train/stream.py, ops/kmeans.py, analysis.py and
pipeline.py consume it. Two independent axes, two flags:

- ``--graph-shards N`` partitions the streaming walk-shard *sequence*
  into N contiguous partitions; partition ``p`` is SAMPLED only by rank
  ``p % n_ranks`` (on the PR 3 host pool) and its packed rows are
  exchanged to the other ranks over the chunked KV transport
  (parallel/hostcomm.exchange_bytes) — a remote rank is just another
  shard producer feeding the PR 7 ring. Every rank still *spools* every
  shard locally, so epoch replay and rewalk-on-corrupt stay local.
- ``--embed-shards R`` splits the ``[G, H]`` embedding table by a
  byte-aligned gene range per rank (R must equal the process count), so
  a rank densifies and trains only ``[G/R, H]`` — the per-rank memory
  cap that makes 100-1000x larger graphs fit. The softmax head ``w_ho``
  stays replicated by determinism (every rank sees identical reduced
  activations and applies the identical update). K-means, t-scores and
  the min-max rescale then run over the local slice, reducing only
  per-cluster statistics and masked extrema; full-width vectors are
  gathered rank-by-rank at the writer boundary alone.

Why byte-aligned ranges: walk rows travel and spool as np.packbits
rows (8 genes/byte, MSB first). A rank whose gene range starts on a
multiple of 8 can slice its columns *in packed form* —
``rows[:, lo // 8 : (hi + 7) // 8]`` — and unpack only its own slice on
device; the full-width multi-hot never materializes on any single rank.

CPU fleets cannot compile cross-process XLA ("Multiprocess computations
aren't implemented on the CPU backend"), so the "psum" of the sharded
trainer is realized as a deterministic host allreduce over the KV-store
allgather (rank-order summation — every rank reduces in the same order,
so replicated state stays bit-identical across ranks). On backends with
real cross-process XLA the same module works unchanged; swapping the
transport for jit-time psums is a pure optimization left signposted.

Parity contract (tests/test_shard.py): at ``n_ranks == 1`` the sharded
mode routes through EXACTLY the unsharded code paths (the local gene
range is the full range, the walk exchange is a passthrough) and is
byte-identical to a run without the flags. At ``n_ranks > 1`` the
reduction order of the hidden activations differs from the one-matmul
unsharded program, so the contract is the PR 7 statistical one (val-ACC
band + biomarker overlap), NOT bitwise.
"""
from __future__ import annotations

import dataclasses
import io
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """The pure partitioning arithmetic — unit-testable without jax.

    ``embed_shards > 0`` activates gene-range splitting (and must then
    equal ``n_ranks``); ``graph_shards > 0`` activates walk-partition
    ownership. Either axis alone is a valid mode: graph-only shards the
    sampling work while the model stays replicated; embed-only shards
    the model while every rank samples everything.
    """

    rank: int
    n_ranks: int
    n_genes: int
    graph_shards: int = 0
    embed_shards: int = 0

    def __post_init__(self):
        if not (0 <= self.rank < max(1, self.n_ranks)):
            raise ValueError(f"rank {self.rank} outside n_ranks {self.n_ranks}")
        if self.embed_shards and self.embed_shards != self.n_ranks:
            raise ValueError(
                f"embed_shards ({self.embed_shards}) must equal the rank "
                f"count ({self.n_ranks}): the gene range is split 1:1 "
                f"across ranks")
        if self.embed_shards > 1 and self.n_genes < 8 * self.embed_shards:
            raise ValueError(
                f"embed sharding needs >= 8 genes (one packed byte) per "
                f"rank; {self.n_genes} genes across {self.embed_shards} "
                f"ranks is too few")

    # ---- embedding (gene-range) axis ----------------------------------
    @property
    def n_bytes(self) -> int:
        """Packed row width: ceil(G / 8)."""
        return (self.n_genes + 7) // 8

    def byte_range(self, rank: Optional[int] = None) -> Tuple[int, int]:
        """Rank's contiguous slice of the packed byte columns."""
        r = self.rank if rank is None else rank
        if not self.embed_shards or self.n_ranks == 1:
            return (0, self.n_bytes)
        nb, R = self.n_bytes, self.n_ranks
        return (r * nb // R, (r + 1) * nb // R)

    def gene_range(self, rank: Optional[int] = None) -> Tuple[int, int]:
        """Rank's gene range [lo, hi) — lo is a multiple of 8 by
        construction; hi is clipped to G on the last rank."""
        blo, bhi = self.byte_range(rank)
        return (blo * 8, min(bhi * 8, self.n_genes))

    @property
    def lo(self) -> int:
        return self.gene_range()[0]

    @property
    def hi(self) -> int:
        return self.gene_range()[1]

    @property
    def g_local(self) -> int:
        return self.hi - self.lo

    @property
    def embed_split(self) -> bool:
        """True when the trainer/stats must take the split-program path.
        Single-rank "sharded" mode stays on the plain programs — the
        byte-identity contract."""
        return bool(self.embed_shards) and self.n_ranks > 1

    def slice_packed(self, rows: np.ndarray) -> np.ndarray:
        """Local packed byte columns of full-width rows [N, ceil(G/8)]."""
        blo, bhi = self.byte_range()
        return np.ascontiguousarray(rows[:, blo:bhi])

    # ---- walk-partition axis ------------------------------------------
    def shard_owner(self, si: int, n_shards: int) -> int:
        """The rank that samples streaming shard ``si`` of ``n_shards``.

        The shard sequence is cut into ``graph_shards`` contiguous
        partitions (a partition is a start-gene range — shard indices
        ARE start-major); partition ``p`` belongs to rank ``p % R``.
        With graph sharding off every rank owns everything itself.
        """
        if not self.graph_shards or self.n_ranks == 1:
            return self.rank
        if not (0 <= si < n_shards):
            raise ValueError(f"shard {si} outside [0, {n_shards})")
        p = si * self.graph_shards // n_shards
        return p % self.n_ranks


class ShardContext:
    """ShardSpec + the host collectives the sharded stages ride.

    All reductions here are MAIN-THREAD collectives in program order on
    every rank (the hostcomm sequence-number contract). The walk-shard
    exchange — which runs on the PRODUCER thread — must NOT come through
    here; it uses the explicit-key ``hostcomm.exchange_bytes`` transport
    directly (see the thread-safety note in parallel/hostcomm.py).
    """

    def __init__(self, spec: ShardSpec, *, deadline: Optional[float] = None):
        self.spec = spec
        self.deadline = deadline

    @property
    def single(self) -> bool:
        return self.spec.n_ranks == 1

    def allreduce(self, name: str, arr: np.ndarray, op: str = "sum"
                  ) -> np.ndarray:
        """Deterministic allreduce of a same-shape host array.

        Rank-order reduction: every rank applies the identical
        left-to-right fold over the allgathered stack, so replicated
        downstream state (the softmax head, k-means centers, early-stop
        decisions) stays bit-identical across ranks.
        """
        arr = np.asarray(arr)
        if self.single:
            return arr
        from g2vec_tpu.parallel import hostcomm

        stack = hostcomm.allgather_array(name, arr, deadline=self.deadline)
        fold = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
        acc = stack[0]
        for p in range(1, stack.shape[0]):
            acc = fold(acc, stack[p])
        return acc

    def gather_concat(self, name: str, arr: np.ndarray, axis: int = 0
                      ) -> np.ndarray:
        """Concatenate per-rank arrays (unequal shapes along ``axis``
        allowed) in rank order, on every rank. The writer-boundary
        gather for scores/labels — small [G]-shaped vectors, never the
        [G, H] table (vectors stream rank-by-rank instead;
        pipeline._write_vectors_sharded)."""
        arr = np.ascontiguousarray(arr)
        if self.single:
            return arr
        from g2vec_tpu.parallel import hostcomm

        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        parts = hostcomm.allgather_bytes(name, buf.getvalue(),
                                         deadline=self.deadline)
        return np.concatenate(
            [np.load(io.BytesIO(p), allow_pickle=False) for p in parts],
            axis=axis)

    def broadcast_array(self, name: str, arr: Optional[np.ndarray]
                        ) -> np.ndarray:
        """Rank 0's array on every rank (k-means seeding, center state)."""
        if self.single:
            if arr is None:
                raise ValueError(f"broadcast {name!r}: rank-0 array is None")
            return np.asarray(arr)
        from g2vec_tpu.parallel import hostcomm

        payload = None
        if arr is not None:
            buf = io.BytesIO()
            np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
            payload = buf.getvalue()
        raw = hostcomm.broadcast_bytes(name, payload, deadline=self.deadline)
        return np.load(io.BytesIO(raw), allow_pickle=False)


def make_shard_context(graph_shards: int, embed_shards: int, n_genes: int,
                       *, deadline: Optional[float] = None
                       ) -> Optional[ShardContext]:
    """The pipeline's entry point: None when both axes are off, else a
    context bound to this process's rank. Validates the embed split
    against the ACTUAL process count (config.py can only check flags
    against flags)."""
    if not graph_shards and not embed_shards:
        return None
    import jax

    spec = ShardSpec(rank=jax.process_index(), n_ranks=jax.process_count(),
                     n_genes=n_genes, graph_shards=graph_shards,
                     embed_shards=embed_shards)
    return ShardContext(spec, deadline=deadline)


# ---------------------------------------------------------------------------
# Edge-partitioned CSR (--edge-partition): owner-range graph storage.
#
# PR 10's graph sharding divided the walk WORK; every rank still held the
# full CSR — the last single-host cap on graph size. Here each rank
# materializes only the adjacency rows of its own gene range (plus, in
# halo mode, the 1-hop boundary neighbors' rows), and walks that step
# onto a row this rank does not hold are SUSPENDED as explicit
# WalkStateBatch state (ops/host_walker.py) and shipped to the owning
# rank over the explicit-key KV transport. Both boundary strategies ride
# the same engine: `handoff` ships every boundary crossing; `halo` keeps
# replicated boundary rows so most walks finish locally and only
# halo-escapes (2+ hops outside the range) fall back to handoff. Because
# every walker's PRNG stream is keyed by global walker index and its raw
# state travels with it, handoff and halo produce byte-identical rows —
# and a single rank (full range) is byte-identical to unsharded.
# ---------------------------------------------------------------------------


def edge_range(rank: int, n_ranks: int, n_genes: int) -> Tuple[int, int]:
    """Rank's owned gene range [lo, hi) on the edge-partition axis —
    plain ``r*G/R`` splits (no byte alignment: this axis partitions CSR
    *rows*; packed columns are the embed axis's concern)."""
    if not (0 <= rank < max(1, n_ranks)):
        raise ValueError(f"rank {rank} outside n_ranks {n_ranks}")
    return (rank * n_genes // max(1, n_ranks),
            (rank + 1) * n_genes // max(1, n_ranks))


def edge_bounds(n_ranks: int, n_genes: int) -> np.ndarray:
    """[R] int64 lower bounds of every rank's owned range (for
    vectorized owner lookup via searchsorted)."""
    return np.array([r * n_genes // n_ranks for r in range(n_ranks)],
                    dtype=np.int64)


def owners_of(genes: np.ndarray, bounds: np.ndarray) -> np.ndarray:
    """Owning rank of each gene id under :func:`edge_bounds`."""
    return (np.searchsorted(bounds, np.asarray(genes, dtype=np.int64),
                            side="right") - 1).astype(np.int32)


@dataclasses.dataclass
class PartitionedCSR:
    """One rank's partial view of a group's walk graph.

    ``indptr`` spans the FULL gene axis (G+1 entries) but only rows with
    ``avail[g] == 1`` hold data — owned rows always, plus halo rows in
    halo mode. The native partial walker (g2v_walk_partial) suspends any
    walk whose current gene has ``avail == 0`` instead of scanning it,
    so an empty non-owned row can never masquerade as a dead end.
    """

    n_genes: int
    lo: int
    hi: int
    indptr: np.ndarray
    indices: np.ndarray
    weights: np.ndarray
    avail: np.ndarray               # uint8 [G]
    halo_genes: np.ndarray          # int32, sorted, empty unless halo
    owned_edges: int
    halo_edges: int = 0

    @property
    def csr(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        return (self.indptr, self.indices, self.weights)

    @property
    def csr_bytes(self) -> int:
        """Bytes this rank actually holds for the graph (indptr +
        indices + weights + avail mask)."""
        return (self.indptr.nbytes + self.indices.nbytes
                + self.weights.nbytes + self.avail.nbytes)

    @property
    def halo_bytes(self) -> int:
        """Bytes attributable to replicated halo rows (8 bytes/edge:
        index + weight)."""
        return 8 * self.halo_edges

    @property
    def halo_overhead_ratio(self) -> float:
        """Halo bytes over owned-row bytes — the measured memory price
        of completing boundary walks locally."""
        owned = 8 * self.owned_edges
        return (self.halo_bytes / owned) if owned else 0.0


def build_partitioned_csr(src: np.ndarray, dst: np.ndarray, w: np.ndarray,
                          n_genes: int, lo: int, hi: int) -> PartitionedCSR:
    """Owned-rows-only CSR from a RANGE-FILTERED edge list (every
    ``src`` must already be inside [lo, hi) — the reader/generator did
    the filtering; this guards the contract instead of re-filtering,
    so no code path here ever touches the full edge list)."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    w = np.asarray(w)
    if src.size and (src.min() < lo or src.max() >= hi):
        raise ValueError(
            f"edge sources outside the owned range [{lo}, {hi}) — the "
            f"range-filtered reader must only hand this rank its own rows")
    if dst.size and (dst.min() < 0 or dst.max() >= n_genes):
        raise ValueError(f"dst contains node ids outside [0, {n_genes})")
    from g2vec_tpu.ops.host_walker import edges_to_csr

    indptr, indices, weights = edges_to_csr(src, dst, w, n_genes)
    avail = np.zeros(n_genes, dtype=np.uint8)
    avail[lo:hi] = 1
    return PartitionedCSR(
        n_genes=n_genes, lo=lo, hi=hi, indptr=indptr, indices=indices,
        weights=weights, avail=avail,
        halo_genes=np.zeros(0, dtype=np.int32), owned_edges=int(src.size))


def _savez_bytes(**arrays) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _loadz_bytes(raw: bytes) -> dict:
    with np.load(io.BytesIO(raw), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def build_halo_csr(pcsr: PartitionedCSR, *, rank: int, n_ranks: int,
                   group: int, exchange=None,
                   deadline: Optional[float] = None) -> PartitionedCSR:
    """Collective halo build: replicate the 1-hop boundary neighbors'
    rows onto this rank so most walks complete locally.

    Two uniform all-pairs rounds over the explicit-key transport (safe
    on any thread; here it runs on the main thread during trainer
    setup): every rank first publishes, per peer, the sorted list of
    boundary genes it wants from that peer's range, then serves the
    requested row slices. A rank killed between the rounds (the
    ``halo_build`` fault seam) leaves its requesters' receive waiting —
    the transport deadline names it (tests/test_edge.py drill).
    """
    from g2vec_tpu.parallel import hostcomm
    from g2vec_tpu.resilience.faults import fault_point

    if n_ranks == 1:
        return pcsr
    if exchange is None:
        exchange = hostcomm.exchange_bytes
    bounds = edge_bounds(n_ranks, pcsr.n_genes)
    outside = pcsr.indices[(pcsr.indices < pcsr.lo)
                           | (pcsr.indices >= pcsr.hi)]
    wants = np.unique(outside).astype(np.int64)
    want_owner = owners_of(wants, bounds)
    kw = dict(deadline=deadline) if deadline else {}
    for b in range(n_ranks):
        if b == rank:
            continue
        payload = _savez_bytes(genes=wants[want_owner == b].astype(np.int32))
        exchange(f"halo/g{group}/want/{rank}to{b}", payload, rank, **kw)
    requests = {}
    for a in range(n_ranks):
        if a == rank:
            continue
        raw = exchange(f"halo/g{group}/want/{a}to{rank}", None, a, **kw)
        requests[a] = _loadz_bytes(raw)["genes"].astype(np.int64)
    # The dead-server seam: a sigkill here (after the want lists, before
    # any row payload) leaves every requester waiting on this rank's
    # row publish; their deadline expiry names it.
    fault_point("halo_build", epoch=group)
    for b, req in requests.items():
        counts = (pcsr.indptr[req + 1] - pcsr.indptr[req]).astype(np.int32)
        slices = [pcsr.indices[pcsr.indptr[g]:pcsr.indptr[g + 1]]
                  for g in req]
        wslices = [pcsr.weights[pcsr.indptr[g]:pcsr.indptr[g + 1]]
                   for g in req]
        payload = _savez_bytes(
            genes=req.astype(np.int32), counts=counts,
            indices=(np.concatenate(slices) if slices
                     else np.zeros(0, np.int32)),
            weights=(np.concatenate(wslices) if wslices
                     else np.zeros(0, np.float32)))
        exchange(f"halo/g{group}/rows/{rank}to{b}", payload, rank, **kw)
    halo_src, halo_dst, halo_w, halo_genes = [], [], [], []
    for a in range(n_ranks):
        if a == rank:
            continue
        raw = exchange(f"halo/g{group}/rows/{a}to{rank}", None, a, **kw)
        z = _loadz_bytes(raw)
        halo_genes.append(z["genes"].astype(np.int32))
        halo_src.append(np.repeat(z["genes"].astype(np.int32),
                                  z["counts"].astype(np.int64)))
        halo_dst.append(z["indices"].astype(np.int32))
        halo_w.append(z["weights"].astype(np.float32))
    from g2vec_tpu.ops.host_walker import edges_to_csr

    own_src = np.repeat(np.arange(pcsr.n_genes, dtype=np.int32),
                        np.diff(pcsr.indptr).astype(np.int64))
    src = np.concatenate([own_src] + halo_src)
    dst = np.concatenate([pcsr.indices] + halo_dst)
    w = np.concatenate([pcsr.weights] + halo_w)
    indptr, indices, weights = edges_to_csr(src, dst, w, pcsr.n_genes)
    genes = np.sort(np.concatenate(halo_genes)) if halo_genes \
        else np.zeros(0, np.int32)
    avail = pcsr.avail.copy()
    avail[genes] = 1
    return PartitionedCSR(
        n_genes=pcsr.n_genes, lo=pcsr.lo, hi=pcsr.hi, indptr=indptr,
        indices=indices, weights=weights, avail=avail, halo_genes=genes,
        owned_edges=pcsr.owned_edges,
        halo_edges=int(src.size - own_src.size))


@dataclasses.dataclass
class EdgeWalkStats:
    """Handoff accounting across one run's shards (metrics ``handoff``
    event; surfaced in BENCH_EDGE_PARTITION.json when
    ``bench.py --_edge_ab`` regenerates it)."""

    shards: int = 0
    rounds: int = 0
    states_sent: int = 0            # suspended walk states shipped
    batches: int = 0                # non-empty per-destination batches
    peak_in_flight: int = 0         # max states in flight in one round


@dataclasses.dataclass
class EdgeContext:
    """What the pipeline hands the streaming trainer for a MULTI-rank
    edge-partitioned run: the per-group partial CSRs (halo-merged in
    halo mode) plus the run-wide handoff accounting. Single-rank
    edge-partitioned runs pass None — the range is the whole graph, so
    the trainer's plain unsharded paths apply (byte-identity)."""

    mode: str                       # "handoff" | "halo"
    pcsrs: List[PartitionedCSR]     # one per prognosis group
    stats: EdgeWalkStats


def run_edge_walk(pcsr: PartitionedCSR, plan, shard_index: int, *,
                  seed: int, owner: int, rank: int, n_ranks: int,
                  starts: Optional[np.ndarray] = None, n_threads: int = 0,
                  exchange=None, deadline: Optional[float] = None,
                  key_prefix: str = "edge", cancelled=None,
                  stats: Optional[EdgeWalkStats] = None
                  ) -> Optional[np.ndarray]:
    """Collectively walk one group's shard over partitioned CSRs.

    ALL ranks call this for every (shard, group) in the same order — it
    is a producer-thread collective over the explicit-key transport.
    The shard owner seeds the initial WalkStateBatch (global-walker-index
    PRNG streams); each round every rank advances the states it holds
    (native partial walker), scatters locally-finished paths, and ships
    suspended states to the rank owning their current gene, with
    finished remote paths riding the same payloads back to the owner.
    The round's payloads each carry the sender's outgoing-state count,
    so every rank computes the same global in-flight total and the loop
    terminates on the same round everywhere — the termination barrier
    (one all-pairs round even when zero walks cross a partition).

    Returns the shard-group's packed rows on the owner (walk_shard's
    exact layout and bytes), None on the other ranks — or None anywhere
    once ``cancelled()`` reports the consumer is gone.
    """
    import time as _time

    from g2vec_tpu.ops.host_walker import (WalkStateBatch,
                                           advance_walk_states,
                                           pack_finished_paths,
                                           shard_walk_states)
    from g2vec_tpu.resilience.faults import fault_point

    len_path = plan.len_path
    n_rows = plan.group_rows(shard_index)
    if n_ranks == 1:
        states = shard_walk_states(plan, shard_index, seed=seed,
                                   starts=starts)
        status = advance_walk_states(states, pcsr.csr, pcsr.n_genes,
                                     pcsr.avail, len_path,
                                     n_threads=n_threads)
        if status.any():
            raise RuntimeError(
                "single-rank edge-partitioned walk suspended — the full "
                "range must be available")
        return pack_finished_paths(states.paths, pcsr.n_genes)

    from g2vec_tpu.parallel import hostcomm
    from g2vec_tpu.resilience.fleet import PeerTimeoutError

    if exchange is None:
        exchange = hostcomm.exchange_bytes
    budget = deadline if deadline else hostcomm.DEFAULT_DEADLINE_S
    t_end = _time.monotonic() + budget

    def _recv(key: str, src_rank: int) -> Optional[bytes]:
        """Deadline-sliced receive that notices a cancelled consumer
        (the _exchange_rows polling pattern, train/stream.py)."""
        while True:
            left = t_end - _time.monotonic()
            if left <= 0:
                # Let the transport raise its own naming of the dead peer.
                return exchange(key, None, src_rank, deadline=1e-3)
            try:
                return exchange(key, None, src_rank,
                                deadline=min(2.0, left))
            except PeerTimeoutError:
                if cancelled is not None and cancelled():
                    return None

    bounds = edge_bounds(n_ranks, pcsr.n_genes)
    i_am_owner = rank == owner
    pending = (shard_walk_states(plan, shard_index, seed=seed, starts=starts)
               if i_am_owner else WalkStateBatch.empty(len_path))
    done_paths = (np.full((n_rows, len_path), -1, np.int32)
                  if i_am_owner else None)
    n_done = 0
    rnd = 0
    if stats is not None:
        stats.shards += 1
    while True:
        fin = WalkStateBatch.empty(len_path)
        out: dict = {}
        if len(pending):
            status = advance_walk_states(pending, pcsr.csr, pcsr.n_genes,
                                         pcsr.avail, len_path,
                                         n_threads=n_threads)
            fin = pending.take(np.nonzero(status == 0)[0])
            sus = pending.take(np.nonzero(status == 1)[0])
            dest = owners_of(sus.cur, bounds)
            for b in range(n_ranks):
                sel = np.nonzero(dest == b)[0]
                if sel.size:
                    out[b] = sus.take(sel)
        if i_am_owner and len(fin):
            done_paths[fin.row] = fin.paths
            n_done += len(fin)
            fin = WalkStateBatch.empty(len_path)
        my_out = sum(len(b) for b in out.values())
        if stats is not None:
            stats.rounds += 1
            stats.states_sent += my_out
            stats.batches += sum(1 for b in out.values() if len(b))
        # The mid-walk seam: a rank sigkilled here holds suspended walk
        # state no other rank can reconstruct — the survivors' receive
        # deadline names it (tests/test_edge.py drill).
        fault_point("walk_handoff", epoch=shard_index)
        for b in range(n_ranks):
            if b == rank:
                continue
            batch = out.get(b, WalkStateBatch.empty(len_path))
            f = fin if b == owner else WalkStateBatch.empty(len_path)
            payload = _savez_bytes(
                row=batch.row, cur=batch.cur, rng=batch.rng, pos=batch.pos,
                paths=batch.paths, fin_row=f.row, fin_paths=f.paths,
                live=np.array([my_out], np.int64))
            exchange(f"{key_prefix}/{shard_index}/r{rnd}/{rank}to{b}",
                     payload, rank)
        incoming = [out[rank]] if rank in out else []
        global_live = my_out
        for a in range(n_ranks):
            if a == rank:
                continue
            raw = _recv(f"{key_prefix}/{shard_index}/r{rnd}/{a}to{rank}", a)
            if raw is None:
                return None          # consumer gone; exit quietly
            z = _loadz_bytes(raw)
            global_live += int(z["live"][0])
            if z["row"].size:
                incoming.append(WalkStateBatch(
                    row=z["row"].astype(np.int32),
                    cur=z["cur"].astype(np.int32),
                    rng=z["rng"].astype(np.uint64),
                    pos=z["pos"].astype(np.int32),
                    paths=z["paths"].astype(np.int32)))
            if i_am_owner and z["fin_row"].size:
                done_paths[z["fin_row"].astype(np.int64)] = \
                    z["fin_paths"].astype(np.int32)
                n_done += int(z["fin_row"].size)
        if stats is not None:
            stats.peak_in_flight = max(stats.peak_in_flight, global_live)
        pending = (WalkStateBatch.concat(incoming) if incoming
                   else WalkStateBatch.empty(len_path))
        rnd += 1
        if global_live == 0:
            break
    if not i_am_owner:
        return None
    if n_done != n_rows:
        raise RuntimeError(
            f"edge walk for shard {shard_index} terminated with "
            f"{n_done}/{n_rows} rows assembled — protocol bug")
    return pack_finished_paths(done_paths, pcsr.n_genes)


def subset_starts(n_genes: int, walk_starts: int) -> Optional[np.ndarray]:
    """Evenly spaced start-gene subset for ``--walk-starts W`` (0/full =
    None — the every-gene-starts reference semantics, byte-identical to
    runs without the flag).

    At million-node scale the reference's walk volume (every gene starts
    ``reps`` times, both groups) is ~2 G x reps packed rows — hundreds of
    GB before training sees a byte. Capping STARTS (not walk length)
    keeps every sampled path a faithful reference walk while making
    total volume a budget; evenly spaced over the sorted gene order so
    coverage stays uniform across the id space.
    """
    if walk_starts <= 0 or walk_starts >= n_genes:
        return None
    idx = (np.arange(walk_starts, dtype=np.int64) * n_genes) // walk_starts
    return np.unique(idx).astype(np.int32)
