"""Gene-range and walk-partition sharding for million-node graphs.

ROADMAP item 2: every subsystem before this module assumes the graph's
CSR, the walk volume, and the ``[G, H]`` embedding table fit one host.
This module owns the *partitioning arithmetic and host collectives* that
break that assumption; train/stream.py, ops/kmeans.py, analysis.py and
pipeline.py consume it. Two independent axes, two flags:

- ``--graph-shards N`` partitions the streaming walk-shard *sequence*
  into N contiguous partitions; partition ``p`` is SAMPLED only by rank
  ``p % n_ranks`` (on the PR 3 host pool) and its packed rows are
  exchanged to the other ranks over the chunked KV transport
  (parallel/hostcomm.exchange_bytes) — a remote rank is just another
  shard producer feeding the PR 7 ring. Every rank still *spools* every
  shard locally, so epoch replay and rewalk-on-corrupt stay local.
- ``--embed-shards R`` splits the ``[G, H]`` embedding table by a
  byte-aligned gene range per rank (R must equal the process count), so
  a rank densifies and trains only ``[G/R, H]`` — the per-rank memory
  cap that makes 100-1000x larger graphs fit. The softmax head ``w_ho``
  stays replicated by determinism (every rank sees identical reduced
  activations and applies the identical update). K-means, t-scores and
  the min-max rescale then run over the local slice, reducing only
  per-cluster statistics and masked extrema; full-width vectors are
  gathered rank-by-rank at the writer boundary alone.

Why byte-aligned ranges: walk rows travel and spool as np.packbits
rows (8 genes/byte, MSB first). A rank whose gene range starts on a
multiple of 8 can slice its columns *in packed form* —
``rows[:, lo // 8 : (hi + 7) // 8]`` — and unpack only its own slice on
device; the full-width multi-hot never materializes on any single rank.

CPU fleets cannot compile cross-process XLA ("Multiprocess computations
aren't implemented on the CPU backend"), so the "psum" of the sharded
trainer is realized as a deterministic host allreduce over the KV-store
allgather (rank-order summation — every rank reduces in the same order,
so replicated state stays bit-identical across ranks). On backends with
real cross-process XLA the same module works unchanged; swapping the
transport for jit-time psums is a pure optimization left signposted.

Parity contract (tests/test_shard.py): at ``n_ranks == 1`` the sharded
mode routes through EXACTLY the unsharded code paths (the local gene
range is the full range, the walk exchange is a passthrough) and is
byte-identical to a run without the flags. At ``n_ranks > 1`` the
reduction order of the hidden activations differs from the one-matmul
unsharded program, so the contract is the PR 7 statistical one (val-ACC
band + biomarker overlap), NOT bitwise.
"""
from __future__ import annotations

import dataclasses
import io
from typing import List, Optional, Tuple

import numpy as np


@dataclasses.dataclass(frozen=True)
class ShardSpec:
    """The pure partitioning arithmetic — unit-testable without jax.

    ``embed_shards > 0`` activates gene-range splitting (and must then
    equal ``n_ranks``); ``graph_shards > 0`` activates walk-partition
    ownership. Either axis alone is a valid mode: graph-only shards the
    sampling work while the model stays replicated; embed-only shards
    the model while every rank samples everything.
    """

    rank: int
    n_ranks: int
    n_genes: int
    graph_shards: int = 0
    embed_shards: int = 0

    def __post_init__(self):
        if not (0 <= self.rank < max(1, self.n_ranks)):
            raise ValueError(f"rank {self.rank} outside n_ranks {self.n_ranks}")
        if self.embed_shards and self.embed_shards != self.n_ranks:
            raise ValueError(
                f"embed_shards ({self.embed_shards}) must equal the rank "
                f"count ({self.n_ranks}): the gene range is split 1:1 "
                f"across ranks")
        if self.embed_shards > 1 and self.n_genes < 8 * self.embed_shards:
            raise ValueError(
                f"embed sharding needs >= 8 genes (one packed byte) per "
                f"rank; {self.n_genes} genes across {self.embed_shards} "
                f"ranks is too few")

    # ---- embedding (gene-range) axis ----------------------------------
    @property
    def n_bytes(self) -> int:
        """Packed row width: ceil(G / 8)."""
        return (self.n_genes + 7) // 8

    def byte_range(self, rank: Optional[int] = None) -> Tuple[int, int]:
        """Rank's contiguous slice of the packed byte columns."""
        r = self.rank if rank is None else rank
        if not self.embed_shards or self.n_ranks == 1:
            return (0, self.n_bytes)
        nb, R = self.n_bytes, self.n_ranks
        return (r * nb // R, (r + 1) * nb // R)

    def gene_range(self, rank: Optional[int] = None) -> Tuple[int, int]:
        """Rank's gene range [lo, hi) — lo is a multiple of 8 by
        construction; hi is clipped to G on the last rank."""
        blo, bhi = self.byte_range(rank)
        return (blo * 8, min(bhi * 8, self.n_genes))

    @property
    def lo(self) -> int:
        return self.gene_range()[0]

    @property
    def hi(self) -> int:
        return self.gene_range()[1]

    @property
    def g_local(self) -> int:
        return self.hi - self.lo

    @property
    def embed_split(self) -> bool:
        """True when the trainer/stats must take the split-program path.
        Single-rank "sharded" mode stays on the plain programs — the
        byte-identity contract."""
        return bool(self.embed_shards) and self.n_ranks > 1

    def slice_packed(self, rows: np.ndarray) -> np.ndarray:
        """Local packed byte columns of full-width rows [N, ceil(G/8)]."""
        blo, bhi = self.byte_range()
        return np.ascontiguousarray(rows[:, blo:bhi])

    # ---- walk-partition axis ------------------------------------------
    def shard_owner(self, si: int, n_shards: int) -> int:
        """The rank that samples streaming shard ``si`` of ``n_shards``.

        The shard sequence is cut into ``graph_shards`` contiguous
        partitions (a partition is a start-gene range — shard indices
        ARE start-major); partition ``p`` belongs to rank ``p % R``.
        With graph sharding off every rank owns everything itself.
        """
        if not self.graph_shards or self.n_ranks == 1:
            return self.rank
        if not (0 <= si < n_shards):
            raise ValueError(f"shard {si} outside [0, {n_shards})")
        p = si * self.graph_shards // n_shards
        return p % self.n_ranks


class ShardContext:
    """ShardSpec + the host collectives the sharded stages ride.

    All reductions here are MAIN-THREAD collectives in program order on
    every rank (the hostcomm sequence-number contract). The walk-shard
    exchange — which runs on the PRODUCER thread — must NOT come through
    here; it uses the explicit-key ``hostcomm.exchange_bytes`` transport
    directly (see the thread-safety note in parallel/hostcomm.py).
    """

    def __init__(self, spec: ShardSpec, *, deadline: Optional[float] = None):
        self.spec = spec
        self.deadline = deadline

    @property
    def single(self) -> bool:
        return self.spec.n_ranks == 1

    def allreduce(self, name: str, arr: np.ndarray, op: str = "sum"
                  ) -> np.ndarray:
        """Deterministic allreduce of a same-shape host array.

        Rank-order reduction: every rank applies the identical
        left-to-right fold over the allgathered stack, so replicated
        downstream state (the softmax head, k-means centers, early-stop
        decisions) stays bit-identical across ranks.
        """
        arr = np.asarray(arr)
        if self.single:
            return arr
        from g2vec_tpu.parallel import hostcomm

        stack = hostcomm.allgather_array(name, arr, deadline=self.deadline)
        fold = {"sum": np.add, "min": np.minimum, "max": np.maximum}[op]
        acc = stack[0]
        for p in range(1, stack.shape[0]):
            acc = fold(acc, stack[p])
        return acc

    def gather_concat(self, name: str, arr: np.ndarray, axis: int = 0
                      ) -> np.ndarray:
        """Concatenate per-rank arrays (unequal shapes along ``axis``
        allowed) in rank order, on every rank. The writer-boundary
        gather for scores/labels — small [G]-shaped vectors, never the
        [G, H] table (vectors stream rank-by-rank instead;
        pipeline._write_vectors_sharded)."""
        arr = np.ascontiguousarray(arr)
        if self.single:
            return arr
        from g2vec_tpu.parallel import hostcomm

        buf = io.BytesIO()
        np.save(buf, arr, allow_pickle=False)
        parts = hostcomm.allgather_bytes(name, buf.getvalue(),
                                         deadline=self.deadline)
        return np.concatenate(
            [np.load(io.BytesIO(p), allow_pickle=False) for p in parts],
            axis=axis)

    def broadcast_array(self, name: str, arr: Optional[np.ndarray]
                        ) -> np.ndarray:
        """Rank 0's array on every rank (k-means seeding, center state)."""
        if self.single:
            if arr is None:
                raise ValueError(f"broadcast {name!r}: rank-0 array is None")
            return np.asarray(arr)
        from g2vec_tpu.parallel import hostcomm

        payload = None
        if arr is not None:
            buf = io.BytesIO()
            np.save(buf, np.ascontiguousarray(arr), allow_pickle=False)
            payload = buf.getvalue()
        raw = hostcomm.broadcast_bytes(name, payload, deadline=self.deadline)
        return np.load(io.BytesIO(raw), allow_pickle=False)


def make_shard_context(graph_shards: int, embed_shards: int, n_genes: int,
                       *, deadline: Optional[float] = None
                       ) -> Optional[ShardContext]:
    """The pipeline's entry point: None when both axes are off, else a
    context bound to this process's rank. Validates the embed split
    against the ACTUAL process count (config.py can only check flags
    against flags)."""
    if not graph_shards and not embed_shards:
        return None
    import jax

    spec = ShardSpec(rank=jax.process_index(), n_ranks=jax.process_count(),
                     n_genes=n_genes, graph_shards=graph_shards,
                     embed_shards=embed_shards)
    return ShardContext(spec, deadline=deadline)


def subset_starts(n_genes: int, walk_starts: int) -> Optional[np.ndarray]:
    """Evenly spaced start-gene subset for ``--walk-starts W`` (0/full =
    None — the every-gene-starts reference semantics, byte-identical to
    runs without the flag).

    At million-node scale the reference's walk volume (every gene starts
    ``reps`` times, both groups) is ~2 G x reps packed rows — hundreds of
    GB before training sees a byte. Capping STARTS (not walk length)
    keeps every sampled path a faithful reference walk while making
    total volume a budget; evenly spaced over the sorted gene order so
    coverage stays uniform across the id space.
    """
    if walk_starts <= 0 or walk_starts >= n_genes:
        return None
    idx = (np.arange(walk_starts, dtype=np.int64) * n_genes) // walk_starts
    return np.unique(idx).astype(np.int32)
