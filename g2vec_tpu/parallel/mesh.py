"""Device mesh + sharding specs for the CBOW trainer and walker.

All sharding is expressed declaratively with ``NamedSharding`` /
``with_sharding_constraint``; XLA GSPMD inserts the actual collectives
(psum over ``model`` for the gene-axis contraction, gradient psum over
``data``) — no hand-written collective calls, riding ICI within a slice and
DCN across slices exactly as compiled (cf. the NCCL/MPI backends the survey
template asks about: JAX collectives ARE this framework's comm backend).

``make_mesh_context(None)`` gives a no-op context so every call site works
unchanged on a single chip.
"""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


@dataclasses.dataclass(frozen=True)
class MeshContext:
    """Holds the mesh and the canonical PartitionSpecs of this framework."""

    mesh: Optional[Mesh]

    # ---- specs ----
    @property
    def batch_spec(self) -> P:
        """Multi-hot path batch X [paths, genes]: DP over rows, TP over cols."""
        return P(DATA_AXIS, MODEL_AXIS)

    @property
    def label_spec(self) -> P:
        return P(DATA_AXIS, None)

    @property
    def w_ih_spec(self) -> P:
        """Embedding table [genes, hidden]: row-sharded over model axis."""
        return P(MODEL_AXIS, None)

    @property
    def w_ho_spec(self) -> P:
        return P(None, None)

    @property
    def hidden_spec(self) -> P:
        """Activations H [paths, hidden] after the psum over model."""
        return P(DATA_AXIS, None)

    @property
    def adj_spec(self) -> P:
        """Dense transition matrix [genes, genes]: row-sharded."""
        return P(MODEL_AXIS, None)

    @property
    def walker_spec(self) -> P:
        """Walker state [walkers, ...]: DP over walkers."""
        return P(DATA_AXIS, None)

    @property
    def packed_batch_spec(self) -> P:
        """Bit-packed path batch [paths, bytes]: rows over 'data', the byte
        axis never sharded (the Pallas kernel consumes whole rows)."""
        return P(DATA_AXIS, None)

    # ---- helpers ----
    def sharding(self, spec: P) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, spec)

    def put(self, x, spec: P):
        """Device-put with this context's sharding (no-op spec on 1 device)."""
        s = self.sharding(spec)
        return jax.device_put(x, s) if s is not None else jax.device_put(x)

    def constrain(self, x, spec: P):
        if self.mesh is None:
            return x
        return jax.lax.with_sharding_constraint(x, NamedSharding(self.mesh, spec))

    @property
    def n_devices(self) -> int:
        return 1 if self.mesh is None else self.mesh.size


def make_mesh_context(mesh_shape: Optional[Tuple[int, int]],
                      devices=None) -> MeshContext:
    """Build a ('data','model') mesh, or a no-op context if shape is None."""
    if mesh_shape is None:
        return MeshContext(mesh=None)
    devices = np.asarray(devices if devices is not None else jax.devices())
    need = mesh_shape[0] * mesh_shape[1]
    if devices.size < need:
        raise ValueError(
            f"mesh {mesh_shape} needs {need} devices, only {devices.size} visible "
            f"(hint: XLA_FLAGS=--xla_force_host_platform_device_count={need} "
            "JAX_PLATFORMS=cpu for a virtual mesh)")
    grid = devices[:need].reshape(mesh_shape)
    return MeshContext(mesh=Mesh(grid, (DATA_AXIS, MODEL_AXIS)))


def pad_to_multiple(n: int, k: int) -> int:
    """Smallest multiple of k that is >= n (shard-even padding helper)."""
    return ((n + k - 1) // k) * k


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
    """``jax.shard_map`` across the API move+rename.

    jax >= 0.6 exposes it top-level with the replication check spelled
    ``check_vma``; older releases (the pinned 0.4.x toolchain) only have
    ``jax.experimental.shard_map.shard_map`` with the same check spelled
    ``check_rep``. Every in-repo caller goes through this shim so the walker
    and trainer track the drift in exactly one place.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)
