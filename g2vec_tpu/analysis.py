"""L5 — L-group assignment and biomarker selection.

Host-level orchestration over the jitted kernels in :mod:`g2vec_tpu.ops`.

``find_lgroups`` reimplements ref G2Vec.py:167-200:
1. k-means (k=3) over the gene embeddings,
2. the LARGEST cluster is declared "other/init" (index 2) — geometrically it
   is the blob of genes that never appeared in a path and whose embedding rows
   barely moved from init,
3. the remaining two clusters are voted good vs poor by comparing, per
   cluster, how many member genes the path-frequency majority marked good
   (freq 0) vs poor (freq 1),
4. renumber to {0: good, 1: poor, 2: other}.

The reference's step-3 vote is neutered by a list-vs-int comparison bug
(``freqIdx == 0`` where freqIdx is a Python list, ref: G2Vec.py:186-187):
both counts are always 0 and the ``>`` tie-break always picks the *second*
remaining cluster as good. We implement the vote correctly by default and
reproduce the degenerate behavior under ``compat_tiebreak=True``
(SURVEY.md §7 quirk (a)).
"""
from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from g2vec_tpu.ops.stats import dscores, minmax, tscores


def find_lgroups(embeddings: np.ndarray, genes: Sequence[str],
                 gene_freq: Dict[str, int], *, key, k: int = 3,
                 compat_tiebreak: bool = False, n_init: int = 10,
                 iters: int = 50) -> np.ndarray:
    """Assign each gene an L-group in {0: good, 1: poor, 2: other}.

    ``gene_freq`` maps gene -> 0/1/2 as produced by path-frequency voting
    (ref: count_geneFreq, G2Vec.py:288-308); genes absent from it default to
    2 (ref: G2Vec.py:172).
    """
    from g2vec_tpu.ops.kmeans import kmeans

    if k < 3:
        raise ValueError(f"find_lgroups needs k >= 3 (good/poor/other), got {k}")
    km_idx, _, _ = kmeans(np.asarray(embeddings), k, key, n_init=n_init, iters=iters)
    km_idx = np.asarray(km_idx)
    freq_idx = np.array([gene_freq.get(g, 2) for g in genes], dtype=np.int32)

    # Largest cluster = "other/init"; ties -> lowest cluster index, matching
    # the reference's strict-> scan (G2Vec.py:174-180).
    counts = np.bincount(km_idx, minlength=k)
    largest = int(np.argmax(counts))
    remaining = [i for i in range(k) if i != largest]

    if compat_tiebreak:
        # Reference bug: the vote always reads 0-0, and the strict '>' sends
        # it down the else branch: good = second remaining, poor = first
        # (ref: G2Vec.py:189-194 with gpDiff identically zero).
        good_cluster, poor_cluster = remaining[1], remaining[0]
    else:
        # Vote: the remaining cluster whose members the path-frequency
        # majority marked good most strongly is "good", the one marked poor
        # most strongly is "poor"; with k > 3 any further clusters fall to
        # "other" below.
        gp_diff = {}
        for i in remaining:
            n_moregood = int(np.count_nonzero((km_idx == i) & (freq_idx == 0)))
            n_morepoor = int(np.count_nonzero((km_idx == i) & (freq_idx == 1)))
            gp_diff[i] = n_moregood - n_morepoor
        good_cluster = max(remaining, key=lambda i: (gp_diff[i], i))
        poor_cluster = min((i for i in remaining if i != good_cluster),
                           key=lambda i: (gp_diff[i], -i))

    result = np.full(len(km_idx), 2, dtype=np.int32)
    result[km_idx == good_cluster] = 0
    result[km_idx == poor_cluster] = 1
    return result


def select_biomarkers(embeddings: np.ndarray, expr: np.ndarray,
                      labels: np.ndarray, genes: np.ndarray,
                      lgroup_idx: np.ndarray, num_biomarker: int,
                      score_mix: float = 0.5) -> Tuple[List[str], Dict[str, np.ndarray]]:
    """Top-N genes per L-group by mixed d/t score (ref: G2Vec.py:83-109).

    For each L-group y in {good(0), poor(1)}:
    - d-score: L2 norm of that group's embedding rows, min-max rescaled
    - t-score: |two-sample t| of that group's expression columns, rescaled
    - gene score: mix*d + (1-mix)*t  (reference: 0.5*(d+t), G2Vec.py:102)
    - sort scores descending (stable, so ties keep gene order like Python's
      sorted), take top N symbols, sort those alphabetically
    Final list = good block + poor block, sorted alphabetically again
    (ref: G2Vec.py:104-109).

    Returns (biomarker list, per-group score dict for metrics/inspection).
    """
    expr_good = expr[labels == 0]
    expr_poor = expr[labels == 1]
    biomarkers: List[str] = []
    detail: Dict[str, np.ndarray] = {}
    for group in (0, 1):
        mask = lgroup_idx == group
        group_genes = genes[mask]
        if group_genes.size == 0:
            continue
        d = minmax(dscores(embeddings[mask]))
        t = minmax(tscores(expr_good[:, mask], expr_poor[:, mask]))
        scores = np.asarray(score_mix * d + (1.0 - score_mix) * t)
        order = np.argsort(-scores, kind="stable")      # ties keep gene order
        top = sorted(group_genes[order[:num_biomarker]].tolist())
        biomarkers += top
        detail["good" if group == 0 else "poor"] = scores
    return sorted(biomarkers), detail


def warm_lgroups_compile(n_genes: int, hidden: int, *, k: int = 3,
                         iters: int = 50, n_init: int = 10) -> bool:
    """Compile (and once-execute) the k-means program find_lgroups will
    run at [n_genes, hidden].

    The overlap scheduler (parallel/overlap.py) calls this in the
    background during stage 3: the walks are host-core work and the
    device sits idle, so the multi-second k-means compile — the one that
    wedged the r5 chip window — hides under the sampling instead of
    extending stage 5. A zeros input is used; the jit executable cache
    keys on shapes/statics, never values, so stage 5's real call is a
    pure cache hit. Keep the statics in lockstep with find_lgroups's
    kmeans call or the warm compiles a program nobody uses.
    """
    import jax

    from g2vec_tpu.ops.kmeans import kmeans

    x = np.zeros((n_genes, hidden), dtype=np.float32)
    labels_d, _, _ = kmeans(x, k, jax.random.key(0), n_init=n_init,
                            iters=iters)
    jax.block_until_ready(labels_d)
    return True
