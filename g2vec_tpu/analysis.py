"""L5 — L-group assignment and biomarker selection.

Host-level orchestration over the jitted kernels in :mod:`g2vec_tpu.ops`.

``find_lgroups`` reimplements ref G2Vec.py:167-200:
1. k-means (k=3) over the gene embeddings,
2. the LARGEST cluster is declared "other/init" (index 2) — geometrically it
   is the blob of genes that never appeared in a path and whose embedding rows
   barely moved from init,
3. the remaining two clusters are voted good vs poor by comparing, per
   cluster, how many member genes the path-frequency majority marked good
   (freq 0) vs poor (freq 1),
4. renumber to {0: good, 1: poor, 2: other}.

The reference's step-3 vote is neutered by a list-vs-int comparison bug
(``freqIdx == 0`` where freqIdx is a Python list, ref: G2Vec.py:186-187):
both counts are always 0 and the ``>`` tie-break always picks the *second*
remaining cluster as good. We implement the vote correctly by default and
reproduce the degenerate behavior under ``compat_tiebreak=True``
(SURVEY.md §7 quirk (a)).
"""
from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from g2vec_tpu.ops.stats import dscores, masked_minmax, minmax, tscores


def freq_index(genes: Sequence[str], gene_freq: Dict[str, int]) -> np.ndarray:
    """``gene_freq`` dict -> the dense [G] int32 vote vector (absent genes
    default to 2 / "other", ref: G2Vec.py:172). Shared by the solo and
    lane-batched stage-5 paths."""
    return np.array([gene_freq.get(g, 2) for g in genes], dtype=np.int32)


@partial(jax.jit, static_argnames=("k",))
def _vote_counts(km_idx: jax.Array, freq_idx: jax.Array, k: int):
    """Per-cluster [k] tallies: member count, good-majority members,
    poor-majority members — the ONLY values the L-group vote needs, so
    they are the only bytes that cross to the host (the [G] embeddings
    and assignments stay on device)."""
    onehot = jax.nn.one_hot(km_idx, k, dtype=jnp.int32)         # [G, k]
    counts = onehot.sum(axis=0)
    good = (onehot * (freq_idx == 0)[:, None]).sum(axis=0)
    poor = (onehot * (freq_idx == 1)[:, None]).sum(axis=0)
    return counts, good, poor


@partial(jax.jit, static_argnames=("k",))
def _vote_counts_lanes(km: jax.Array, freq_stack: jax.Array, k: int):
    """Per-lane vote tallies: [B, k] stacks of :func:`_vote_counts`."""
    return jax.vmap(lambda a, b: _vote_counts(a, b, k))(km, freq_stack)


def _pick_clusters(counts: np.ndarray, good_counts: np.ndarray,
                   poor_counts: np.ndarray, k: int,
                   compat_tiebreak: bool) -> Tuple[int, int]:
    """The good/poor cluster vote on host ints (exact arithmetic on three
    [k] vectors — the heavy [G] work stays on device).

    Largest cluster = "other/init"; ties -> lowest cluster index, matching
    the reference's strict-> scan (G2Vec.py:174-180).
    """
    largest = int(np.argmax(counts))
    remaining = [i for i in range(k) if i != largest]
    if compat_tiebreak:
        # Reference bug: the vote always reads 0-0, and the strict '>' sends
        # it down the else branch: good = second remaining, poor = first
        # (ref: G2Vec.py:189-194 with gpDiff identically zero).
        return remaining[1], remaining[0]
    # Vote: the remaining cluster whose members the path-frequency
    # majority marked good most strongly is "good", the one marked poor
    # most strongly is "poor"; with k > 3 any further clusters fall to
    # "other" below.
    gp_diff = {i: int(good_counts[i]) - int(poor_counts[i])
               for i in remaining}
    good_cluster = max(remaining, key=lambda i: (gp_diff[i], i))
    poor_cluster = min((i for i in remaining if i != good_cluster),
                       key=lambda i: (gp_diff[i], -i))
    return good_cluster, poor_cluster


@partial(jax.jit, static_argnames=("k", "n_init", "iters"))
def _kmeans_lanes(x: jax.Array, keys: jax.Array, k: int, n_init: int,
                  iters: int):
    """vmapped multi-restart k-means over a [B, G, H] lane stack.

    Wrapped in its OWN jit so the batched executable caches on
    shapes/statics like every other program here (a bare vmap-of-jit
    re-traces per call); the compile is shared by the engine's warm and
    the real stage-5 call.
    """
    from g2vec_tpu.ops.kmeans import kmeans

    return jax.vmap(
        lambda xx, kk: kmeans(xx, k, kk, n_init=n_init, iters=iters)
    )(x, keys)


@jax.jit
def _renumber(km_idx: jax.Array, good: jax.Array, poor: jax.Array) -> jax.Array:
    """Cluster labels -> {0: good, 1: poor, 2: other} (any extra k > 3
    clusters fall to 2). Broadcasts over leading lane axes."""
    return jnp.where(km_idx == good, 0,
                     jnp.where(km_idx == poor, 1, 2)).astype(jnp.int32)


def find_lgroups_device(embeddings, freq_idx: np.ndarray, *, key,
                        k: int = 3, compat_tiebreak: bool = False,
                        n_init: int = 10, iters: int = 50,
                        return_centers: bool = False):
    """:func:`find_lgroups` staying ON DEVICE end to end.

    ``embeddings`` may be a device array (the trainer's snapshot slice) or
    host numpy; the result is a device [G] int32 the caller materializes
    only at the writer boundary. The former host round trip (np.asarray
    before the jitted k-means, np.bincount/count_nonzero after) now moves
    three [k]-int vectors instead of three [G]-sized arrays.

    ``return_centers`` additionally returns the winning restart's [k, d]
    centers — the ANN coarse quantizer's seed (ops/ann.build_ivf), free
    here because k-means already computed them.
    """
    from g2vec_tpu.ops.kmeans import kmeans

    if k < 3:
        raise ValueError(f"find_lgroups needs k >= 3 (good/poor/other), got {k}")
    km_idx, centers, _ = kmeans(embeddings, k, key, n_init=n_init,
                                iters=iters)
    counts, good, poor = _vote_counts(km_idx, jnp.asarray(freq_idx), k)
    good_cluster, poor_cluster = _pick_clusters(
        np.asarray(counts), np.asarray(good), np.asarray(poor), k,
        compat_tiebreak)
    out = _renumber(km_idx, good_cluster, poor_cluster)
    return (out, centers) if return_centers else out


def find_lgroups_lanes(emb_stack, freq_stack: np.ndarray,
                       kmeans_seeds: Sequence[int], *, k: int = 3,
                       compat_tiebreak: bool = False, n_init: int = 10,
                       iters: int = 50, return_centers: bool = False):
    """Lane-batched stage 5: one vmapped k-means program over the [B, G, H]
    embedding stack (every lane shares the gene axis, so the batched shape
    is manifest-invariant), per-lane k-means keys, the host vote per lane
    on the tiny [B, k] tallies, and a device [B, G] result.

    Per-lane bitwise parity with :func:`find_lgroups_device` is the lane
    contract (batched matmul/argmin/scan reproduce the per-example
    programs on this backend; tests/test_batch_engine.py pins it through
    the output files).
    """
    if k < 3:
        raise ValueError(f"find_lgroups needs k >= 3 (good/poor/other), got {k}")
    keys = jax.vmap(jax.random.key)(
        jnp.asarray(list(kmeans_seeds), dtype=jnp.uint32))
    # [B, G] labels, [B, k, d] per-lane winning centers
    km, centers, _ = _kmeans_lanes(emb_stack, keys, k, n_init, iters)
    counts, good, poor = _vote_counts_lanes(km, jnp.asarray(freq_stack), k)
    counts, good, poor = (np.asarray(counts), np.asarray(good),
                          np.asarray(poor))
    picks = np.array([_pick_clusters(counts[b], good[b], poor[b], k,
                                     compat_tiebreak)
                      for b in range(km.shape[0])], dtype=np.int32)
    out = _renumber(km, jnp.asarray(picks[:, 0:1]),
                    jnp.asarray(picks[:, 1:2]))
    return (out, centers) if return_centers else out


def find_lgroups(embeddings: np.ndarray, genes: Sequence[str],
                 gene_freq: Dict[str, int], *, key, k: int = 3,
                 compat_tiebreak: bool = False, n_init: int = 10,
                 iters: int = 50) -> np.ndarray:
    """Assign each gene an L-group in {0: good, 1: poor, 2: other}.

    ``gene_freq`` maps gene -> 0/1/2 as produced by path-frequency voting
    (ref: count_geneFreq, G2Vec.py:288-308); genes absent from it default to
    2 (ref: G2Vec.py:172). Host-convenience wrapper over
    :func:`find_lgroups_device` (same bytes, one materialization).
    """
    return np.asarray(find_lgroups_device(
        embeddings, freq_index(genes, gene_freq), key=key, k=k,
        compat_tiebreak=compat_tiebreak, n_init=n_init, iters=iters))


def biomarker_scores_device(embeddings, expr_good, expr_poor, lgroup_idx,
                            score_mix: float = 0.5) -> jax.Array:
    """Mixed d/t gene scores for both L-groups, device-resident: a [2, G]
    stack over the FULL gene axis (masked-minmax views instead of host
    boolean gathers — ops/stats.py has the bitwise argument). Row 0 is the
    good group's scores, row 1 the poor group's; positions outside a row's
    L-group are rescaled garbage the host-side top-N never reads.

    Every op is the solo path's own jitted kernel called op-by-op (no
    enclosing mega-jit): per-program fma contraction is what broke
    bitwise parity in the trainer's fused fold, so stage 6 keeps each
    arithmetic step in the exact program it always ran in.
    """
    d_full = dscores(embeddings)
    t_full = tscores(expr_good, expr_poor)
    rows = []
    for group in (0, 1):
        mask = lgroup_idx == group
        d = masked_minmax(d_full, mask)
        t = masked_minmax(t_full, mask)
        rows.append(score_mix * d + (1.0 - score_mix) * t)
    return jnp.stack(rows)


def biomarker_scores_lanes(emb_stack, expr_good, expr_poor, lgroup_stack,
                           score_mix: float = 0.5) -> jax.Array:
    """Lane-batched :func:`biomarker_scores_device`: [B, 2, G] scores for
    lanes SHARING one expression identity (the engine groups lanes by
    subsample identity first — the t-score input must match the lane's
    solo run). The t-scores are lane-invariant and computed ONCE through
    the exact solo program; the per-lane d-score/minmax ops run batched
    (bitwise per lane on this backend, pinned end to end by the engine
    parity tests)."""
    t_full = tscores(expr_good, expr_poor)          # [G], shared by lanes

    def one(emb, lg):
        d_full = dscores(emb)
        rows = []
        for group in (0, 1):
            mask = lg == group
            rows.append(score_mix * masked_minmax(d_full, mask)
                        + (1.0 - score_mix) * masked_minmax(t_full, mask))
        return jnp.stack(rows)

    return jax.vmap(one)(emb_stack, lgroup_stack)


def top_biomarkers(scores2: np.ndarray, lgroup_idx: np.ndarray,
                   genes: np.ndarray, num_biomarker: int
                   ) -> Tuple[List[str], Dict[str, np.ndarray]]:
    """The host half of biomarker selection: top-N symbols per L-group from
    the [2, G] score stack (ref sort semantics: G2Vec.py:104-109). ONE
    definition shared by :func:`select_biomarkers` and the batch engine's
    writer boundary, so a lane's list is selected by the byte-exact solo
    logic."""
    biomarkers: List[str] = []
    detail: Dict[str, np.ndarray] = {}
    for group in (0, 1):
        mask = lgroup_idx == group
        group_genes = genes[mask]
        if group_genes.size == 0:
            continue
        scores = scores2[group][mask]
        order = np.argsort(-scores, kind="stable")      # ties keep gene order
        top = sorted(group_genes[order[:num_biomarker]].tolist())
        biomarkers += top
        detail["good" if group == 0 else "poor"] = scores
    return sorted(biomarkers), detail


def select_biomarkers(embeddings: np.ndarray, expr: np.ndarray,
                      labels: np.ndarray, genes: np.ndarray,
                      lgroup_idx: np.ndarray, num_biomarker: int,
                      score_mix: float = 0.5) -> Tuple[List[str], Dict[str, np.ndarray]]:
    """Top-N genes per L-group by mixed d/t score (ref: G2Vec.py:83-109).

    For each L-group y in {good(0), poor(1)}:
    - d-score: L2 norm of that group's embedding rows, min-max rescaled
    - t-score: |two-sample t| of that group's expression columns, rescaled
    - gene score: mix*d + (1-mix)*t  (reference: 0.5*(d+t), G2Vec.py:102)
    - sort scores descending (stable, so ties keep gene order like Python's
      sorted), take top N symbols, sort those alphabetically
    Final list = good block + poor block, sorted alphabetically again
    (ref: G2Vec.py:104-109).

    ``embeddings`` and ``lgroup_idx`` may be device arrays (the pipeline
    feeds the trainer snapshot and stage 5's device result straight
    through); the scores and the L-group vector are materialized exactly
    once, here at the selection boundary.

    Returns (biomarker list, per-group score dict for metrics/inspection).
    """
    labels = np.asarray(labels)
    scores2 = np.asarray(biomarker_scores_device(
        embeddings, expr[labels == 0], expr[labels == 1], lgroup_idx,
        score_mix))
    return top_biomarkers(scores2, np.asarray(lgroup_idx), genes,
                          num_biomarker)


def find_lgroups_sharded(emb_local, freq_idx_local: np.ndarray, sctx, *,
                         key, k: int = 3, compat_tiebreak: bool = False,
                         n_init: int = 10, iters: int = 50) -> jax.Array:
    """:func:`find_lgroups_device` over a gene-range-sharded embedding
    (ROADMAP item 2): ``emb_local`` is this rank's ``[g_local, H]`` slice,
    ``freq_idx_local`` the matching slice of the [G] vote vector, and
    ``sctx`` a parallel/shard.ShardContext. Returns the LOCAL [g_local]
    L-group assignment (the writer boundary concatenates rank slices).

    Per Lloyd iteration only per-cluster sufficient statistics cross
    ranks (ops/kmeans.kmeans_sharded); the good/poor vote reduces one
    [3, k] tally stack, then runs the identical host arithmetic — the
    vote, tie-breaks and the compat quirk included, so the decision is
    replicated bit-for-bit on every rank. Single-rank callers must use
    :func:`find_lgroups_device` instead (pipeline.py routes them there —
    the byte-identity contract)."""
    from g2vec_tpu.ops.kmeans import kmeans_sharded

    if k < 3:
        raise ValueError(f"find_lgroups needs k >= 3 (good/poor/other), got {k}")
    km_idx, _, _ = kmeans_sharded(
        emb_local, k, key, allreduce=sctx.allreduce,
        gather=sctx.gather_concat, n_init=n_init, iters=iters)
    counts, good, poor = _vote_counts(km_idx, jnp.asarray(freq_idx_local), k)
    tallies = sctx.allreduce("lg_vote", np.stack(
        [np.asarray(counts), np.asarray(good), np.asarray(poor)]))
    good_cluster, poor_cluster = _pick_clusters(
        tallies[0], tallies[1], tallies[2], k, compat_tiebreak)
    return _renumber(km_idx, good_cluster, poor_cluster)


def biomarker_scores_sharded(emb_local, expr_good_local, expr_poor_local,
                             lgroup_local, sctx,
                             score_mix: float = 0.5) -> jax.Array:
    """:func:`biomarker_scores_device` over the rank's gene-range slice:
    a LOCAL [2, g_local] score stack. d-scores are row-local and t-scores
    column-local, so both are exact on slices; the only global quantities
    are each group's masked extrema — reduced as two scalars per score
    kind (min/max are order-independent, so the reduced values are
    BITWISE the unsharded reduction's) and fed to the rescale half of
    masked_minmax (ops/stats.masked_rescale mirrors it term for term).
    Masked positions of the concatenated rank slices therefore carry
    exactly the [2, G] values the unsharded call produces — sharded
    stage 6 is numerically exact, unlike the statistically-contracted
    trainer. ``expr_*_local`` are the expression matrices' local gene
    COLUMNS ([samples, g_local])."""
    from g2vec_tpu.ops.stats import masked_extrema, masked_rescale

    d_local = dscores(emb_local)
    t_local = tscores(expr_good_local, expr_poor_local)
    rows = []
    for group in (0, 1):
        mask = lgroup_local == group
        parts = []
        for name, s in (("d", d_local), ("t", t_local)):
            lo, hi = masked_extrema(s, mask)
            ext = np.array([float(lo), -float(hi)])
            ext = sctx.allreduce(f"bm_ext/{group}/{name}", ext, op="min")
            parts.append(masked_rescale(s, jnp.float32(ext[0]),
                                        jnp.float32(-ext[1])))
        rows.append(score_mix * parts[0] + (1.0 - score_mix) * parts[1])
    return jnp.stack(rows)


def warm_lgroups_compile(n_genes: int, hidden: int, *, k: int = 3,
                         iters: int = 50, n_init: int = 10,
                         lanes: int = 0) -> bool:
    """Compile (and once-execute) the k-means program find_lgroups will
    run at [n_genes, hidden].

    The overlap scheduler (parallel/overlap.py) calls this in the
    background during stage 3: the walks are host-core work and the
    device sits idle, so the multi-second k-means compile — the one that
    wedged the r5 chip window — hides under the sampling instead of
    extending stage 5. A zeros input is used; the jit executable cache
    keys on shapes/statics, never values, so stage 5's real call is a
    pure cache hit. Keep the statics in lockstep with find_lgroups's
    kmeans call or the warm compiles a program nobody uses.

    ``lanes=B`` warms the batch engine's vmapped program instead — the
    [B, n_genes, hidden] stack find_lgroups_lanes will run (the batched
    stage-5 shape is manifest-invariant, so this warm is submitted the
    moment the lane count is known, before any walk finishes).
    """
    from g2vec_tpu.ops.kmeans import kmeans

    if lanes:
        x = np.zeros((lanes, n_genes, hidden), dtype=np.float32)
        keys = jax.vmap(jax.random.key)(
            jnp.zeros(lanes, dtype=jnp.uint32))
        labels_d, _, _ = _kmeans_lanes(x, keys, k, n_init, iters)
    else:
        x = np.zeros((n_genes, hidden), dtype=np.float32)
        labels_d, _, _ = kmeans(x, k, jax.random.key(0), n_init=n_init,
                                iters=iters)
    jax.block_until_ready(labels_d)
    return True
