"""Config/env-driven fault injection at the pipeline's failure seams.

Production embedding systems treat restartability as a first-class design
axis; a recovery path that is never exercised is a recovery path that does
not work. This module makes every fault mode the supervisor and the
checkpoint-integrity layer claim to survive *injectable on demand*, so the
fault-matrix tests (tests/test_resilience.py) can drive them continuously.

A fault plan is one or more ``;``-separated entries of ``,``-separated
``key=value`` pairs::

    G2VEC_FAULT_PLAN="stage=train,epoch=40,kind=crash"
    --fault-plan "stage=paths,kind=sigkill;stage=checkpoint_finalize,kind=corrupt"

Keys:

- ``stage`` (required) — the seam name. Pipeline stage boundaries: ``load``,
  ``preprocess``, ``paths``, ``train``, ``lgroups``, ``biomarkers``,
  ``save``. Trainer epoch loop: ``train`` with ``epoch=N``. Checkpointing:
  ``checkpoint_write`` (before the write), ``checkpoint_finalize`` (after
  the atomic rename — the seam for ``corrupt``). Native libraries:
  ``native_load`` (TSV parser), ``native_walker_load`` (walk sampler).
  Walk-artifact cache: ``walk_cache`` (after a store finalizes — the
  ``corrupt`` drill for g2vec_tpu/cache.py's sha256 verification).
- ``epoch`` — only fire once the hook reports an epoch >= this value
  (meaningful at the ``train`` seam).
- ``kind`` — what to do when the seam is hit:
  ``crash`` (default) raises :class:`InjectedFault` (classified retryable);
  ``fatal`` raises :class:`InjectedFatal` (classified fatal);
  ``sigkill`` SIGKILLs the current process — no Python cleanup runs, the
  exact shape of a TPU preemption;
  ``stall`` sleeps ``seconds`` (default 300) then raises
  :class:`InjectedFault`, modelling a wedged collective that a watchdog
  eventually shoots;
  ``corrupt`` flips bytes in the middle of the file the seam passes as
  ``path`` (checkpoint seams) and then RETURNS — a torn write that the
  writer believes succeeded, detectable only by manifest verification.
- ``process`` — only fire on this rank of a multi-process (fleet) run, e.g.
  ``process=1,stage=allgather,kind=stall`` stalls rank 1 at the collective
  entry while its peers proceed into the watchdog. Rank identity comes from
  ``G2VEC_PROCESS_ID`` (exported by every fleet launcher) or
  ``jax.process_index()``; entries without ``process=`` fire on every rank.
- ``times`` — fire at most this many times (default 1).
- ``skip`` — let the first N matching hits pass before firing (default 0;
  e.g. ``stage=checkpoint_finalize,kind=corrupt,skip=1`` corrupts the
  SECOND checkpoint save, leaving a good ``.prev`` generation behind).
- ``seconds`` — stall duration for ``kind=stall``.

Fired entries are recorded in ``G2VEC_FAULT_STATE`` (a JSON file) when that
env var is set, so a one-shot fault stays one-shot ACROSS process restarts —
without it a supervisor-restarted run would re-hit the same SIGKILL forever.
The supervisor sets this automatically when it sees a plan and no state path.

Zero-cost when inactive: with no plan installed and no ``G2VEC_FAULT_PLAN``
in the environment, :func:`fault_point` is one falsy check and a return.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import sys
import time
from typing import List, Optional

ENV_PLAN = "G2VEC_FAULT_PLAN"
ENV_STATE = "G2VEC_FAULT_STATE"

KINDS = ("crash", "fatal", "sigkill", "stall", "corrupt")

#: The seams the pipeline exposes. fault_point() accepts only these so a
#: typo'd plan fails at install time, not by silently never firing. The last
#: three are the distributed seams (resilience/fleet.py): ``allgather`` fires
#: at the entry of every host-side collective gather, ``stage_barrier`` at
#: the per-stage fleet barrier, ``heartbeat`` inside the liveness thread's
#: beat loop (a ``crash`` there silently stops the beats — the shape of a
#: host whose monitoring died before the host did).
SEAMS = ("load", "preprocess", "paths", "train", "lgroups", "biomarkers",
         "save", "checkpoint_write", "checkpoint_finalize",
         "native_load", "native_walker_load",
         # Bit-exact device sampler (ops/device_walker.py): fires inside
         # walk_shard_device between state init and the device scan
         # (epoch = shard index). Recovery is a clean recompute — the
         # sampler is a pure function of (plan, shard, seed) — and the
         # drill pins that recomputed rows are byte-identical.
         "device_walk",
         "allgather", "stage_barrier", "heartbeat",
         # Walk-artifact cache (g2vec_tpu/cache.py): fires right after a
         # store finalizes, so kind=corrupt models post-save bitrot that
         # only the manifest verification can catch.
         "walk_cache",
         # Streaming trainer (train/stream.py): ``shard_ring`` fires in
         # the producer right after a shard spools and before it enters
         # the ring (epoch = shard index; kind=corrupt gets the spool
         # file, modelling a torn shard the replay verification must
         # catch and re-walk); ``prefetch`` fires in the consumer as it
         # requests the next shard (a wedged/dying prefetch stage). The
         # ring's failure contract — producer faults surface at the
         # consumer's next get, consumer death cancels the ring so a
         # blocked producer unblocks — makes every kind here terminate
         # instead of deadlocking the edge.
         "shard_ring", "prefetch",
         # Durable-job seams (train/stream.py + serve/daemon.py):
         # ``stream_ckpt`` fires right after a streaming cursor checkpoint
         # finalizes (epoch = training epoch; a sigkill here is the
         # worst-case mid-epoch death the resume drill pins);
         # ``drain`` fires as the daemon begins a graceful drain, before
         # it checkpoints in-flight jobs (a crash there models a drain
         # that never completed — the journal must still re-queue).
         "stream_ckpt", "drain",
         # Sharded scale-out seams (train/stream.py with a ShardContext):
         # ``shard_exchange`` fires on the OWNING rank right before it
         # publishes a walk shard to its peers (epoch = shard index) — a
         # sigkill there leaves the shard's KV keys absent forever, so the
         # peers' chunked get times out with a PeerTimeoutError naming the
         # dead rank (the fleet-watchdog drill). ``embed_allreduce`` fires
         # in the trainer right before a rank contributes its partial
         # hidden activations to the per-step allreduce (epoch = global
         # step) — the same named-rank attribution for a death inside the
         # model-parallel reduction.
         "shard_exchange", "embed_allreduce",
         # Edge-partitioned CSR seams (parallel/shard.py with an
         # EdgeContext): ``walk_handoff`` fires in the collective walk
         # engine's round loop right before a rank publishes its
         # suspended-walk batches (epoch = shard index) — a sigkill
         # there takes walk state no other rank can reconstruct with it,
         # and the survivors' receive deadline names the dead rank.
         # ``halo_build`` fires between the halo want-list round and the
         # row-ship round (epoch = group index) — a dead row SERVER at
         # setup time, named by its requesters' deadline expiry.
         "walk_handoff", "halo_build",
         # ANN index publication (io/writers.py): fires after the bundle
         # manifest is sealed and before the atomic rename, with the
         # staged ann_postings.npy as the path — so kind=corrupt models
         # a published bundle whose IVF index bytes mismatch their
         # manifest hash. The query plane's contract (tests/test_ann.py
         # corrupt drill): the index is refused at map time with a
         # structured warning and queries fall back to the exact path —
         # a corrupted index can never change answers.
         "ann_build",
         # Generation-atomic republish (io/writers.py): fires between
         # the staged generation directory's rename into the bundle and
         # the GENERATION pointer flip — the exact window a SIGKILL
         # leaves an orphan gen-* directory on disk with the pointer
         # (and therefore every reader) still on the old generation.
         # The update drill (tests/test_update.py) pins the contract:
         # queries keep answering from the old generation, the orphan
         # is swept by the next successful publish, and the journaled
         # update re-runs to completion after relaunch.
         "update_publish")


class FaultPlanError(ValueError):
    """A malformed --fault-plan / G2VEC_FAULT_PLAN spec."""


class InjectedFault(RuntimeError):
    """An injected RETRYABLE failure (preemption-shaped). Subclasses
    RuntimeError so every layer that degrades on RuntimeError (native
    bindings fall back to Python, the supervisor retries) treats it like
    the real faults it stands in for."""


class InjectedFatal(ValueError):
    """An injected FATAL failure (bad-input-shaped). Subclasses ValueError
    — the type the readers/config raise — so classification tests exercise
    the supervisor's real fatal path."""


@dataclasses.dataclass
class _Entry:
    stage: str
    kind: str = "crash"
    epoch: Optional[int] = None
    times: int = 1
    skip: int = 0
    seconds: float = 300.0
    process: Optional[int] = None   # only fire on this rank (None = any)
    seen: int = 0       # matching hits so far (this process; drives skip)

    @property
    def key(self) -> str:
        return f"{self.stage}:{self.epoch}:{self.kind}"


# None = environment not consulted yet; [] = consulted, no plan (the
# zero-cost steady state for un-faulted runs).
_plan: Optional[List[_Entry]] = None
_state_path: Optional[str] = None
_fired: dict = {}          # entry.key -> count, this process
_INJECTED_NOTE = "injected by the G2VEC fault plan"


def parse_plan(spec: str) -> List[_Entry]:
    """Parse a plan spec; raises FaultPlanError with the offending token."""
    entries = []
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        fields = {}
        for tok in part.split(","):
            if "=" not in tok:
                raise FaultPlanError(
                    f"fault plan token {tok!r} is not key=value (in {part!r})")
            k, v = tok.split("=", 1)
            fields[k.strip()] = v.strip()
        unknown = set(fields) - {"stage", "kind", "epoch", "times", "skip",
                                 "seconds", "process"}
        if unknown:
            raise FaultPlanError(
                f"unknown fault plan keys {sorted(unknown)} in {part!r} "
                "(want stage/kind/epoch/times/skip/seconds/process)")
        if "stage" not in fields:
            raise FaultPlanError(f"fault plan entry {part!r} needs stage=")
        if fields["stage"] not in SEAMS:
            raise FaultPlanError(
                f"unknown fault seam {fields['stage']!r}; seams: "
                f"{', '.join(SEAMS)}")
        kind = fields.get("kind", "crash")
        if kind not in KINDS:
            raise FaultPlanError(
                f"unknown fault kind {kind!r}; kinds: {', '.join(KINDS)}")
        try:
            entries.append(_Entry(
                stage=fields["stage"], kind=kind,
                epoch=int(fields["epoch"]) if "epoch" in fields else None,
                times=int(fields.get("times", 1)),
                skip=int(fields.get("skip", 0)),
                seconds=float(fields.get("seconds", 300.0)),
                process=(int(fields["process"]) if "process" in fields
                         else None)))
        except ValueError as e:
            raise FaultPlanError(
                f"non-numeric epoch/times/skip/seconds/process in {part!r}: "
                f"{e}") from e
    return entries


def install_plan(spec: Optional[str], state_path: Optional[str] = None) -> None:
    """Install (or with ``None``/empty spec, clear) the process fault plan.

    Re-installing the same plan does NOT reset which entries already fired
    in this process — an in-process supervisor retry must not re-trip a
    once-only fault.
    """
    global _plan, _state_path
    _plan = parse_plan(spec) if spec else []
    if state_path is not None:
        _state_path = state_path


def _load_state() -> dict:
    path = _state_path or os.environ.get(ENV_STATE)
    if not path or not os.path.exists(path):
        return {}
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return {}


def _record_fired(entry: _Entry) -> None:
    _fired[entry.key] = _fired.get(entry.key, 0) + 1
    path = _state_path or os.environ.get(ENV_STATE)
    if not path:
        return
    state = _load_state()
    state[entry.key] = state.get(entry.key, 0) + 1
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
    os.replace(tmp, path)


def _corrupt_file(path: str) -> None:
    """Flip a byte run in the middle of ``path`` — a torn write the writer
    never notices. The file length is preserved (a truncation would be
    caught by far cruder checks than the manifest hashes)."""
    size = os.path.getsize(path)
    with open(path, "r+b") as f:
        if size < 128:
            f.write(b"\xff" * max(size, 1))
            return
        f.seek(size // 2)
        chunk = f.read(64)
        f.seek(size // 2)
        f.write(bytes(b ^ 0xFF for b in chunk))


def _fire(entry: _Entry, seam: str, epoch: Optional[int],
          path: Optional[str]) -> None:
    where = f"seam={seam}" + (f" epoch={epoch}" if epoch is not None else "")
    # State is recorded BEFORE the action: a sigkill leaves no later chance,
    # and a crash must not re-fire on the supervised retry.
    _record_fired(entry)
    if entry.kind == "crash":
        raise InjectedFault(f"injected crash at {where} ({_INJECTED_NOTE})")
    if entry.kind == "fatal":
        raise InjectedFatal(f"injected fatal error at {where} "
                            f"({_INJECTED_NOTE})")
    if entry.kind == "sigkill":
        os.kill(os.getpid(), signal.SIGKILL)
        time.sleep(30)     # unreachable; belt for exotic signal handling
        raise InjectedFault(f"sigkill at {where} did not terminate")
    if entry.kind == "stall":
        time.sleep(entry.seconds)
        raise InjectedFault(
            f"injected stall at {where} expired after {entry.seconds}s "
            f"({_INJECTED_NOTE})")
    if entry.kind == "corrupt":
        if not path or not os.path.exists(path):
            raise InjectedFault(
                f"kind=corrupt at {where} needs a seam that passes a file "
                f"path (checkpoint_write/checkpoint_finalize); got "
                f"path={path!r}")
        _corrupt_file(path)    # silent: the torn write "succeeds"


def current_rank() -> int:
    """The process's rank for ``process=K`` fault scoping.

    Fleet launches (resilience/fleet.py and real multi-host drivers) export
    ``G2VEC_PROCESS_ID``, so the common case needs no jax. Fall back to
    ``jax.process_index()`` only when jax is already imported — this hook
    must never be the thing that drags the backend up.
    """
    pid = os.environ.get("G2VEC_PROCESS_ID")
    if pid is not None:
        try:
            return int(pid)
        except ValueError:
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:  # noqa: BLE001 — backend not up yet
            return 0
    return 0


def fault_point(seam: str, *, epoch: Optional[int] = None,
                path: Optional[str] = None) -> None:
    """Hook called at every named seam. No-op unless a plan entry matches.

    ``epoch`` qualifies the ``train`` seam and the checkpoint seams (the
    save's epoch); ``path`` hands ``corrupt`` faults their target file
    (checkpoint seams). ``process=K`` entries fire only on rank K.
    """
    global _plan
    if _plan is None:
        _plan = parse_plan(os.environ.get(ENV_PLAN, ""))
    if not _plan:
        return
    persisted = _load_state()
    for entry in _plan:
        if entry.stage != seam:
            continue
        if entry.process is not None and entry.process != current_rank():
            continue
        if entry.epoch is not None and (epoch is None or epoch < entry.epoch):
            continue
        fired = max(_fired.get(entry.key, 0), persisted.get(entry.key, 0))
        if fired >= entry.times:
            continue
        entry.seen += 1
        if entry.seen <= entry.skip:
            continue
        _fire(entry, seam, epoch, path)


def _reset_for_tests() -> None:
    """Forget the installed plan, fired counts, and state path."""
    global _plan, _state_path
    _plan = None
    _state_path = None
    _fired.clear()
