"""Cooperative job interruption — the exception vocabulary of the
durable-job layer (serve/daemon.py + batch/engine.py + both trainers).

The daemon threads a zero-argument ``check`` callable through
``ResidentEngine.execute`` into the trainers' epoch/shard loops. The
trainers call it at every consistent boundary (full-batch: top of each
chunk; streaming: every shard step, where the cursor checkpoint is also
cut). When the daemon wants a job stopped — client cancel, deadline
passed, SIGTERM drain — ``check`` raises one of these, the trainer
unwinds (the streaming trainer checkpoints its cursor first on a drain),
and the daemon maps the exception back to a per-job terminal or
re-queued state. Cooperative beats preemptive here: the boundaries are
exactly where device state is host-consistent, so an interrupted job is
always either resumable or cleanly terminal, never torn.
"""
from __future__ import annotations


class JobInterrupted(RuntimeError):
    """Base of all cooperative interruptions. ``job_id`` is the serve job
    the interruption targets (None for whole-process reasons like drain —
    every job in the batch is affected)."""

    reason = "interrupted"

    def __init__(self, job_id=None, detail: str = ""):
        self.job_id = job_id
        msg = f"job {job_id}: {self.reason}" if job_id else self.reason
        super().__init__(f"{msg} ({detail})" if detail else msg)


class JobCancelled(JobInterrupted):
    """A client asked for this specific job to stop. Terminal."""

    reason = "cancelled"


class JobDeadlineExceeded(JobInterrupted):
    """The job's ``deadline_s`` elapsed before it finished. Terminal."""

    reason = "deadline_exceeded"


class DrainRequested(JobInterrupted):
    """The daemon is draining (SIGTERM). NOT terminal for the job: the
    streaming trainer checkpoints its cursor before re-raising, the
    daemon leaves the job journaled, and the next daemon run resumes
    it from the checkpoint."""

    reason = "drain"


# ---------------------------------------------------------------------------
# Replica health — the router's per-replica probe state machine.
# ---------------------------------------------------------------------------

#: healthy → suspect → dead → rejoining → healthy. ``suspect`` keeps the
#: replica in the hash ring (a single missed probe is usually GC or a
#: long compile, not death); ``dead`` removes it and triggers failover;
#: ``rejoining`` answers probes again but takes no traffic until its
#: stale journal is drained.
REPLICA_STATES = ("healthy", "suspect", "dead", "rejoining")


class ReplicaHealth:
    """Pure probe-driven health state for one replica. No I/O, no clock
    reads — the router feeds it ``on_probe(ok, journal_depth, now)`` and
    acts on the returned transition, which keeps every edge unit-testable
    as a table.

    Thresholds: ``suspect_after`` consecutive failures demote healthy →
    suspect, ``dead_after`` total consecutive failures declare dead (the
    failover trigger — the router fences the process before re-queueing,
    so a slow-but-alive replica can never double-execute), and a dead
    replica that answers again must produce ``rejoin_after`` consecutive
    OK probes **with an empty journal** before it is healthy: the empty-
    journal gate is what forces a rejoining replica to drain stale work
    (or have the router migrate it) before taking new traffic.

    ``probe_interval(base)`` backs off exponentially for non-healthy
    replicas so a dead host costs probes, not a probe *storm*.
    """

    def __init__(self, name: str, suspect_after: int = 1,
                 dead_after: int = 3, rejoin_after: int = 2):
        if not (1 <= suspect_after < dead_after):
            raise ValueError("need 1 <= suspect_after < dead_after")
        self.name = name
        self.state = "healthy"
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self.rejoin_after = rejoin_after
        self.fails = 0          # consecutive failed probes
        self.oks = 0            # consecutive OK probes (rejoin gate)
        self.last_ok: float = 0.0
        self.last_transition: float = 0.0
        self.journal_depth: int = 0

    @property
    def in_ring(self) -> bool:
        """Whether the hash ring may route new jobs here."""
        return self.state in ("healthy", "suspect")

    def probe_interval(self, base: float) -> float:
        """Seconds until the next probe: ``base`` while healthy, doubled
        per consecutive failure (capped at 8x) otherwise — plus nothing;
        jitter is the caller's business."""
        if self.state == "healthy":
            return base
        return base * min(8.0, 2.0 ** max(0, self.fails - 1))

    def on_probe(self, ok: bool, journal_depth: int = 0,
                 now: float = 0.0):
        """Feed one probe outcome. Returns ``(old_state, new_state)`` on
        a transition, else None."""
        old = self.state
        if ok:
            self.fails = 0
            self.oks += 1
            self.last_ok = now
            self.journal_depth = journal_depth
            if old in ("healthy", "suspect"):
                self.state = "healthy"
            elif old == "dead":
                self.state = "rejoining"
                self.oks = 1        # this probe is the first of the gate
            elif old == "rejoining":
                if self.oks >= self.rejoin_after and journal_depth == 0:
                    self.state = "healthy"
        else:
            self.fails += 1
            self.oks = 0
            if old == "rejoining":
                self.state = "dead"     # flapped straight back out
            elif old in ("healthy", "suspect"):
                if self.fails >= self.dead_after:
                    self.state = "dead"
                elif self.fails >= self.suspect_after:
                    self.state = "suspect"
        if self.state != old:
            self.last_transition = now
            return (old, self.state)
        return None

    def force_dead(self, now: float = 0.0):
        """The router *observed* death out-of-band (connection refused on
        a forward, fence kill). Skips the probe count."""
        old = self.state
        self.state = "dead"
        self.fails = max(self.fails, self.dead_after)
        self.oks = 0
        if old != "dead":
            self.last_transition = now
            return (old, "dead")
        return None

    def snapshot(self) -> dict:
        return {"name": self.name, "state": self.state,
                "fails": self.fails, "oks": self.oks,
                "journal_depth": self.journal_depth,
                "last_ok": self.last_ok}
