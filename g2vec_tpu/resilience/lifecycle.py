"""Cooperative job interruption — the exception vocabulary of the
durable-job layer (serve/daemon.py + batch/engine.py + both trainers).

The daemon threads a zero-argument ``check`` callable through
``ResidentEngine.execute`` into the trainers' epoch/shard loops. The
trainers call it at every consistent boundary (full-batch: top of each
chunk; streaming: every shard step, where the cursor checkpoint is also
cut). When the daemon wants a job stopped — client cancel, deadline
passed, SIGTERM drain — ``check`` raises one of these, the trainer
unwinds (the streaming trainer checkpoints its cursor first on a drain),
and the daemon maps the exception back to a per-job terminal or
re-queued state. Cooperative beats preemptive here: the boundaries are
exactly where device state is host-consistent, so an interrupted job is
always either resumable or cleanly terminal, never torn.
"""
from __future__ import annotations


class JobInterrupted(RuntimeError):
    """Base of all cooperative interruptions. ``job_id`` is the serve job
    the interruption targets (None for whole-process reasons like drain —
    every job in the batch is affected)."""

    reason = "interrupted"

    def __init__(self, job_id=None, detail: str = ""):
        self.job_id = job_id
        msg = f"job {job_id}: {self.reason}" if job_id else self.reason
        super().__init__(f"{msg} ({detail})" if detail else msg)


class JobCancelled(JobInterrupted):
    """A client asked for this specific job to stop. Terminal."""

    reason = "cancelled"


class JobDeadlineExceeded(JobInterrupted):
    """The job's ``deadline_s`` elapsed before it finished. Terminal."""

    reason = "deadline_exceeded"


class DrainRequested(JobInterrupted):
    """The daemon is draining (SIGTERM). NOT terminal for the job: the
    streaming trainer checkpoints its cursor before re-raising, the
    daemon leaves the job journaled, and the next daemon run resumes
    it from the checkpoint."""

    reason = "drain"
