"""Cooperative job interruption — the exception vocabulary of the
durable-job layer (serve/daemon.py + batch/engine.py + both trainers).

The daemon threads a zero-argument ``check`` callable through
``ResidentEngine.execute`` into the trainers' epoch/shard loops. The
trainers call it at every consistent boundary (full-batch: top of each
chunk; streaming: every shard step, where the cursor checkpoint is also
cut). When the daemon wants a job stopped — client cancel, deadline
passed, SIGTERM drain — ``check`` raises one of these, the trainer
unwinds (the streaming trainer checkpoints its cursor first on a drain),
and the daemon maps the exception back to a per-job terminal or
re-queued state. Cooperative beats preemptive here: the boundaries are
exactly where device state is host-consistent, so an interrupted job is
always either resumable or cleanly terminal, never torn.
"""
from __future__ import annotations

import random


class JobInterrupted(RuntimeError):
    """Base of all cooperative interruptions. ``job_id`` is the serve job
    the interruption targets (None for whole-process reasons like drain —
    every job in the batch is affected)."""

    reason = "interrupted"

    def __init__(self, job_id=None, detail: str = ""):
        self.job_id = job_id
        msg = f"job {job_id}: {self.reason}" if job_id else self.reason
        super().__init__(f"{msg} ({detail})" if detail else msg)


class JobCancelled(JobInterrupted):
    """A client asked for this specific job to stop. Terminal."""

    reason = "cancelled"


class JobDeadlineExceeded(JobInterrupted):
    """The job's ``deadline_s`` elapsed before it finished. Terminal."""

    reason = "deadline_exceeded"


class DrainRequested(JobInterrupted):
    """The daemon is draining (SIGTERM). NOT terminal for the job: the
    streaming trainer checkpoints its cursor before re-raising, the
    daemon leaves the job journaled, and the next daemon run resumes
    it from the checkpoint."""

    reason = "drain"


# ---------------------------------------------------------------------------
# Replica health — the router's per-replica probe state machine.
# ---------------------------------------------------------------------------

#: healthy → suspect → dead → rejoining → healthy. ``suspect`` keeps the
#: replica in the hash ring (a single missed probe is usually GC or a
#: long compile, not death); ``dead`` removes it and triggers failover;
#: ``rejoining`` answers probes again but takes no traffic until its
#: stale journal is drained.
REPLICA_STATES = ("healthy", "suspect", "dead", "rejoining")


class ReplicaHealth:
    """Pure probe-driven health state for one replica. No I/O, no clock
    reads — the router feeds it ``on_probe(ok, journal_depth, now)`` and
    acts on the returned transition, which keeps every edge unit-testable
    as a table.

    Thresholds: ``suspect_after`` consecutive failures demote healthy →
    suspect, ``dead_after`` total consecutive failures declare dead (the
    failover trigger — the router fences the process before re-queueing,
    so a slow-but-alive replica can never double-execute), and a dead
    replica that answers again must produce ``rejoin_after`` consecutive
    OK probes **with an empty journal** before it is healthy: the empty-
    journal gate is what forces a rejoining replica to drain stale work
    (or have the router migrate it) before taking new traffic.

    ``probe_interval(base)`` backs off exponentially for non-healthy
    replicas so a dead host costs probes, not a probe *storm*.
    """

    def __init__(self, name: str, suspect_after: int = 1,
                 dead_after: int = 3, rejoin_after: int = 2):
        if not (1 <= suspect_after < dead_after):
            raise ValueError("need 1 <= suspect_after < dead_after")
        self.name = name
        self.state = "healthy"
        self.suspect_after = suspect_after
        self.dead_after = dead_after
        self.rejoin_after = rejoin_after
        self.fails = 0          # consecutive failed probes
        self.oks = 0            # consecutive OK probes (rejoin gate)
        self.last_ok: float = 0.0
        self.last_transition: float = 0.0
        self.journal_depth: int = 0

    @property
    def in_ring(self) -> bool:
        """Whether the hash ring may route new jobs here."""
        return self.state in ("healthy", "suspect")

    def probe_interval(self, base: float) -> float:
        """Seconds until the next probe: ``base`` while healthy, doubled
        per consecutive failure (capped at 8x) otherwise — plus nothing;
        jitter is the caller's business."""
        if self.state == "healthy":
            return base
        return base * min(8.0, 2.0 ** max(0, self.fails - 1))

    def on_probe(self, ok: bool, journal_depth: int = 0,
                 now: float = 0.0):
        """Feed one probe outcome. Returns ``(old_state, new_state)`` on
        a transition, else None."""
        old = self.state
        if ok:
            self.fails = 0
            self.oks += 1
            self.last_ok = now
            self.journal_depth = journal_depth
            if old in ("healthy", "suspect"):
                self.state = "healthy"
            elif old == "dead":
                self.state = "rejoining"
                self.oks = 1        # this probe is the first of the gate
            elif old == "rejoining":
                if self.oks >= self.rejoin_after and journal_depth == 0:
                    self.state = "healthy"
        else:
            self.fails += 1
            self.oks = 0
            if old == "rejoining":
                self.state = "dead"     # flapped straight back out
            elif old in ("healthy", "suspect"):
                if self.fails >= self.dead_after:
                    self.state = "dead"
                elif self.fails >= self.suspect_after:
                    self.state = "suspect"
        if self.state != old:
            self.last_transition = now
            return (old, self.state)
        return None

    def force_dead(self, now: float = 0.0):
        """The router *observed* death out-of-band (connection refused on
        a forward, fence kill). Skips the probe count."""
        old = self.state
        self.state = "dead"
        self.fails = max(self.fails, self.dead_after)
        self.oks = 0
        if old != "dead":
            self.last_transition = now
            return (old, "dead")
        return None

    def snapshot(self) -> dict:
        return {"name": self.name, "state": self.state,
                "fails": self.fails, "oks": self.oks,
                "journal_depth": self.journal_depth,
                "last_ok": self.last_ok}


# ---------------------------------------------------------------------------
# Admission SLOs — per-tenant rate limiting and deadline-aware shedding.
# ---------------------------------------------------------------------------


class TokenBucket:
    """Classic token-bucket rate limiter, pure like :class:`ReplicaHealth`:
    the caller supplies ``now`` on every call, so refill math is exact
    and every edge (burst boundary, fractional refill, idle catch-up) is
    unit-testable without sleeping.

    ``rate`` tokens/second refill up to ``burst`` capacity; ``take``
    consumes one if available. ``retry_after`` answers the *useful*
    refusal: not "no", but "no for this many more seconds" — the number
    the daemon's structured ``tenant_quota`` rejection carries so a
    well-behaved client backs off exactly long enough."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError("token bucket needs rate > 0 and burst > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self.tokens = float(burst)      # full at birth: allow the burst
        self._last: float = 0.0         # now of the last refill
        self._primed = False

    def _refill(self, now: float):
        if not self._primed:
            self._primed = True
            self._last = now
            return
        if now > self._last:
            self.tokens = min(self.burst,
                              self.tokens + (now - self._last) * self.rate)
            self._last = now

    def take(self, now: float, n: float = 1.0) -> bool:
        """Consume ``n`` tokens if available. False = rate-limited."""
        self._refill(now)
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False

    def retry_after(self, now: float, n: float = 1.0) -> float:
        """Seconds until ``n`` tokens will exist (0.0 = already there)."""
        self._refill(now)
        if self.tokens >= n:
            return 0.0
        return (n - self.tokens) / self.rate

    def snapshot(self) -> dict:
        return {"rate": self.rate, "burst": self.burst,
                "tokens": round(self.tokens, 6)}


def shed_decision(deadline_s, queued: int, service_time_s):
    """Deadline-aware admission check. Returns ``None`` to admit, or a
    positive ``retry_after_s`` (seconds) to shed.

    The estimate is deliberately simple — ``queued × observed per-job
    service time`` — because it only has to be *directionally* right:
    accepting a job whose estimated wait already exceeds its whole
    ``deadline_s`` burns a queue slot, walk sampling, and training time
    on work that is contractually dead on arrival. Boundary semantics
    (pinned by tests):

    - no deadline (``None``) → never shed; the job can wait forever,
    - no service-time observation yet (``None``) → never shed; without
      evidence the conservative call is to accept,
    - estimated wait exactly equal to the deadline → admit (shed only
      on strict excess),
    - ``retry_after_s`` = the excess wait, floored at one service time,
      i.e. how long the queue needs to drain before this job could
      plausibly make its deadline."""
    if deadline_s is None or service_time_s is None:
        return None
    est_wait = max(0, queued) * float(service_time_s)
    if est_wait <= float(deadline_s):
        return None
    return max(float(service_time_s), est_wait - float(deadline_s))


# ---------------------------------------------------------------------------
# Scaling policy — the router's hysteretic replica-count controller.
# ---------------------------------------------------------------------------


class ScalingPolicy:
    """Seeded, hysteretic scale controller. Pure: the router feeds it one
    ``observe(queued_total, active, est_wait_s)`` per control tick and
    acts on the returned decision (``"up"`` / ``"down"`` / ``"hold"``).

    Two signals, asymmetric thresholds, streak counting, and a cooldown
    — the classic recipe against flapping:

    - **pressure** = queued jobs per active replica. ``up_queue`` and
      ``down_queue`` are deliberately far apart (default 4.0 vs 0.5) so
      the region between them is a dead band.
    - **wait** — the fleet-MEAN queue-drain estimate (total queued ×
      mean observed service time / reachable replicas), not a tail
      percentile: tune ``up_wait_s`` as "a typical queued job waits
      this long", not as a p99. Scale-up also triggers when it crosses
      ``up_wait_s`` even at modest depth (a few slow jobs hurt
      deadlines as much as many fast ones).
    - A decision needs ``up_ticks`` (or ``down_ticks``) *consecutive*
      ticks beyond threshold; any tick back inside the band resets the
      streak, so a square-wave load (spike, quiet, spike …) that flips
      faster than the streak length produces zero decisions.
    - After any decision, ``cooldown_ticks`` ticks of enforced hold let
      the fleet absorb the change before the next one.

    Scale-down is much slower than scale-up (6 ticks vs 2 by default):
    adding capacity late costs deadlines, removing it late costs only a
    warm idle process. ``choose_victim`` picks the replica to drain with
    the policy's own seeded rng, so a chaos run with a fixed seed drains
    the same replicas every time."""

    def __init__(self, min_replicas: int, max_replicas: int,
                 up_queue: float = 4.0, down_queue: float = 0.5,
                 up_wait_s: float = 8.0, up_ticks: int = 2,
                 down_ticks: int = 6, cooldown_ticks: int = 5,
                 seed: int = 0):
        if not (1 <= min_replicas <= max_replicas):
            raise ValueError("need 1 <= min_replicas <= max_replicas")
        if not (0 <= down_queue < up_queue):
            raise ValueError("need 0 <= down_queue < up_queue")
        self.min_replicas = min_replicas
        self.max_replicas = max_replicas
        self.up_queue = float(up_queue)
        self.down_queue = float(down_queue)
        self.up_wait_s = float(up_wait_s)
        self.up_ticks = int(up_ticks)
        self.down_ticks = int(down_ticks)
        self.cooldown_ticks = int(cooldown_ticks)
        self.rng = random.Random(seed)
        self.up_streak = 0
        self.down_streak = 0
        self.cooldown = 0
        self.ticks = 0
        self.decisions = 0

    def observe(self, queued_total: int, active: int,
                est_wait_s=None) -> str:
        """Feed one control tick; returns ``"up"``, ``"down"`` or
        ``"hold"``. The caller is responsible for actually changing the
        fleet — the policy only counts and decides."""
        self.ticks += 1
        pressure = queued_total / max(1, active)
        hot = (pressure >= self.up_queue
               or (est_wait_s is not None
                   and est_wait_s >= self.up_wait_s))
        cold = (pressure <= self.down_queue
                and (est_wait_s is None or est_wait_s < self.up_wait_s))
        if hot:
            self.up_streak += 1
            self.down_streak = 0
        elif cold:
            self.down_streak += 1
            self.up_streak = 0
        else:                   # dead band — reset both streaks
            self.up_streak = 0
            self.down_streak = 0
        if self.cooldown > 0:
            self.cooldown -= 1
            return "hold"
        if (self.up_streak >= self.up_ticks
                and active < self.max_replicas):
            self.up_streak = 0
            self.cooldown = self.cooldown_ticks
            self.decisions += 1
            return "up"
        if (self.down_streak >= self.down_ticks
                and active > self.min_replicas):
            self.down_streak = 0
            self.cooldown = self.cooldown_ticks
            self.decisions += 1
            return "down"
        return "hold"

    def choose_victim(self, candidates):
        """Seeded pick of the replica to drain on scale-down. Sorted
        input + the policy's own rng = reproducible under a fixed seed."""
        ordered = sorted(candidates)
        if not ordered:
            return None
        return self.rng.choice(ordered)

    def snapshot(self) -> dict:
        return {"min": self.min_replicas, "max": self.max_replicas,
                "up_streak": self.up_streak,
                "down_streak": self.down_streak,
                "cooldown": self.cooldown, "ticks": self.ticks,
                "decisions": self.decisions}
