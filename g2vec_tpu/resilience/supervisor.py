"""Auto-resume supervision: bounded retries around ``pipeline.run``.

Two entry points with one retry policy:

- :func:`supervise` — in-process: call ``pipeline.run(cfg)``, classify any
  exception, and re-enter with ``resume=True`` after exponential backoff
  with jitter. Survives everything that surfaces as a Python exception
  (injected crashes, preemption errors, OOMs, stalls that time out).
- :func:`supervise_cli` — child-process (the ``--supervise`` CLI path):
  re-invoke ``python -m g2vec_tpu`` with the original argv (minus the
  supervisor flags, plus ``--resume``) and classify the child's exit.
  This is the only mode that survives SIGKILL / hard preemption — the
  supervisor process itself holds no accelerator state.

Classification (the table is documented in ARCHITECTURE.md):

retryable — preemption/worker-death shapes: ``InjectedFault``,
``RuntimeError`` (XLA runtime errors — preemption, stale collectives —
surface here), ``MemoryError``/OOM, ``ConnectionError``, transient
``OSError``; in child mode any signal exit (negative returncode).

fatal — wrong-input shapes where a retry would burn the whole budget
reproducing the same error: ``InjectedFatal``, ``ValueError`` (config and
reader validation errors; unless its message matches a retryable pattern
like "preempted" or "resource exhausted"), ``TypeError``/``KeyError``/
``AttributeError``/``ImportError``/``NotImplementedError``,
``FileNotFoundError``/``PermissionError``/``IsADirectoryError``.

Every decision is emitted to the run's MetricsWriter JSONL stream
(``retry`` / ``resume`` / ``gave_up`` events, appended so the events from
all attempts form one stream with the pipeline's own records).
"""
from __future__ import annotations

import dataclasses
import os
import random
import re
import subprocess
import sys
import tempfile
import time
from typing import Callable, List, Optional

from g2vec_tpu.resilience.faults import (ENV_PLAN, ENV_STATE, InjectedFatal,
                                         InjectedFault)

# Message patterns that mark an otherwise-fatal-typed exception as
# preemption/capacity-shaped (jax wraps several of these in ValueError).
RETRYABLE_MESSAGE = re.compile(
    r"preempt|out of memory|resource[ _]?exhausted|oom\b|unavailable|"
    r"deadline|collective|all[- ]reduce|socket closed|connection reset|"
    r"data[ _]?loss|injected (crash|stall)|PeerTimeoutError", re.I)

_FATAL_TYPES = (InjectedFatal, FileNotFoundError, IsADirectoryError,
                PermissionError, TypeError, KeyError, AttributeError,
                ImportError, NotImplementedError)
_RETRYABLE_TYPES = (InjectedFault, MemoryError, ConnectionError)

# Child-mode stderr classification: the last traceback line names the type.
_FATAL_NAME = re.compile(
    r"\b(InjectedFatal|ValueError|TypeError|KeyError|AttributeError|"
    r"ImportError|ModuleNotFoundError|NotImplementedError|"
    r"FileNotFoundError|PermissionError|IsADirectoryError)\b")


def classify_exception(exc: BaseException) -> str:
    """Return ``"retryable"`` or ``"fatal"`` for an in-process failure."""
    from g2vec_tpu.resilience.lifecycle import JobInterrupted

    if isinstance(exc, JobInterrupted):
        # A cooperative interruption is an ANSWER, not a failure — it must
        # never enter a retry loop (the daemon handles it before
        # classification; this guard is for any other supervisor).
        return "fatal"
    if isinstance(exc, _RETRYABLE_TYPES):
        return "retryable"
    if isinstance(exc, InjectedFatal):
        return "fatal"
    if isinstance(exc, ValueError):
        # Reader/config validation errors are ValueError by contract
        # (io/readers.py, config.validate) — but jax also ValueError-wraps
        # some capacity errors, so the message gets a vote.
        return "retryable" if RETRYABLE_MESSAGE.search(str(exc)) else "fatal"
    if isinstance(exc, _FATAL_TYPES):
        return "fatal"
    # RuntimeError (incl. XlaRuntimeError), OSError, and anything unknown:
    # assume worker-death shape. The bounded retry budget caps the cost of
    # guessing wrong; misclassifying a preemption as fatal costs the run.
    return "retryable"


def classify_child(returncode: int, stderr_tail: str) -> str:
    """Classify a supervised child process exit (``supervise_cli``)."""
    if returncode < 0:
        return "retryable"     # killed by signal: preemption-shaped
    if RETRYABLE_MESSAGE.search(stderr_tail):
        return "retryable"
    # InjectedFault is a RuntimeError subclass — retryable — so check it
    # before the fatal-name scan (which would not match it anyway, but be
    # explicit about precedence).
    if "InjectedFault" in stderr_tail:
        return "retryable"
    if _FATAL_NAME.search(stderr_tail):
        return "fatal"
    return "retryable"


@dataclasses.dataclass
class RetryPolicy:
    max_retries: int = 3        # retries, not attempts: N+1 runs total
    backoff_base: float = 1.0   # seconds; doubles per retry
    backoff_max: float = 60.0
    jitter: float = 0.25        # +[0, jitter) fraction, decorrelates a fleet

    def delay(self, attempt: int, rng: random.Random) -> float:
        base = min(self.backoff_max, self.backoff_base * (2.0 ** attempt))
        return base * (1.0 + self.jitter * rng.random())


def _policy_from_cfg(cfg) -> RetryPolicy:
    return RetryPolicy(max_retries=cfg.supervise_retries,
                       backoff_base=cfg.supervise_backoff)


def _event_writer(cfg):
    from g2vec_tpu.utils.metrics import MetricsWriter

    return MetricsWriter(cfg.metrics_jsonl, append=True)


def supervise(cfg, policy: Optional[RetryPolicy] = None,
              console: Callable[[str], None] = print,
              sleep: Callable[[float], None] = time.sleep):
    """Run the pipeline under in-process supervision; returns its
    PipelineResult or re-raises the exception that exhausted the policy."""
    from g2vec_tpu.pipeline import run

    policy = policy if policy is not None else _policy_from_cfg(cfg)
    rng = random.Random(cfg.seed)
    attempt = 0
    while True:
        try:
            result = run(cfg, console=console)
        except BaseException as e:  # noqa: BLE001 — classified right below
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            verdict = classify_exception(e)
            err = f"{type(e).__name__}: {e}"[:500]
            if verdict == "fatal" or attempt >= policy.max_retries:
                with _event_writer(cfg) as events:
                    events.emit("gave_up", attempt=attempt, classified=verdict,
                                error=err)
                console(f"[supervisor] giving up after attempt {attempt}: "
                        f"{verdict} — {err}")
                raise
            delay = policy.delay(attempt, rng)
            with _event_writer(cfg) as events:
                events.emit("retry", attempt=attempt, classified=verdict,
                            error=err, delay_seconds=round(delay, 3))
            console(f"[supervisor] attempt {attempt} failed ({err}); "
                    f"retrying with --resume in {delay:.1f}s")
            sleep(delay)
            attempt += 1
            if cfg.distributed:
                # Tear the (possibly wedged) distributed runtime down so
                # the re-entered pipeline.run re-initializes instead of
                # silently reusing dead state — distributed.shutdown()
                # resets the module's _initialized flag for exactly this.
                from g2vec_tpu.parallel.distributed import shutdown

                shutdown()
            cfg = dataclasses.replace(cfg, resume=True)
            with _event_writer(cfg) as events:
                events.emit("resume", attempt=attempt,
                            checkpoint_dir=cfg.checkpoint_dir)
            continue
        if attempt:
            with _event_writer(cfg) as events:
                events.emit("supervised_done", attempts=attempt + 1)
        return result


def _scrub_supervisor_argv(argv: List[str]) -> List[str]:
    """Drop the supervisor's own flags from the child argv."""
    out, skip = [], False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok == "--supervise":
            continue
        if tok in ("--supervise-retries", "--supervise-backoff"):
            skip = True
            continue
        if tok.startswith("--supervise-retries=") \
                or tok.startswith("--supervise-backoff="):
            continue
        out.append(tok)
    return out


def supervise_serve(argv: List[str], *, retries: int = 3,
                    backoff: float = 1.0,
                    metrics_jsonl: Optional[str] = None,
                    state_dir: Optional[str] = None,
                    sleep: Callable[[float], None] = time.sleep) -> int:
    """Watchdog for the ``g2vec serve`` daemon (``g2vec serve --supervise``).

    Relaunches ``python -m g2vec_tpu serve`` (minus the supervisor's own
    flags) until it exits cleanly, a failure classifies fatal, or the
    retry budget runs out — the same policy/classification as
    :func:`supervise_cli`, WITHOUT ``--resume``: the daemon's own journal
    re-queues in-flight jobs on relaunch, and its persistent ``--cache-dir``
    tiers restore the compile/walk warm state. The child's stderr goes to
    ``<state_dir>/serve-stderr.log`` (a resident daemon can outlive any
    pipe buffer); its tail feeds the exit classification.
    """
    policy = RetryPolicy(max_retries=retries, backoff_base=backoff)
    rng = random.Random(0)
    child_argv = _scrub_supervisor_argv(list(argv))
    env = dict(os.environ)
    if env.get(ENV_PLAN) or any(a == "--fault-plan"
                                or a.startswith("--fault-plan=")
                                for a in child_argv):
        if not env.get(ENV_STATE):
            # One-shot faults must stay one-shot across daemon restarts
            # (same contract as supervise_cli).
            fd, state = tempfile.mkstemp(prefix="g2vec-fault-state-")
            os.close(fd)
            os.unlink(state)
            env[ENV_STATE] = state

    def _events():
        from g2vec_tpu.utils.metrics import MetricsWriter

        return MetricsWriter(metrics_jsonl, append=True)

    err_log = os.path.join(state_dir, "serve-stderr.log") if state_dir \
        else None
    attempt = 0
    while True:
        cmd = [sys.executable, "-m", "g2vec_tpu", "serve", *child_argv]
        if err_log:
            os.makedirs(state_dir, exist_ok=True)
            with open(err_log, "ab") as ef:
                ef.write(f"--- serve attempt {attempt} ---\n".encode())
                ef.flush()
                proc = subprocess.run(cmd, env=env, stderr=ef)
            with open(err_log, "rb") as ef2:
                tail = ef2.read()[-2000:].decode(errors="replace")
        else:
            proc = subprocess.run(cmd, env=env, stderr=subprocess.PIPE,
                                  text=True)
            if proc.stderr:
                sys.stderr.write(proc.stderr)
            tail = (proc.stderr or "")[-2000:]
        if proc.returncode == 0:
            if attempt:
                with _events() as events:
                    events.emit("serve_supervised_done",
                                attempts=attempt + 1)
            return 0
        verdict = classify_child(proc.returncode, tail)
        err = f"serve rc={proc.returncode}: {tail[-300:].strip()}"[:500]
        if verdict == "fatal" or attempt >= policy.max_retries:
            with _events() as events:
                events.emit("gave_up", attempt=attempt, classified=verdict,
                            error=err)
            print(f"[serve-supervisor] giving up after attempt {attempt}: "
                  f"{verdict} — rc={proc.returncode}", file=sys.stderr)
            return proc.returncode if proc.returncode > 0 else 1
        delay = policy.delay(attempt, rng)
        with _events() as events:
            events.emit("serve_relaunch", attempt=attempt,
                        classified=verdict, error=err,
                        delay_seconds=round(delay, 3))
        print(f"[serve-supervisor] daemon died (rc={proc.returncode}, "
              f"{verdict}); relaunching in {delay:.1f}s — journaled jobs "
              f"re-queue on start", file=sys.stderr)
        sleep(delay)
        attempt += 1


# ---------------------------------------------------------------------------
# Fleet-of-daemons supervision — the router's process-management substrate.
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class ReplicaSpec:
    """One serve replica: its directory layout plus the live handle.

    ``proc`` is a Popen when this fleet launched the replica; after a
    router restart an *adopted* replica has only ``pid`` (learned from
    its ``/status``) — fencing handles both.

    Concurrency: the fleet/spec objects carry no lock of their own. The
    mutable fields below are guarded EXTERNALLY by the router's
    per-replica lock (the dotted guarded-by form is documentation-only
    to the lock-discipline checker — it records the contract without
    pretending to verify a lock it cannot see from this file)."""

    name: str
    dir: str
    socket_path: str
    state_dir: str
    log_path: str
    addr: Optional[str] = None          # guarded-by: Router._rep_locks
    pid: Optional[int] = None           # guarded-by: Router._rep_locks
    proc: Optional[object] = None       # guarded-by: Router._rep_locks
    boots: int = 0                      # guarded-by: Router._rep_locks
    exits: int = 0                      # guarded-by: Router._rep_locks
    #: False for a replica on another host (or behind a relay): its
    #: death can never be verified from here — fence() must not kill a
    #: recycled local pid, and the router must quarantine via a fence
    #: marker instead of trusting SIGKILL.
    local: bool = True                  # guarded-by: Router._rep_locks


class ReplicaFleet:
    """Launch/adopt/fence/relaunch N ``g2vec serve`` daemon children.

    Layout: ``<fleet_dir>/<name>/`` holds ``sock`` (UNIX socket),
    ``state/`` (the daemon's durable state dir — journal, results,
    ckpt), and ``serve.log`` (stderr). Each replica also gets a TCP
    listener on an ephemeral port, discovered via the daemon's
    ``<state>/tcp_addr`` file.

    The fleet does NOT auto-relaunch a dead replica — that is the
    router's call, *after* it has fenced the corpse and migrated its
    journal (relaunch-before-migrate would resurrect the stale journal
    and double-run jobs). ``supervise_serve`` above remains the
    single-daemon watchdog; this class is deliberately dumber.
    """

    def __init__(self, fleet_dir: str, n: int,
                 serve_argv: Optional[List[str]] = None,
                 listen_host: str = "127.0.0.1",
                 env: Optional[dict] = None,
                 console: Callable[[str], None] = print):
        if n < 1:
            raise ValueError("fleet needs n >= 1 replicas")
        self.fleet_dir = os.path.abspath(fleet_dir)
        self.listen_host = listen_host
        self.serve_argv = list(serve_argv or [])
        self.env = dict(env) if env is not None else dict(os.environ)
        self.console = console
        #: name -> ReplicaSpec. The dict shape is fixed at construction;
        #: per-spec mutation happens under Router._rep_locks[name].
        # guarded-by: Router._rep_locks
        self.replicas: dict = {}
        for i in range(n):
            name = f"r{i}"
            rdir = os.path.join(self.fleet_dir, name)
            self.replicas[name] = ReplicaSpec(
                name=name, dir=rdir,
                socket_path=os.path.join(rdir, "sock"),
                state_dir=os.path.join(rdir, "state"),
                log_path=os.path.join(rdir, "serve.log"))

    def names(self) -> List[str]:
        return list(self.replicas)

    def replica(self, name: str) -> ReplicaSpec:
        return self.replicas[name]

    def _addr_file(self, spec: ReplicaSpec) -> str:
        return os.path.join(spec.state_dir, "tcp_addr")

    def launch(self, name: str, wait_ready_s: float = 90.0) -> ReplicaSpec:
        """Spawn one replica and wait for its TCP listener to come up
        (the daemon writes ``<state>/tcp_addr`` at bind time)."""
        spec = self.replicas[name]
        os.makedirs(spec.state_dir, exist_ok=True)
        # Never boot a successor over an unfenced predecessor: a zombie
        # replica this fleet object has no handle for (router restarted,
        # probe timed out so it was never adopted) would race the new
        # process on the same journal. fence() falls back to the
        # daemon's own pidfile, so this reaches even unknown pids.
        self.fence(name, grace_s=0.0)
        try:
            os.unlink(self._addr_file(spec))    # never read a stale addr
        except OSError:
            pass
        # A successor on this state dir boots UNFENCED: the quarantine
        # marker that parked the predecessor (serve/leader.py) must not
        # instantly park the fresh daemon. The router clears it on its
        # own relaunch path too; this covers manual/boot launches.
        from g2vec_tpu.serve.leader import clear_fence_marker

        clear_fence_marker(spec.state_dir)
        cmd = [sys.executable, "-m", "g2vec_tpu", "serve",
               "--socket", spec.socket_path,
               "--state-dir", spec.state_dir,
               "--listen", f"{self.listen_host}:0",
               # Per-replica stream (a later --metrics-jsonl in serve_argv
               # overrides): fleet-wide accounting scans every replica's
               # file, so two processes never interleave one JSONL.
               "--metrics-jsonl", os.path.join(spec.dir, "metrics.jsonl"),
               *self.serve_argv]
        logf = open(spec.log_path, "ab")
        logf.write(f"--- boot {spec.boots} ---\n".encode())
        logf.flush()
        spec.proc = subprocess.Popen(cmd, env=self.env, stderr=logf,
                                     stdout=logf)
        logf.close()        # child holds the fd
        spec.pid = spec.proc.pid
        spec.boots += 1
        deadline = time.monotonic() + wait_ready_s
        addr_file = self._addr_file(spec)
        while time.monotonic() < deadline:
            if os.path.exists(addr_file):
                with open(addr_file) as fh:
                    spec.addr = fh.read().strip()
                if spec.addr:
                    return spec
            if spec.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {name} died during boot "
                    f"(rc={spec.proc.returncode}); see {spec.log_path}")
            time.sleep(0.05)
        raise TimeoutError(f"replica {name} TCP listener not up within "
                           f"{wait_ready_s:.0f}s; see {spec.log_path}")

    def adopt(self, name: str, pid: int, addr: Optional[str],
              local: bool = True) -> ReplicaSpec:
        """Record an already-running replica (router restart: the daemons
        survived, only the router died). Fencing falls back to
        ``os.kill`` since the process is not our child; ``local=False``
        marks a replica whose process lives beyond this host's reach
        (remote or relayed), so fencing can only ever be advisory."""
        spec = self.replicas[name]
        spec.proc = None
        spec.pid = pid
        spec.local = local
        if addr:
            spec.addr = addr
        elif os.path.exists(self._addr_file(spec)):
            with open(self._addr_file(spec)) as fh:
                spec.addr = fh.read().strip()
        return spec

    def alive(self, name: str) -> bool:
        spec = self.replicas[name]
        if spec.proc is not None:
            return spec.proc.poll() is None
        if spec.pid is None:
            return False
        if not spec.local:
            # A remote pid means nothing to this host's process table;
            # only the router's network probes can judge it. Having a
            # pid at all means it was adopted alive.
            return True
        try:
            os.kill(spec.pid, 0)
            return True
        except OSError:
            return False

    def _pidfile_pid(self, spec: "ReplicaSpec") -> Optional[int]:
        """The pid the daemon recorded in ``<state>/serve.pid`` — the
        fence target of last resort for a replica this fleet object
        never launched or adopted. Verified against the process's
        cmdline (must mention this replica's state dir) so a recycled
        pid is never killed; a clean daemon exit unlinks the file."""
        path = os.path.join(spec.state_dir, "serve.pid")
        try:
            with open(path) as fh:
                pid = int(fh.read().strip())
        except (OSError, ValueError):
            return None
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as fh:
                cmdline = fh.read().decode("utf-8", "replace")
        except OSError:
            return None
        return pid if spec.state_dir in cmdline else None

    def fence(self, name: str, grace_s: float = 2.0) -> Optional[int]:
        """Guarantee the replica process is dead before its journal is
        migrated — a slow-but-alive replica must never race a survivor
        on the same job. Waits up to ``grace_s`` for a natural exit,
        then SIGKILLs. Returns the exit code when known (negative =
        killed by that signal), None for a non-child — None means the
        caller has NO local proof of death, which is what separates a
        verified-dead failover from a false-dead quarantine."""
        import signal as _signal

        spec = self.replicas[name]
        rc: Optional[int] = None
        if not spec.local:
            # The process lives on another host: os.kill here would hit
            # a recycled local pid at best. Death is unverifiable.
            spec.proc = None
            spec.pid = None
            spec.exits += 1
            return None
        if spec.proc is None and spec.pid is None:
            spec.pid = self._pidfile_pid(spec)
        if spec.proc is not None:
            try:
                rc = spec.proc.wait(timeout=grace_s)
            except subprocess.TimeoutExpired:
                spec.proc.kill()
                rc = spec.proc.wait(timeout=10.0)
            spec.proc = None
        elif spec.pid is not None:
            deadline = time.monotonic() + grace_s
            while time.monotonic() < deadline:
                try:
                    os.kill(spec.pid, 0)
                except OSError:
                    break
                time.sleep(0.05)
            try:
                os.kill(spec.pid, _signal.SIGKILL)
            except OSError:
                pass
            # Non-child: poll until the pid is gone (bounded).
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                try:
                    os.kill(spec.pid, 0)
                    time.sleep(0.05)
                except OSError:
                    break
        spec.pid = None
        spec.exits += 1
        return rc

    def relaunch(self, name: str, wait_ready_s: float = 90.0) -> ReplicaSpec:
        """Fence (idempotent if already dead) then boot a fresh process
        on the same state dir — the journal recovery + idem table
        restore on boot are what make this safe."""
        self.fence(name, grace_s=0.0)
        return self.launch(name, wait_ready_s=wait_ready_s)

    def stop_all(self, grace_s: float = 30.0) -> None:
        import signal as _signal

        for spec in self.replicas.values():
            if not spec.local:
                continue        # not ours to signal
            if spec.proc is not None and spec.proc.poll() is None:
                spec.proc.send_signal(_signal.SIGTERM)
            else:
                if spec.pid is None:
                    spec.pid = self._pidfile_pid(spec)
                if spec.pid is not None:
                    try:
                        os.kill(spec.pid, _signal.SIGTERM)
                    except OSError:
                        pass
        deadline = time.monotonic() + grace_s
        for name in self.names():
            self.fence(name, grace_s=max(0.0,
                                         deadline - time.monotonic()))


def supervise_cli(cfg, argv: List[str],
                  sleep: Callable[[float], None] = time.sleep) -> int:
    """The ``--supervise`` entry: run ``python -m g2vec_tpu`` children until
    one succeeds, the policy is exhausted, or a failure classifies fatal.
    Returns the exit code to hand the shell."""
    policy = _policy_from_cfg(cfg)
    rng = random.Random(cfg.seed)
    child_argv = _scrub_supervisor_argv(list(argv))
    env = dict(os.environ)
    if cfg.fault_plan:
        env[ENV_PLAN] = cfg.fault_plan
    if env.get(ENV_PLAN) and not env.get(ENV_STATE):
        # One-shot faults must stay one-shot across child restarts; without
        # a cross-process state file the same sigkill would fire forever.
        fd, state = tempfile.mkstemp(prefix="g2vec-fault-state-")
        os.close(fd)
        os.unlink(state)        # the fault hook creates it on first fire
        env[ENV_STATE] = state
    attempt = 0
    while True:
        cmd = [sys.executable, "-m", "g2vec_tpu", *child_argv]
        if attempt and "--resume" not in child_argv:
            cmd.append("--resume")
        proc = subprocess.run(cmd, env=env, stderr=subprocess.PIPE, text=True)
        if proc.stderr:
            sys.stderr.write(proc.stderr)
        if proc.returncode == 0:
            if attempt:
                with _event_writer(cfg) as events:
                    events.emit("supervised_done", attempts=attempt + 1)
            return 0
        tail = (proc.stderr or "")[-2000:]
        verdict = classify_child(proc.returncode, tail)
        err = f"child rc={proc.returncode}: {tail[-300:].strip()}"[:500]
        if verdict == "fatal" or attempt >= policy.max_retries:
            with _event_writer(cfg) as events:
                events.emit("gave_up", attempt=attempt, classified=verdict,
                            error=err)
            print(f"[supervisor] giving up after attempt {attempt}: "
                  f"{verdict} — rc={proc.returncode}", file=sys.stderr)
            return proc.returncode if proc.returncode > 0 else 1
        delay = policy.delay(attempt, rng)
        with _event_writer(cfg) as events:
            events.emit("retry", attempt=attempt, classified=verdict,
                        error=err, delay_seconds=round(delay, 3))
        print(f"[supervisor] attempt {attempt} failed "
              f"(rc={proc.returncode}); retrying with --resume in "
              f"{delay:.1f}s", file=sys.stderr)
        sleep(delay)
        attempt += 1
        with _event_writer(cfg) as events:
            events.emit("resume", attempt=attempt,
                        checkpoint_dir=cfg.checkpoint_dir)
