"""Auto-resume supervision: bounded retries around ``pipeline.run``.

Two entry points with one retry policy:

- :func:`supervise` — in-process: call ``pipeline.run(cfg)``, classify any
  exception, and re-enter with ``resume=True`` after exponential backoff
  with jitter. Survives everything that surfaces as a Python exception
  (injected crashes, preemption errors, OOMs, stalls that time out).
- :func:`supervise_cli` — child-process (the ``--supervise`` CLI path):
  re-invoke ``python -m g2vec_tpu`` with the original argv (minus the
  supervisor flags, plus ``--resume``) and classify the child's exit.
  This is the only mode that survives SIGKILL / hard preemption — the
  supervisor process itself holds no accelerator state.

Classification (the table is documented in ARCHITECTURE.md):

retryable — preemption/worker-death shapes: ``InjectedFault``,
``RuntimeError`` (XLA runtime errors — preemption, stale collectives —
surface here), ``MemoryError``/OOM, ``ConnectionError``, transient
``OSError``; in child mode any signal exit (negative returncode).

fatal — wrong-input shapes where a retry would burn the whole budget
reproducing the same error: ``InjectedFatal``, ``ValueError`` (config and
reader validation errors; unless its message matches a retryable pattern
like "preempted" or "resource exhausted"), ``TypeError``/``KeyError``/
``AttributeError``/``ImportError``/``NotImplementedError``,
``FileNotFoundError``/``PermissionError``/``IsADirectoryError``.

Every decision is emitted to the run's MetricsWriter JSONL stream
(``retry`` / ``resume`` / ``gave_up`` events, appended so the events from
all attempts form one stream with the pipeline's own records).
"""
from __future__ import annotations

import dataclasses
import os
import random
import re
import subprocess
import sys
import tempfile
import time
from typing import Callable, List, Optional

from g2vec_tpu.resilience.faults import (ENV_PLAN, ENV_STATE, InjectedFatal,
                                         InjectedFault)

# Message patterns that mark an otherwise-fatal-typed exception as
# preemption/capacity-shaped (jax wraps several of these in ValueError).
RETRYABLE_MESSAGE = re.compile(
    r"preempt|out of memory|resource[ _]?exhausted|oom\b|unavailable|"
    r"deadline|collective|all[- ]reduce|socket closed|connection reset|"
    r"data[ _]?loss|injected (crash|stall)|PeerTimeoutError", re.I)

_FATAL_TYPES = (InjectedFatal, FileNotFoundError, IsADirectoryError,
                PermissionError, TypeError, KeyError, AttributeError,
                ImportError, NotImplementedError)
_RETRYABLE_TYPES = (InjectedFault, MemoryError, ConnectionError)

# Child-mode stderr classification: the last traceback line names the type.
_FATAL_NAME = re.compile(
    r"\b(InjectedFatal|ValueError|TypeError|KeyError|AttributeError|"
    r"ImportError|ModuleNotFoundError|NotImplementedError|"
    r"FileNotFoundError|PermissionError|IsADirectoryError)\b")


def classify_exception(exc: BaseException) -> str:
    """Return ``"retryable"`` or ``"fatal"`` for an in-process failure."""
    from g2vec_tpu.resilience.lifecycle import JobInterrupted

    if isinstance(exc, JobInterrupted):
        # A cooperative interruption is an ANSWER, not a failure — it must
        # never enter a retry loop (the daemon handles it before
        # classification; this guard is for any other supervisor).
        return "fatal"
    if isinstance(exc, _RETRYABLE_TYPES):
        return "retryable"
    if isinstance(exc, InjectedFatal):
        return "fatal"
    if isinstance(exc, ValueError):
        # Reader/config validation errors are ValueError by contract
        # (io/readers.py, config.validate) — but jax also ValueError-wraps
        # some capacity errors, so the message gets a vote.
        return "retryable" if RETRYABLE_MESSAGE.search(str(exc)) else "fatal"
    if isinstance(exc, _FATAL_TYPES):
        return "fatal"
    # RuntimeError (incl. XlaRuntimeError), OSError, and anything unknown:
    # assume worker-death shape. The bounded retry budget caps the cost of
    # guessing wrong; misclassifying a preemption as fatal costs the run.
    return "retryable"


def classify_child(returncode: int, stderr_tail: str) -> str:
    """Classify a supervised child process exit (``supervise_cli``)."""
    if returncode < 0:
        return "retryable"     # killed by signal: preemption-shaped
    if RETRYABLE_MESSAGE.search(stderr_tail):
        return "retryable"
    # InjectedFault is a RuntimeError subclass — retryable — so check it
    # before the fatal-name scan (which would not match it anyway, but be
    # explicit about precedence).
    if "InjectedFault" in stderr_tail:
        return "retryable"
    if _FATAL_NAME.search(stderr_tail):
        return "fatal"
    return "retryable"


@dataclasses.dataclass
class RetryPolicy:
    max_retries: int = 3        # retries, not attempts: N+1 runs total
    backoff_base: float = 1.0   # seconds; doubles per retry
    backoff_max: float = 60.0
    jitter: float = 0.25        # +[0, jitter) fraction, decorrelates a fleet

    def delay(self, attempt: int, rng: random.Random) -> float:
        base = min(self.backoff_max, self.backoff_base * (2.0 ** attempt))
        return base * (1.0 + self.jitter * rng.random())


def _policy_from_cfg(cfg) -> RetryPolicy:
    return RetryPolicy(max_retries=cfg.supervise_retries,
                       backoff_base=cfg.supervise_backoff)


def _event_writer(cfg):
    from g2vec_tpu.utils.metrics import MetricsWriter

    return MetricsWriter(cfg.metrics_jsonl, append=True)


def supervise(cfg, policy: Optional[RetryPolicy] = None,
              console: Callable[[str], None] = print,
              sleep: Callable[[float], None] = time.sleep):
    """Run the pipeline under in-process supervision; returns its
    PipelineResult or re-raises the exception that exhausted the policy."""
    from g2vec_tpu.pipeline import run

    policy = policy if policy is not None else _policy_from_cfg(cfg)
    rng = random.Random(cfg.seed)
    attempt = 0
    while True:
        try:
            result = run(cfg, console=console)
        except BaseException as e:  # noqa: BLE001 — classified right below
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            verdict = classify_exception(e)
            err = f"{type(e).__name__}: {e}"[:500]
            if verdict == "fatal" or attempt >= policy.max_retries:
                with _event_writer(cfg) as events:
                    events.emit("gave_up", attempt=attempt, classified=verdict,
                                error=err)
                console(f"[supervisor] giving up after attempt {attempt}: "
                        f"{verdict} — {err}")
                raise
            delay = policy.delay(attempt, rng)
            with _event_writer(cfg) as events:
                events.emit("retry", attempt=attempt, classified=verdict,
                            error=err, delay_seconds=round(delay, 3))
            console(f"[supervisor] attempt {attempt} failed ({err}); "
                    f"retrying with --resume in {delay:.1f}s")
            sleep(delay)
            attempt += 1
            if cfg.distributed:
                # Tear the (possibly wedged) distributed runtime down so
                # the re-entered pipeline.run re-initializes instead of
                # silently reusing dead state — distributed.shutdown()
                # resets the module's _initialized flag for exactly this.
                from g2vec_tpu.parallel.distributed import shutdown

                shutdown()
            cfg = dataclasses.replace(cfg, resume=True)
            with _event_writer(cfg) as events:
                events.emit("resume", attempt=attempt,
                            checkpoint_dir=cfg.checkpoint_dir)
            continue
        if attempt:
            with _event_writer(cfg) as events:
                events.emit("supervised_done", attempts=attempt + 1)
        return result


def _scrub_supervisor_argv(argv: List[str]) -> List[str]:
    """Drop the supervisor's own flags from the child argv."""
    out, skip = [], False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok == "--supervise":
            continue
        if tok in ("--supervise-retries", "--supervise-backoff"):
            skip = True
            continue
        if tok.startswith("--supervise-retries=") \
                or tok.startswith("--supervise-backoff="):
            continue
        out.append(tok)
    return out


def supervise_serve(argv: List[str], *, retries: int = 3,
                    backoff: float = 1.0,
                    metrics_jsonl: Optional[str] = None,
                    state_dir: Optional[str] = None,
                    sleep: Callable[[float], None] = time.sleep) -> int:
    """Watchdog for the ``g2vec serve`` daemon (``g2vec serve --supervise``).

    Relaunches ``python -m g2vec_tpu serve`` (minus the supervisor's own
    flags) until it exits cleanly, a failure classifies fatal, or the
    retry budget runs out — the same policy/classification as
    :func:`supervise_cli`, WITHOUT ``--resume``: the daemon's own journal
    re-queues in-flight jobs on relaunch, and its persistent ``--cache-dir``
    tiers restore the compile/walk warm state. The child's stderr goes to
    ``<state_dir>/serve-stderr.log`` (a resident daemon can outlive any
    pipe buffer); its tail feeds the exit classification.
    """
    policy = RetryPolicy(max_retries=retries, backoff_base=backoff)
    rng = random.Random(0)
    child_argv = _scrub_supervisor_argv(list(argv))
    env = dict(os.environ)
    if env.get(ENV_PLAN) or any(a == "--fault-plan"
                                or a.startswith("--fault-plan=")
                                for a in child_argv):
        if not env.get(ENV_STATE):
            # One-shot faults must stay one-shot across daemon restarts
            # (same contract as supervise_cli).
            fd, state = tempfile.mkstemp(prefix="g2vec-fault-state-")
            os.close(fd)
            os.unlink(state)
            env[ENV_STATE] = state

    def _events():
        from g2vec_tpu.utils.metrics import MetricsWriter

        return MetricsWriter(metrics_jsonl, append=True)

    err_log = os.path.join(state_dir, "serve-stderr.log") if state_dir \
        else None
    attempt = 0
    while True:
        cmd = [sys.executable, "-m", "g2vec_tpu", "serve", *child_argv]
        if err_log:
            os.makedirs(state_dir, exist_ok=True)
            with open(err_log, "ab") as ef:
                ef.write(f"--- serve attempt {attempt} ---\n".encode())
                ef.flush()
                proc = subprocess.run(cmd, env=env, stderr=ef)
            with open(err_log, "rb") as ef2:
                tail = ef2.read()[-2000:].decode(errors="replace")
        else:
            proc = subprocess.run(cmd, env=env, stderr=subprocess.PIPE,
                                  text=True)
            if proc.stderr:
                sys.stderr.write(proc.stderr)
            tail = (proc.stderr or "")[-2000:]
        if proc.returncode == 0:
            if attempt:
                with _events() as events:
                    events.emit("serve_supervised_done",
                                attempts=attempt + 1)
            return 0
        verdict = classify_child(proc.returncode, tail)
        err = f"serve rc={proc.returncode}: {tail[-300:].strip()}"[:500]
        if verdict == "fatal" or attempt >= policy.max_retries:
            with _events() as events:
                events.emit("gave_up", attempt=attempt, classified=verdict,
                            error=err)
            print(f"[serve-supervisor] giving up after attempt {attempt}: "
                  f"{verdict} — rc={proc.returncode}", file=sys.stderr)
            return proc.returncode if proc.returncode > 0 else 1
        delay = policy.delay(attempt, rng)
        with _events() as events:
            events.emit("serve_relaunch", attempt=attempt,
                        classified=verdict, error=err,
                        delay_seconds=round(delay, 3))
        print(f"[serve-supervisor] daemon died (rc={proc.returncode}, "
              f"{verdict}); relaunching in {delay:.1f}s — journaled jobs "
              f"re-queue on start", file=sys.stderr)
        sleep(delay)
        attempt += 1


def supervise_cli(cfg, argv: List[str],
                  sleep: Callable[[float], None] = time.sleep) -> int:
    """The ``--supervise`` entry: run ``python -m g2vec_tpu`` children until
    one succeeds, the policy is exhausted, or a failure classifies fatal.
    Returns the exit code to hand the shell."""
    policy = _policy_from_cfg(cfg)
    rng = random.Random(cfg.seed)
    child_argv = _scrub_supervisor_argv(list(argv))
    env = dict(os.environ)
    if cfg.fault_plan:
        env[ENV_PLAN] = cfg.fault_plan
    if env.get(ENV_PLAN) and not env.get(ENV_STATE):
        # One-shot faults must stay one-shot across child restarts; without
        # a cross-process state file the same sigkill would fire forever.
        fd, state = tempfile.mkstemp(prefix="g2vec-fault-state-")
        os.close(fd)
        os.unlink(state)        # the fault hook creates it on first fire
        env[ENV_STATE] = state
    attempt = 0
    while True:
        cmd = [sys.executable, "-m", "g2vec_tpu", *child_argv]
        if attempt and "--resume" not in child_argv:
            cmd.append("--resume")
        proc = subprocess.run(cmd, env=env, stderr=subprocess.PIPE, text=True)
        if proc.stderr:
            sys.stderr.write(proc.stderr)
        if proc.returncode == 0:
            if attempt:
                with _event_writer(cfg) as events:
                    events.emit("supervised_done", attempts=attempt + 1)
            return 0
        tail = (proc.stderr or "")[-2000:]
        verdict = classify_child(proc.returncode, tail)
        err = f"child rc={proc.returncode}: {tail[-300:].strip()}"[:500]
        if verdict == "fatal" or attempt >= policy.max_retries:
            with _event_writer(cfg) as events:
                events.emit("gave_up", attempt=attempt, classified=verdict,
                            error=err)
            print(f"[supervisor] giving up after attempt {attempt}: "
                  f"{verdict} — rc={proc.returncode}", file=sys.stderr)
            return proc.returncode if proc.returncode > 0 else 1
        delay = policy.delay(attempt, rng)
        with _event_writer(cfg) as events:
            events.emit("retry", attempt=attempt, classified=verdict,
                        error=err, delay_seconds=round(delay, 3))
        print(f"[supervisor] attempt {attempt} failed "
              f"(rc={proc.returncode}); retrying with --resume in "
              f"{delay:.1f}s", file=sys.stderr)
        sleep(delay)
        attempt += 1
        with _event_writer(cfg) as events:
            events.emit("resume", attempt=attempt,
                        checkpoint_dir=cfg.checkpoint_dir)
