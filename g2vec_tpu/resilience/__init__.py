"""Resilience subsystem: fault injection, checkpoint integrity, auto-resume.

Three cooperating layers (none of which the reference has — a single fault
kills its whole run with no recovery path):

- :mod:`g2vec_tpu.resilience.faults` — a config/env-driven fault plan that
  can raise, SIGKILL, stall, or corrupt bytes at named seams (stage
  boundaries, the epoch loop, checkpoint writes, native-library loads).
  Zero-cost when no plan is set; exists so the recovery paths below are
  continuously testable instead of exercised only by real outages.
- checkpoint integrity — ``train/checkpoint.py`` writes a sidecar manifest
  (per-leaf sha256 + config fingerprint + schema version) with every save
  and verifies it on load, falling back to the kept-previous checkpoint on
  corruption.
- :mod:`g2vec_tpu.resilience.supervisor` — wraps ``pipeline.run`` in a
  bounded retry loop (exponential backoff + jitter), classifies failures as
  retryable vs fatal, re-enters via resume, and emits ``retry`` / ``resume``
  / ``gave_up`` events to the MetricsWriter JSONL stream.
- :mod:`g2vec_tpu.resilience.fleet` — the multi-process extension:
  per-rank heartbeats/liveness files, deadline watchdogs over every
  blocking multihost collective (``PeerTimeoutError`` names the missing
  rank instead of hanging), per-stage straggler detection, and the
  degraded-mesh fleet supervisor (on peer death: re-plan the mesh over
  the surviving devices, relaunch, resume from the sharded checkpoint).

This package must stay importable without jax: the fault hooks run inside
modules (native bindings, CLI entry) that are deliberately jax-free, and
``fleet`` defers every jax import to call time for the same reason.
"""
from g2vec_tpu.resilience.faults import (FaultPlanError, InjectedFatal,
                                         InjectedFault, fault_point,
                                         install_plan)
from g2vec_tpu.resilience.fleet import PeerTimeoutError

__all__ = ["fault_point", "install_plan", "InjectedFault", "InjectedFatal",
           "FaultPlanError", "PeerTimeoutError"]
