"""Fleet-grade resilience: heartbeats, collective watchdogs, degraded-mesh
resume.

PR-1 made single-process faults survivable; this module extends the
subsystem from one process to the fleet, where the failure modes change
shape: a multihost collective does not crash when a peer dies — it blocks
forever, and the job wedges with no diagnostic. Systems that scale
embeddings to pods treat peer failure as routine (HUGE, arXiv 2307.14490;
GraphVite, arXiv 1903.00757); the pieces here make it so:

- **Heartbeats** (:class:`Heartbeat`): a per-process daemon thread that
  writes a liveness file (``rank_K.json`` under ``--fleet-liveness-dir``)
  every ``--fleet-heartbeat-interval`` seconds and emits ``heartbeat``
  events into the run's ``--metrics-jsonl`` stream. The file carries the
  rank's current pipeline phase and the (name, seq) of the last host
  collective it entered — the forensic record every other piece reads.

- **Collective watchdogs**: every host-side collective runs under a
  deadline. The KV-transport collectives (parallel/hostcomm.py) enforce it
  natively and name the exact ranks whose contribution never arrived; XLA
  collectives that cannot time out (the multihost_utils paths on real
  pods) are wrapped in :func:`collective_watchdog`, which times the call
  out from a sibling thread and attributes blame from the peers' liveness
  files. Both raise :class:`PeerTimeoutError` — a RuntimeError, so the
  supervisor classifies it retryable.

- **Straggler detection** (:func:`stage_barrier`): after each pipeline
  stage every rank allgathers its stage duration; ranks slower than
  ``--fleet-straggler-factor`` x the median are reported in a
  ``straggler_warning`` metrics event. The gather doubles as a per-stage
  barrier, converting "rank 3 died during stage 4" into a named
  PeerTimeoutError at the next stage edge instead of a silent wedge
  somewhere inside stage 5.

- **Degraded-mesh resume** (:func:`supervise_fleet`): the fleet launcher /
  supervisor. Starts ``--fleet-size`` ranks, watches them, and on peer
  death re-plans the mesh to the largest valid ``(data, model)``
  factorization of the surviving device count (:func:`plan_mesh`),
  relaunches the survivors, and resumes from the sharded orbax checkpoint
  — leaves reshard onto the new mesh at load. Final vectors are
  bit-identical to an uninterrupted run whenever the checkpoint captured
  the trainer's last-epoch/terminal state: the walk stage re-executes
  bit-identically under any mesh (the walker's global stream identities),
  and the analysis stages are pure functions of the restored embeddings.
  Epochs that must be RE-TRAINED under a different mesh reassociate
  floating-point reductions and track the original to ~1e-7 instead —
  ARCHITECTURE.md documents the boundary.

Everything is inert by default: with no ``--fleet-*`` flags the heartbeat
never starts, deadlines are "block forever" (legacy semantics), and
single-process runs skip every barrier.
"""
from __future__ import annotations

import dataclasses
import json
import os
import signal
import socket
import subprocess
import sys
import tempfile
import threading
import time
from typing import Callable, List, Optional, Sequence, Tuple

from g2vec_tpu.resilience.faults import ENV_PLAN, ENV_STATE, fault_point

_ENV_PID = "G2VEC_PROCESS_ID"


class PeerTimeoutError(RuntimeError):
    """A watched collective missed its deadline; ``suspects`` holds the
    rank(s) that never arrived (empty when attribution was impossible).
    RuntimeError on purpose: the supervisor classifies it retryable —
    peer death is preemption-shaped, not config-shaped."""

    def __init__(self, message: str, *, collective: Optional[str] = None,
                 suspects: Tuple[int, ...] = ()):
        super().__init__(message)
        self.collective = collective
        self.suspects = suspects


@dataclasses.dataclass
class FleetConfig:
    """Process-wide fleet-resilience knobs (all off by default)."""

    liveness_dir: Optional[str] = None
    heartbeat_interval: float = 0.0   # seconds; 0 = no heartbeat thread
    watchdog_deadline: float = 0.0    # seconds; 0 = block (legacy semantics)
    straggler_factor: float = 0.0     # x median; 0 = no straggler warnings


_config = FleetConfig()
_heartbeat: Optional["Heartbeat"] = None


def configure(cfg: Optional[FleetConfig] = None, **kwargs) -> FleetConfig:
    """Install the process fleet config (pipeline.run calls this per run)."""
    global _config
    _config = dataclasses.replace(cfg or FleetConfig(), **kwargs)
    return _config


def config() -> FleetConfig:
    return _config


def _rank() -> int:
    pid = os.environ.get(_ENV_PID)
    if pid is not None:
        try:
            return int(pid)
        except ValueError:
            pass
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_index())
        except Exception:  # noqa: BLE001
            return 0
    return 0


def _nranks() -> int:
    jax = sys.modules.get("jax")
    if jax is not None:
        try:
            return int(jax.process_count())
        except Exception:  # noqa: BLE001
            pass
    nproc = os.environ.get("G2VEC_NUM_PROCESSES")
    return int(nproc) if nproc and nproc.isdigit() else 1


def liveness_path(directory: str, rank: int) -> str:
    return os.path.join(directory, f"rank_{rank}.json")


def read_liveness(directory: str, rank: int) -> Optional[dict]:
    try:
        with open(liveness_path(directory, rank)) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def describe_ranks(ranks: Sequence[int],
                   directory: Optional[str] = None) -> str:
    """Human-readable liveness detail for suspect ranks — distinguishes a
    dead host (stale/absent heartbeat) from a live straggler. Empty string
    when no liveness dir is configured."""
    directory = directory or _config.liveness_dir
    if not directory:
        return ""
    now = time.time()
    bits = []
    for r in ranks:
        rec = read_liveness(directory, r)
        if rec is None:
            bits.append(f"rank {r}: no liveness record")
            continue
        age = now - float(rec.get("ts", 0.0))
        state = ("heartbeat stale" if age > _stale_after() else
                 "heartbeat fresh — live straggler?")
        bits.append(f"rank {r}: {state} (last beat {age:.1f}s ago, "
                    f"phase={rec.get('phase')!r}, last collective="
                    f"{rec.get('collective')!r} seq {rec.get('collective_seq')})")
    return " [" + "; ".join(bits) + "]"


def _stale_after() -> float:
    # Three missed beats = dead, with a floor for coarse intervals.
    return max(3.0 * (_config.heartbeat_interval or 1.0), 5.0)


class Heartbeat:
    """Per-process liveness beacon (daemon thread).

    Each beat: (1) passes the ``heartbeat`` fault seam — an injected crash
    there kills only the thread, modelling a host whose monitoring died
    before the host did; (2) atomically rewrites this rank's liveness file;
    (3) emits a ``heartbeat`` event into the provided MetricsWriter (the
    run's ``--metrics-jsonl`` stream; no-op writer on non-coordinator
    ranks, whose liveness lives in the file).
    """

    def __init__(self, directory: str, interval: float, metrics=None,
                 rank: Optional[int] = None):
        self.directory = directory
        self.interval = interval
        self.metrics = metrics
        self.rank = _rank() if rank is None else rank
        self.beats = 0
        self.phase = "start"
        self.collective: Optional[str] = None
        self.collective_seq: Optional[int] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- called from the main thread --
    def start(self) -> "Heartbeat":
        os.makedirs(self.directory, exist_ok=True)
        self.beat()                      # liveness exists before any wait
        self._thread = threading.Thread(
            target=self._loop, name=f"g2vec-heartbeat-{self.rank}",
            daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=max(1.0, 2 * self.interval))
            self._thread = None

    def note(self, phase: str) -> None:
        self.phase = phase

    def note_collective(self, name: str, seq: int) -> None:
        self.collective, self.collective_seq = name, seq
        self.beat()      # peers must see the entry record immediately

    def beat(self) -> None:
        record = {"rank": self.rank, "pid": os.getpid(), "ts": time.time(),
                  "beats": self.beats, "phase": self.phase,
                  "collective": self.collective,
                  "collective_seq": self.collective_seq,
                  "interval": self.interval}
        path = liveness_path(self.directory, self.rank)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump(record, f)
        os.replace(tmp, path)
        if self.metrics is not None:
            self.metrics.emit("heartbeat", **{k: v for k, v in record.items()
                                              if k != "ts"})
        self.beats += 1

    # -- thread body --
    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            try:
                fault_point("heartbeat")
                self.beat()
            except Exception:  # noqa: BLE001 — beats stop, process lives
                # The injected (or real) failure mode is "monitoring died":
                # the thread exits, the liveness file goes stale, and peers
                # start attributing timeouts to this rank.
                return


def start_heartbeat(metrics=None) -> Optional[Heartbeat]:
    """Start the process heartbeat per the installed config (None when
    disabled). Replaces any previous instance (supervised re-entry)."""
    global _heartbeat
    stop_heartbeat()
    if not _config.liveness_dir or _config.heartbeat_interval <= 0:
        return None
    _heartbeat = Heartbeat(_config.liveness_dir,
                           _config.heartbeat_interval, metrics).start()
    return _heartbeat


def stop_heartbeat() -> None:
    global _heartbeat
    if _heartbeat is not None:
        _heartbeat.stop()
        _heartbeat = None


def current_heartbeat() -> Optional[Heartbeat]:
    return _heartbeat


def note_phase(phase: str) -> None:
    if _heartbeat is not None:
        _heartbeat.note(phase)


def note_collective(name: str, seq: int) -> None:
    """Record (in this rank's liveness file) that it entered a collective —
    the attribution record watchdogs on OTHER ranks read on timeout."""
    if _heartbeat is not None:
        _heartbeat.note_collective(name, seq)


def collective_watchdog(name: str, fn: Callable[[], object], *,
                        deadline: Optional[float] = None):
    """Run a blocking collective under a timeout.

    For collectives that cannot themselves time out (XLA collectives via
    multihost_utils: they block inside the runtime until every participant
    arrives). ``fn`` runs in a sibling thread; if it misses the deadline,
    blame is attributed from the peers' liveness files and
    :class:`PeerTimeoutError` is raised in the caller. The abandoned
    thread keeps blocking harmlessly — the caller's process is about to be
    torn down by the supervisor anyway (nothing else can release a
    half-entered XLA collective).

    ``deadline=None`` uses the configured ``watchdog_deadline``; 0 runs
    ``fn`` inline (legacy block-forever semantics).
    """
    deadline = _config.watchdog_deadline if deadline is None else deadline
    seq = -1
    hb = _heartbeat
    if hb is not None:
        seq = (hb.collective_seq or 0) + 1
        hb.note_collective(name, seq)
    if not deadline:
        return fn()
    result: dict = {}
    done = threading.Event()

    def _run():
        try:
            result["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — re-raised in caller
            result["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=_run, name=f"g2vec-collective-{name}",
                         daemon=True)
    t.start()
    if not done.wait(deadline):
        suspects = _liveness_suspects(name, seq)
        raise PeerTimeoutError(
            f"collective {name!r} exceeded its {deadline:.1f}s watchdog "
            f"deadline; suspect rank(s): {list(suspects) or 'unknown'}"
            + describe_ranks(suspects),
            collective=name, suspects=suspects)
    if "error" in result:
        raise result["error"]
    return result.get("value")


def _liveness_suspects(name: str, seq: int) -> Tuple[int, ...]:
    """Ranks that (per their liveness files) never reached collective
    ``(name, seq)`` or whose heartbeat went stale."""
    directory = _config.liveness_dir
    if not directory or seq < 0:
        return ()
    me, now, suspects = _rank(), time.time(), []
    for peer in range(_nranks()):
        if peer == me:
            continue
        rec = read_liveness(directory, peer)
        if rec is None:
            suspects.append(peer)
            continue
        stale = (now - float(rec.get("ts", 0.0))) > _stale_after()
        behind = (rec.get("collective_seq") is None
                  or int(rec["collective_seq"]) < seq
                  or (int(rec["collective_seq"]) == seq
                      and rec.get("collective") != name))
        if stale or behind:
            suspects.append(peer)
    return tuple(suspects)


def stage_barrier(stage: str, seconds: float, metrics=None,
                  console: Optional[Callable[[str], None]] = None) -> None:
    """Per-stage fleet barrier + straggler detector. COLLECTIVE (all ranks,
    same stage order); no-op single-process or when the fleet config
    enables neither the watchdog nor straggler detection.

    Allgathers every rank's stage duration under the watchdog deadline —
    so a rank that died mid-stage surfaces HERE as a PeerTimeoutError
    naming it — and flags ranks slower than ``straggler_factor`` x the
    median duration with a ``straggler_warning`` event.
    """
    if _nranks() <= 1:
        return
    if not (_config.watchdog_deadline or _config.straggler_factor):
        return
    import numpy as np

    from g2vec_tpu.parallel import hostcomm

    fault_point("stage_barrier")
    note_phase(f"barrier:{stage}")
    durations = hostcomm.allgather_array(
        f"stage/{stage}", np.asarray([seconds], dtype=np.float64),
        deadline=_config.watchdog_deadline or None).reshape(-1)
    if not _config.straggler_factor:
        return
    median = float(np.median(durations))
    threshold = max(_config.straggler_factor * median, median + 0.05)
    for peer, dur in enumerate(durations):
        if float(dur) > threshold:
            if metrics is not None:
                metrics.emit("straggler_warning", stage=stage, rank=peer,
                             seconds=round(float(dur), 4),
                             median_seconds=round(median, 4),
                             factor=_config.straggler_factor)
            if console is not None:
                console(f"[fleet] straggler warning: rank {peer} took "
                        f"{float(dur):.2f}s in stage {stage!r} "
                        f"(median {median:.2f}s)")


# --------------------------------------------------------------- mesh plan

def plan_mesh(n_devices: int, prefer_model: int = 1) -> Tuple[int, int]:
    """Largest valid ``(data, model)`` factorization of ``n_devices``.

    The model axis is kept as large as possible without exceeding the
    preferred (pre-degradation) model size — gene shards may merge when a
    host dies, never grow — and the data axis takes everything else, so
    the factorization ``data * model == n_devices`` always holds.
    """
    if n_devices < 1:
        raise ValueError(f"cannot plan a mesh over {n_devices} devices")
    prefer_model = max(1, prefer_model)
    model = max(d for d in range(1, min(prefer_model, n_devices) + 1)
                if n_devices % d == 0)
    return (n_devices // model, model)


# ------------------------------------------------------- fleet supervisor

def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _scrub_fleet_argv(argv: List[str]) -> List[str]:
    """Child argv: the original CLI minus the launcher-only flags (fleet
    sizing, supervision, fault plan — the plan travels via env so fired
    state survives relaunches) and minus --mesh/--resume, which the
    launcher re-plans per attempt."""
    launcher_flags = ("--fleet-size", "--fleet-devices-per-rank",
                      "--supervise-retries", "--supervise-backoff",
                      "--fault-plan", "--mesh")
    out, skip = [], False
    for tok in argv:
        if skip:
            skip = False
            continue
        if tok in ("--supervise", "--resume"):
            continue
        if tok in launcher_flags:
            skip = True
            continue
        if any(tok.startswith(f + "=") for f in launcher_flags):
            continue
        out.append(tok)
    return out


def _tail(path: str, n: int = 2000) -> str:
    try:
        with open(path, "rb") as f:
            f.seek(0, os.SEEK_END)
            f.seek(max(0, f.tell() - n))
            return f.read().decode("utf-8", "replace")
    except OSError:
        return ""


def supervise_fleet(cfg, argv: List[str],
                    sleep: Callable[[float], None] = time.sleep) -> int:
    """Launch and supervise a ``--fleet-size`` multi-process run with
    degraded-mesh resume. Returns the exit code for the shell.

    Each attempt: spawn one ``python -m g2vec_tpu`` child per rank with the
    coordination-service env (coordinator address, rank, world size) and —
    on CPU — per-rank virtual devices. On failure, ranks that died by
    signal or were wedged (still running after the grace period) are
    dropped; the mesh is re-planned over the survivors' devices
    (:func:`plan_mesh`), and the fleet relaunches with ``--resume``.
    Requires ``--checkpoint-layout sharded`` for resume (config.validate
    enforces it): survivors reshard the orbax leaves onto the new mesh at
    load.
    """
    from g2vec_tpu.resilience.supervisor import (RetryPolicy, _event_writer,
                                                 classify_child)
    import random

    ranks = cfg.fleet_size
    mesh = cfg.mesh_shape or (ranks * max(1, cfg.fleet_devices_per_rank or 1), 1)
    devices_per_rank = cfg.fleet_devices_per_rank or \
        max(1, (mesh[0] * mesh[1]) // ranks)
    policy = RetryPolicy(max_retries=cfg.supervise_retries,
                         backoff_base=cfg.supervise_backoff)
    rng = random.Random(cfg.seed)
    base_argv = _scrub_fleet_argv(list(argv))
    liveness = cfg.fleet_liveness_dir or tempfile.mkdtemp(
        prefix="g2vec-fleet-liveness-")
    if not cfg.fleet_liveness_dir \
            and "--fleet-liveness-dir" not in " ".join(base_argv):
        base_argv += ["--fleet-liveness-dir", liveness]
    state_dir = tempfile.mkdtemp(prefix="g2vec-fleet-fault-state-")
    attempt = 0
    resume = bool(cfg.resume)
    while True:
        port = _free_port()
        log_dir = os.path.join(liveness, f"logs-attempt{attempt}")
        os.makedirs(log_dir, exist_ok=True)
        with _event_writer(cfg) as events:
            events.emit("fleet_launch", attempt=attempt, ranks=ranks,
                        mesh=list(mesh), devices_per_rank=devices_per_rank,
                        resume=resume)
        procs: List[subprocess.Popen] = []
        errs: List[str] = []
        handles: List = []
        for r in range(ranks):
            env = dict(os.environ)
            env["G2VEC_COORDINATOR"] = f"127.0.0.1:{port}"
            env["G2VEC_PROCESS_ID"] = str(r)
            env["G2VEC_NUM_PROCESSES"] = str(ranks)
            if cfg.fault_plan:
                env[ENV_PLAN] = cfg.fault_plan
                # Per-rank fired-state files: a once-only fault on rank 0
                # must not be suppressed because rank 1 fired its own copy.
                env[ENV_STATE] = os.path.join(state_dir, f"rank{r}.json")
            if (cfg.platform or "cpu") == "cpu":
                flags = [f for f in env.get("XLA_FLAGS", "").split()
                         if "xla_force_host_platform_device_count" not in f]
                env["XLA_FLAGS"] = " ".join(
                    flags + ["--xla_force_host_platform_device_count="
                             f"{devices_per_rank}"])
            cmd = [sys.executable, "-m", "g2vec_tpu", *base_argv,
                   "--distributed", "--mesh", f"{mesh[0]}x{mesh[1]}"]
            if resume:
                cmd.append("--resume")
            err_path = os.path.join(log_dir, f"rank{r}.err")
            errs.append(err_path)
            out_f = open(os.path.join(log_dir, f"rank{r}.out"), "w")
            err_f = open(err_path, "w")
            handles += [out_f, err_f]
            procs.append(subprocess.Popen(cmd, env=env, stdout=out_f,
                                          stderr=err_f))
        # ---- watch the attempt ----
        failed = False
        while True:
            codes = [p.poll() for p in procs]
            if any(c is not None and c != 0 for c in codes):
                failed = True
                break
            if all(c == 0 for c in codes):
                break
            sleep(0.1)
        wedged: List[int] = []
        died: List[int] = []
        if failed:
            # Grace: peers of the first casualty usually exit on their own
            # with a PeerTimeoutError; give them one watchdog window.
            grace = (cfg.fleet_watchdog_deadline or 5.0) + 5.0
            t_end = time.monotonic() + grace
            while time.monotonic() < t_end \
                    and any(p.poll() is None for p in procs):
                sleep(0.1)
            for r, p in enumerate(procs):
                if p.poll() is None:
                    wedged.append(r)
                    p.send_signal(signal.SIGTERM)
            for p in procs:
                try:
                    p.wait(timeout=10)
                except subprocess.TimeoutExpired:
                    p.kill()
                    p.wait()
            died = [r for r, p in enumerate(procs)
                    if p.returncode is not None and p.returncode < 0
                    and r not in wedged]
        for h in handles:
            h.close()
        if not failed:
            with _event_writer(cfg) as events:
                events.emit("fleet_done", attempts=attempt + 1, ranks=ranks,
                            mesh=list(mesh))
            return 0
        # ---- classify + replan ----
        tails = {r: _tail(e) for r, e in enumerate(errs)}
        for r, t in tails.items():
            if procs[r].returncode != 0 and t:
                sys.stderr.write(f"[fleet] rank {r} "
                                 f"(rc={procs[r].returncode}) stderr tail:\n"
                                 f"{t[-1200:]}\n")
        lost = sorted(set(died) | set(wedged))
        survivors = [r for r in range(ranks) if r not in lost]
        verdicts = [classify_child(procs[r].returncode or 1, tails.get(r, ""))
                    for r in range(ranks) if procs[r].returncode != 0]
        verdict = "fatal" if "fatal" in verdicts else "retryable"
        rcs = {r: procs[r].returncode for r in range(ranks)}
        with _event_writer(cfg) as events:
            events.emit("fleet_peer_death", attempt=attempt,
                        dead_ranks=lost, wedged_ranks=wedged,
                        returncodes={str(k): v for k, v in rcs.items()},
                        classified=verdict)
        if verdict == "fatal" or attempt >= policy.max_retries \
                or not survivors:
            with _event_writer(cfg) as events:
                events.emit("gave_up", attempt=attempt, classified=verdict,
                            error=f"fleet ranks failed: rcs={rcs}")
            print(f"[fleet] giving up after attempt {attempt}: {verdict} — "
                  f"rcs={rcs}", file=sys.stderr)
            bad = [rc for rc in rcs.values() if rc and rc > 0]
            return bad[0] if bad else 1
        new_ranks = len(survivors) if lost else ranks
        new_devices = new_ranks * devices_per_rank
        new_mesh = plan_mesh(new_devices, prefer_model=mesh[1])
        delay = policy.delay(attempt, rng)
        with _event_writer(cfg) as events:
            events.emit("fleet_replan", attempt=attempt,
                        surviving_ranks=new_ranks,
                        surviving_devices=new_devices,
                        old_mesh=list(mesh), new_mesh=list(new_mesh),
                        delay_seconds=round(delay, 3))
        print(f"[fleet] attempt {attempt} lost rank(s) {lost or '(none)'}; "
              f"re-planning mesh {mesh[0]}x{mesh[1]} -> "
              f"{new_mesh[0]}x{new_mesh[1]} over {new_ranks} rank(s); "
              f"relaunching with --resume in {delay:.1f}s", file=sys.stderr)
        sleep(delay)
        attempt += 1
        # Stale liveness from dropped ranks must not poison the next
        # attempt's suspect attribution (survivors renumber 0..n-1).
        for r in range(new_ranks, ranks):
            try:
                os.unlink(liveness_path(liveness, r))
            except OSError:
                pass
        ranks, mesh = new_ranks, new_mesh
        resume = bool(cfg.checkpoint_dir)
