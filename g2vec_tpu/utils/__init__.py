"""Observability utilities: structured metrics, stage timing, profiling.

The reference's only observability is ``print`` (SURVEY.md §5) — its console
transcript (README.md:21-49) is the de-facto golden spec, reproduced by
:mod:`g2vec_tpu.pipeline`. This package adds what the reference lacks:
JSONL metrics, per-stage wall timing, and ``jax.profiler`` trace capture.
"""
from g2vec_tpu.utils.metrics import MetricsWriter  # noqa: F401
from g2vec_tpu.utils.timing import StageTimer  # noqa: F401
