"""The declared schema for every MetricsWriter event kind.

The metrics JSONL is an API surface: the chaos-soak accountant sums
``job_done`` events, the router's dashboards pivot on ``failover``
latencies, and tests assert field presence. Emission, though, is
stringly typed — so this module pins, per event kind, which fields
every emission MUST carry (``required``) and which any emission MAY
carry (``optional``). The ``metrics-schema`` checker
(analyze/events.py) lints every literal ``.emit("<kind>", ...)`` site
in g2vec_tpu/ and tools/ against this table; adding an event kind or a
field means adding it HERE in the same commit, which is exactly the
reviewable drift signal dashboards need.

Conventions:

- Fields injected by the BoundMetrics facade (``job``, ``lane``) and
  by MetricsWriter itself (``ts``, ``seq``, ``event``) are not listed:
  they are structural, not per-site.
- Kinds emitted through a ``**fields`` splat (``config``, ``stream``,
  ``done`` extras, ``job_state`` info) declare only the literal kwargs
  their sites pass; the splat contents are deliberately open — the
  checker skips missing-field enforcement at splat sites but still
  rejects unknown literal kwargs.
- This dict is read by ``ast.literal_eval`` in the checker (never
  imported), so it must stay a pure literal.
"""

EVENT_SCHEMAS = {
    'ann_build': {
        "required": ['bundle', 'nlist', 'outcome'],
        "optional": ['error', 'ms', 'postings', 'seeded']},
    'auth_rejected': {
        "required": ['op'],
        "optional": []},
    'batch_config': {
        "required": ['batch_serial', 'lanes_cap', 'n_lanes', 'variants'],
        "optional": []},
    'batch_start': {
        "required": ['batch', 'jobs', 'n_lanes'],
        "optional": []},
    'batch_walks': {
        "required": [],
        "optional": ['lane_walks', 'n_walk_tasks']},
    'config': {
        "required": [],
        "optional": []},
    'delta_walk': {
        "required": ['group', 'outcome'],
        "optional": ['bundle', 'cache_hits', 'job_id', 'ranges_rewalked',
                     'ranges_total', 'walked_rows']},
    'device_walk': {
        "required": ['feed_mode', 'h2d_bytes_saved', 'paths_per_s'],
        "optional": ['device_recomputes', 'shards']},
    'done': {
        "required": [],
        "optional": ['acc_val', 'buckets', 'n_lanes', 'n_paths', 'outputs', 'overlap_saved_s', 'runs_per_hour', 'sampler_threads', 'stage_extras', 'stage_seconds', 'stop_epoch', 'stop_epochs', 'stream_totals', 'train_mode', 'walk_cache_hits', 'walk_stats', 'walker_backend', 'wall_seconds']},
    'drain_begin': {
        "required": ['queued', 'running', 'source'],
        "optional": []},
    'edge_partition': {
        "required": ['csr_bytes', 'gene_hi', 'gene_lo', 'mode', 'n_ranks', 'owned_edges', 'rank'],
        "optional": []},
    'epoch': {
        "required": ['acc_tr', 'acc_val', 'secs', 'step'],
        "optional": []},
    'failover': {
        "required": ['deduped', 'from_replica', 'job_id', 'latency_s', 'to_replica'],
        "optional": []},
    'failover_deferred': {
        "required": ['from_replica', 'job_id'],
        "optional": []},
    'failover_error': {
        "required": ['error', 'from_replica', 'job_id', 'to_replica'],
        "optional": []},
    'failover_reconciled': {
        "required": ['from_replica', 'job_id'],
        "optional": ['already_on']},
    'fenced': {
        "required": ['epoch', 'replica'],
        "optional": []},
    'fleet_done': {
        "required": ['attempts', 'mesh', 'ranks'],
        "optional": []},
    'fleet_launch': {
        "required": ['attempt', 'devices_per_rank', 'mesh', 'ranks', 'resume'],
        "optional": []},
    'fleet_peer_death': {
        "required": ['attempt', 'classified', 'dead_ranks', 'returncodes', 'wedged_ranks'],
        "optional": []},
    'fleet_replan': {
        "required": ['attempt', 'delay_seconds', 'new_mesh', 'old_mesh', 'surviving_devices', 'surviving_ranks'],
        "optional": []},
    'fquery': {
        "required": ['fq', 'ms'],
        "optional": ['bundles', 'error', 'replica_down', 'served_by']},
    'gave_up': {
        "required": ['attempt', 'classified', 'error'],
        "optional": []},
    'halo': {
        "required": ['halo_bytes', 'halo_edges', 'halo_genes', 'overhead_ratio'],
        "optional": []},
    'handoff': {
        "required": ['batches', 'mode', 'peak_in_flight', 'rounds', 'shards', 'states_sent'],
        "optional": []},
    'heartbeat': {
        "required": [],
        "optional": []},
    'inventory': {
        "required": ['bundle', 'bytes', 'outcome'],
        "optional": ['error', 'generation']},
    'job_accepted': {
        "required": ['n_lanes', 'priority', 'queued', 'tenant'],
        "optional": []},
    'job_cancel_requested': {
        "required": [],
        "optional": []},
    'job_deduped': {
        "required": ['tenant'],
        "optional": []},
    'job_done': {
        "required": ['batch', 'joined_jobs', 'latency_seconds', 'tenant'],
        "optional": []},
    'job_failed': {
        "required": ['classified', 'error'],
        "optional": []},
    'job_recovered_complete': {
        "required": [],
        "optional": []},
    'job_rejected': {
        "required": ['error'],
        "optional": ['detail', 'tenant']},
    'job_requeued': {
        "required": ['tenant'],
        "optional": []},
    'job_retry': {
        "required": ['attempt', 'error'],
        "optional": []},
    'job_routed': {
        "required": ['deduped', 'job_id', 'replica'],
        "optional": []},
    'job_state': {
        "required": [],
        "optional": ['state']},
    'lane_variant': {
        "required": [],
        "optional": []},
    'leader_elected': {
        "required": ['epoch', 'holder'],
        "optional": ['standby', 'takeover_s']},
    'paths': {
        "required": ['n_path_genes', 'n_paths', 'sampler_threads', 'walker_backend'],
        "optional": ['walk_cache_hits']},
    'preprocess': {
        "required": ['n_edges', 'n_genes', 'n_samples'],
        "optional": []},
    'quarantine': {
        "required": ['epoch'],
        "optional": ['parked']},
    'query': {
        "required": ['cache', 'ms', 'q'],
        "optional": ['bundle', 'error', 'mode', 'nprobe', 'recall_mode',
                     'served_by']},
    'replica_adopted': {
        "required": ['journal_depth', 'pid', 'replica'],
        "optional": []},
    'replica_drained': {
        "required": ['rc', 'replica'],
        "optional": []},
    'replica_health': {
        "required": ['from_state', 'journal_depth', 'replica', 'to_state'],
        "optional": []},
    'replica_relaunch_failed': {
        "required": ['error', 'replica'],
        "optional": []},
    'replica_relaunched': {
        "required": ['replica'],
        "optional": []},
    'replicate': {
        "required": ['acc_val', 'index', 'n_selected', 'name'],
        "optional": []},
    'republish': {
        "required": ['bundle', 'bytes', 'generation', 'mode'],
        "optional": []},
    'resume': {
        "required": ['attempt', 'checkpoint_dir'],
        "optional": []},
    'retry': {
        "required": ['attempt', 'classified', 'delay_seconds', 'error'],
        "optional": []},
    'router_start': {
        "required": ['listen', 'pid', 'replicas'],
        "optional": []},
    'router_stop': {
        "required": ['failovers', 'jobs_routed'],
        "optional": []},
    'scale_down': {
        "required": ['active', 'replica'],
        "optional": ['rc']},
    'scale_up': {
        "required": ['active', 'from_warm', 'reaction_s', 'replica'],
        "optional": []},
    'scenario': {
        "required": ['n_variants', 'scenario', 'scenario_id', 'scenario_seed', 'via'],
        "optional": ['folds', 'replicates']},
    'scheduler_error': {
        "required": ['error'],
        "optional": []},
    'serve_relaunch': {
        "required": ['attempt', 'classified', 'delay_seconds', 'error'],
        "optional": []},
    'serve_start': {
        "required": ['listen', 'pid', 'queued', 'socket', 'state_dir'],
        "optional": []},
    'serve_stop': {
        "required": ['jobs_done', 'jobs_failed', 'queued'],
        "optional": []},
    'serve_supervised_done': {
        "required": ['attempts'],
        "optional": []},
    'shed': {
        "required": ['est_wait_s', 'retry_after_s', 'tenant'],
        "optional": []},
    'stability': {
        "required": ['n_genes', 'output', 'scenario_id'],
        "optional": ['acc_mean', 'ci_hi', 'ci_lo', 'columns', 'n_replicates']},
    'stale_epoch': {
        "required": ['got_epoch', 'op', 'seen_epoch'],
        "optional": ['replica', 'side']},
    'straggler_warning': {
        "required": ['factor', 'median_seconds', 'rank', 'seconds', 'stage'],
        "optional": []},
    'stream': {
        "required": [],
        "optional": []},
    'submit_retry_later': {
        "required": ['job_id', 'journal_owner'],
        "optional": []},
    'supervised_done': {
        "required": ['attempts'],
        "optional": []},
    'tenant_quota': {
        "required": ['retry_after_s', 'tenant'],
        "optional": []},
    'train_done': {
        "required": ['acc_tr', 'acc_val', 'stop_epoch', 'stopped_early'],
        "optional": ['bucket', 'bucket_mode']},
    'update': {
        "required": ['bundle', 'generation', 'job_id'],
        "optional": ['cache_hits', 'carried_rows', 'epochs', 'mode',
                     'n_genes', 'prior_generation', 'ranges_rewalked',
                     'ranges_total', 'stop_epoch', 'walked_rows',
                     'wall_s']},
    'update_retry_later': {
        "required": ['bundle_owner', 'job_id'],
        "optional": []},
    'walk_cache': {
        "required": ['group', 'outcome'],
        "optional": ['n_rows']},
    'warm_spare': {
        "required": ['outcome', 'replica'],
        "optional": ['error', 'warmup_s']},
}
