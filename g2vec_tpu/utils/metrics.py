"""Structured JSONL metrics (the reference has none — SURVEY.md §5).

One JSON object per line, each stamped with wall time and a monotonically
increasing sequence number, so post-hoc tooling can reconstruct the run
without parsing the console transcript.
"""
from __future__ import annotations

import json
import threading
import time
from typing import IO, Optional


class MetricsWriter:
    """Append-only JSONL metrics sink; no-op when constructed with None.

    ``append=True`` continues an existing stream instead of truncating it —
    resumed runs and the resilience supervisor use it so the events of all
    attempts (config/epoch records, ``retry``/``resume``/``gave_up``) form
    one chronological stream per file.

    Thread-safe: the fleet heartbeat thread (resilience/fleet.py) emits
    into the same writer the pipeline's main thread uses; a lock keeps
    every JSONL line whole and the sequence numbers strictly increasing.
    """

    def __init__(self, path: Optional[str], append: bool = False):
        mode = "a" if append else "w"
        self._fout: Optional[IO[str]] = open(path, mode) if path else None
        self._seq = 0
        self._lock = threading.Lock()

    def emit(self, event: str, **fields) -> None:
        if self._fout is None:
            return
        with self._lock:
            if self._fout is None:
                return
            record = {"seq": self._seq, "ts": time.time(), "event": event,
                      **fields}
            self._fout.write(json.dumps(record) + "\n")
            self._fout.flush()
            self._seq += 1

    def close(self) -> None:
        with self._lock:
            if self._fout is not None:
                self._fout.close()
                self._fout = None

    def bind_lane(self, lane: str) -> "BoundMetrics":
        """A view of this writer that stamps every event with ``lane`` —
        the batch engine gives each manifest lane one, so B interleaving
        runs stay per-run parseable inside ONE chronological JSONL stream
        (filter on the ``lane`` field; events without it are batch-scoped).
        ``lane`` is '<manifest index>:<variant fingerprint>'."""
        return BoundMetrics(self, lane=lane)

    def bind_job(self, job_id: str) -> "BoundMetrics":
        """A view stamping every event with ``job_id`` — the serve daemon
        binds one per admitted job, so the lanes of interleaved (and
        bucket-joined) jobs inside ONE daemon stream stay attributable to
        the job that submitted them. Chains with :meth:`bind_lane`:
        ``writer.bind_job(j).bind_lane(l)`` stamps both fields."""
        return BoundMetrics(self, job_id=job_id)

    def __enter__(self) -> "MetricsWriter":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


class BoundMetrics:
    """A field-stamping emit() facade over a shared :class:`MetricsWriter`.

    Deliberately NOT a subclass and NOT closable: the engine (or the serve
    daemon) owns the writer's lifecycle; bound views only decorate events.
    Views chain — ``bind_job(...).bind_lane(...)`` — each returning a new
    view with the union of stamped fields. Thread-safety is the writer's
    (views may emit from overlap-pool threads).
    """

    def __init__(self, writer: MetricsWriter, **fields):
        self._writer = writer
        self._fields = fields

    @property
    def lane(self) -> Optional[str]:
        return self._fields.get("lane")

    @property
    def job_id(self) -> Optional[str]:
        return self._fields.get("job_id")

    def bind_lane(self, lane: str) -> "BoundMetrics":
        return BoundMetrics(self._writer, **{**self._fields, "lane": lane})

    def bind_job(self, job_id: str) -> "BoundMetrics":
        return BoundMetrics(self._writer,
                            **{**self._fields, "job_id": job_id})

    def emit(self, event: str, **fields) -> None:
        self._writer.emit(event, **{**self._fields, **fields})


#: Back-compat name: lane-bound views predate the job dimension.
LaneMetrics = BoundMetrics
