"""Content-hash / atomic-write primitives shared by every integrity layer.

One home for the sha256-manifest machinery: the checkpoint manifests
(train/checkpoint.py) and the walk-artifact cache (g2vec_tpu/cache.py)
verify bytes the same way, and the cache must be importable with NO jax
in the process (bench.py's host-only child), so these helpers cannot live
in checkpoint.py (which imports jax at module scope).
"""
from __future__ import annotations

import hashlib
import json
import os

import numpy as np


def sha256_file(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 20), b""):
            h.update(chunk)
    return h.hexdigest()


def sha256_array(arr: np.ndarray) -> str:
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def write_json_atomic(path: str, payload: dict) -> None:
    """tmp + rename so a torn write never leaves a half-JSON behind."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1)
        f.write("\n")
    os.replace(tmp, path)
