"""Per-stage wall-clock timing.

The reference only times training epochs (``time.time()`` deltas,
ref: G2Vec.py:260-272). This timer covers every pipeline stage and feeds both
the metrics JSONL and the end-of-run summary.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Tuple


class StageTimer:
    """Records (stage, seconds) pairs in order of completion.

    Under overlapped execution (parallel/overlap.py) a stage's seconds
    alone no longer say HOW it got that fast — stage 3 may have run on N
    sampler threads, stage 4's compile may have been warmed elsewhere, a
    cache hit may have skipped the walks outright. :meth:`annotate`
    attaches those attribution facts to a stage; they ride the ``done``
    metrics event as ``stage_extras`` beside ``stage_seconds``.
    """

    def __init__(self) -> None:
        self.stages: List[Tuple[str, float]] = []
        self.extras: Dict[str, Dict] = {}

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.stages.append((name, time.perf_counter() - t0))

    def annotate(self, name: str, **extras) -> None:
        """Attach attribution facts (backend, thread count, cache hits,
        overlap savings) to ``name``'s record."""
        self.extras.setdefault(name, {}).update(extras)

    def as_dict(self) -> Dict[str, float]:
        return dict(self.stages)

    def extras_dict(self) -> Dict[str, Dict]:
        return {k: dict(v) for k, v in self.extras.items()}

    @property
    def total(self) -> float:
        return sum(s for _, s in self.stages)
