"""Per-stage wall-clock timing.

The reference only times training epochs (``time.time()`` deltas,
ref: G2Vec.py:260-272). This timer covers every pipeline stage and feeds both
the metrics JSONL and the end-of-run summary.
"""
from __future__ import annotations

import contextlib
import time
from typing import Dict, Iterator, List, Tuple


class StageTimer:
    """Records (stage, seconds) pairs in order of completion."""

    def __init__(self) -> None:
        self.stages: List[Tuple[str, float]] = []

    @contextlib.contextmanager
    def stage(self, name: str) -> Iterator[None]:
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.stages.append((name, time.perf_counter() - t0))

    def as_dict(self) -> Dict[str, float]:
        return dict(self.stages)

    @property
    def total(self) -> float:
        return sum(s for _, s in self.stages)
