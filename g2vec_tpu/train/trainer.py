"""The modified-CBOW trainer (ref: compute_genetovec, G2Vec.py:217-286).

Reference behavior, reproduced exactly (SURVEY.md §7 quirk (c)):

- shuffled 80/20 hold-out (ref: G2Vec.py:219-226) — here with a seeded PRNG
  (the reference is unseeded);
- full-batch training: the whole train split in every optimizer step
  (ref: G2Vec.py:264);
- Adam with TF1 defaults (b1=0.9, b2=0.999, eps=1e-8; ref: G2Vec.py:246);
- after each step, val and train accuracies are evaluated with the UPDATED
  weights (ref: G2Vec.py:264-267);
- early stop on the FIRST strict decrease of val accuracy, returning the
  PREVIOUS epoch's embedding table (the reference fetches W_ih every epoch at
  G2Vec.py:283, after the break check, so on stop the previous epoch's value
  survives);
- ``--epoch`` caps the loop (the reference parses but ignores it,
  hardcoding 500 — SURVEY.md §7 quirk (b); we honor it).

TPU design vs the reference: the TF1 version re-feeds the full dense path
matrix host->runtime three times per epoch through ``feed_dict``
(~1.3 GB/epoch at example scale, ref: G2Vec.py:264-267) and pulls the whole
W_ih back every epoch (G2Vec.py:283). Here the path matrix and parameters are
device-resident; one jit-compiled epoch function performs step + both evals,
and exactly two scalars cross to the host per epoch. The previous-epoch
snapshot is a device-side reference (params are immutable pytrees — keeping
the old one costs nothing and no transfer happens until training ends).
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from g2vec_tpu.models.cbow import CBOWParams, forward, init_params
from g2vec_tpu.parallel.mesh import MeshContext, make_mesh_context


@dataclasses.dataclass
class TrainResult:
    w_ih: np.ndarray            # [n_genes, hidden] float32 — the embeddings
    stop_epoch: int             # reported stop epoch (reference convention)
    stopped_early: bool
    acc_val: float              # accuracy pair at the reported epoch
    acc_tr: float
    history: List[dict]         # per-epoch {epoch, acc_val, acc_tr, loss, secs}
    params: Optional[CBOWParams] = None  # device params (for checkpointing)


def _make_epoch_fn(tx: optax.GradientTransformation, compute_dtype,
                   decision_threshold: float, ctx: MeshContext):
    logit_threshold = float(np.log(decision_threshold / (1.0 - decision_threshold)))

    # ``w`` is a [batch, 1] 1/0 mask: 1 for real rows, 0 for shard-even
    # padding rows (see train_cbow). Weighted means make the padded program
    # numerically identical to the unpadded one.
    def loss_fn(params, x, y, w):
        logits = forward(params, x, compute_dtype)
        logits = ctx.constrain(logits, ctx.label_spec)
        bce = optax.sigmoid_binary_cross_entropy(logits, y)
        return jnp.sum(bce * w) / jnp.sum(w)

    def accuracy(params, x, y, w):
        logits = forward(params, x, compute_dtype)
        pred = (logits > logit_threshold).astype(jnp.float32)
        return jnp.sum((pred == y).astype(jnp.float32) * w) / jnp.sum(w)

    def epoch(params, opt_state, xtr, ytr, wtr, xval, yval, wval):
        loss, grads = jax.value_and_grad(loss_fn)(params, xtr, ytr, wtr)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if ctx.mesh is not None:
            params = CBOWParams(
                w_ih=ctx.constrain(params.w_ih, ctx.w_ih_spec),
                w_ho=ctx.constrain(params.w_ho, ctx.w_ho_spec))
        acc_val = accuracy(params, xval, yval, wval)
        acc_tr = accuracy(params, xtr, ytr, wtr)
        return params, opt_state, acc_val, acc_tr, loss

    return jax.jit(epoch)


def _pad_rows(arr: np.ndarray, n_rows: int) -> np.ndarray:
    """Zero-pad axis 0 to exactly n_rows."""
    if arr.shape[0] == n_rows:
        return arr
    pad = np.zeros((n_rows - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def train_cbow(paths: np.ndarray, labels: np.ndarray, *,
               hidden: int, learning_rate: float, max_epochs: int,
               val_fraction: float = 0.2, decision_threshold: float = 0.5,
               compute_dtype: str = "bfloat16", param_dtype: str = "float32",
               seed: int = 0, mesh_ctx: Optional[MeshContext] = None,
               on_epoch: Optional[Callable[[int, float, float, float], None]] = None,
               checkpoint_dir: Optional[str] = None, resume: bool = False,
               checkpoint_every: int = 25,
               ) -> TrainResult:
    """Train the modified CBOW; returns the embedding table and history.

    ``paths``: [n_paths, n_genes] multi-hot (any integer/float dtype);
    ``labels``: [n_paths] in {0, 1}. ``on_epoch(step, acc_val, acc_tr, secs)``
    fires every epoch so the CLI can render the reference's log cadence.
    """
    if paths.shape[0] < 2:
        raise ValueError(f"need at least 2 paths to split, got {paths.shape[0]}")
    ctx = mesh_ctx if mesh_ctx is not None else make_mesh_context(None)
    cdtype = jnp.bfloat16 if compute_dtype == "bfloat16" else jnp.float32
    pdtype = jnp.float32 if param_dtype == "float32" else jnp.bfloat16
    n_paths, n_genes = paths.shape

    # ---- shuffled hold-out split (ref: G2Vec.py:219-226) ----
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_paths)
    pivot = int(n_paths * (1.0 - val_fraction))
    if pivot in (0, n_paths):
        raise ValueError(
            f"val_fraction={val_fraction} leaves an empty split for {n_paths} paths")
    tr_idx, vl_idx = perm[:pivot], perm[pivot:]

    # ---- shard-even padding (SPMD needs dims divisible by mesh axes) ----
    # Rows pad to a multiple of the data axis, the gene axis to a multiple of
    # the model axis. Padding rows carry weight 0 (masked means above);
    # padding gene columns are all-zero in X, so the matching W_ih rows get
    # exactly zero gradient and are sliced off before returning.
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    if ctx.mesh is not None:
        from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        data_dim = ctx.mesh.shape[DATA_AXIS]
        model_dim = ctx.mesh.shape[MODEL_AXIS]
    else:
        data_dim = model_dim = 1
    n_genes_pad = pad_to_multiple(n_genes, model_dim)

    def _prep(idx):
        # Keep the multi-hot in its narrow integer dtype through slicing and
        # padding; cast to the compute dtype once, at device-put time.
        x = paths[idx]
        y = labels[idx].astype(np.float32).reshape(-1, 1)
        n_pad = pad_to_multiple(x.shape[0], data_dim)
        w = _pad_rows(np.ones((x.shape[0], 1), np.float32), n_pad)
        x = _pad_rows(x, n_pad)
        if n_genes_pad != n_genes:
            x = np.concatenate(
                [x, np.zeros((x.shape[0], n_genes_pad - n_genes), x.dtype)], axis=1)
        return (ctx.put(x.astype(np.dtype(cdtype)), ctx.batch_spec),
                ctx.put(_pad_rows(y, n_pad), ctx.label_spec),
                ctx.put(w, ctx.label_spec))

    xtr, ytr, wtr = _prep(tr_idx)
    xval, yval, wval = _prep(vl_idx)

    # ---- params + optimizer ----
    key = jax.random.key(seed)
    params = init_params(key, n_genes_pad, hidden, param_dtype=pdtype)
    if ctx.mesh is not None:
        params = CBOWParams(w_ih=ctx.put(params.w_ih, ctx.w_ih_spec),
                            w_ho=ctx.put(params.w_ho, ctx.w_ho_spec))
    tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
    opt_state = tx.init(params)
    epoch_fn = _make_epoch_fn(tx, cdtype, decision_threshold, ctx)

    # ---- epoch loop with first-val-dip early stopping ----
    history: List[dict] = []
    before_val, before_tr = -1.0, -1.0
    snapshot = params            # device-side reference, no copy
    start_epoch = 0
    stopped_early = False
    stop_epoch = max_epochs - 1
    if checkpoint_dir and resume:
        from g2vec_tpu.train.checkpoint import (RUN_EARLY_STOPPED,
                                                RUN_IN_PROGRESS, load_state)

        restored = load_state(checkpoint_dir, params, opt_state)
        if restored is not None:
            (params, opt_state, snapshot, last_epoch,
             before_val, before_tr, done) = restored
            if ctx.mesh is not None:
                # Restored leaves are host arrays; re-apply the DP/TP
                # shardings the fresh-init path declares, or the resumed
                # program compiles with replicated (possibly OOM-ing) params.
                # Classification is by tree position (CBOWParams containers
                # inside params/opt_state/snapshot), never by shape — shapes
                # are ambiguous when hidden == n_genes_pad.
                from jax.sharding import PartitionSpec as P

                def _reshard_params(p: CBOWParams) -> CBOWParams:
                    return CBOWParams(
                        w_ih=ctx.put(np.asarray(p.w_ih), ctx.w_ih_spec),
                        w_ho=ctx.put(np.asarray(p.w_ho), ctx.w_ho_spec))

                params = _reshard_params(params)
                snapshot = _reshard_params(snapshot)
                opt_state = jax.tree.map(
                    lambda sub: (_reshard_params(sub)
                                 if isinstance(sub, CBOWParams)
                                 else ctx.put(np.asarray(sub), P())),
                    opt_state,
                    is_leaf=lambda x: isinstance(x, CBOWParams))
            if (done == RUN_EARLY_STOPPED
                    or (done != RUN_IN_PROGRESS and last_epoch + 1 >= max_epochs)):
                # Terminal state: an early stop is final (stepping on would
                # re-apply the dip epoch's update — the saved params are
                # post-dip, the snapshot pre-dip), and a completed run with
                # no additional epoch budget has nothing to do. A completed
                # run CAN continue when max_epochs was raised.
                w_ih = np.asarray(jax.device_get(snapshot.w_ih),
                                  dtype=np.float32)[:n_genes]
                return TrainResult(
                    w_ih=w_ih, stop_epoch=last_epoch,
                    stopped_early=(done == RUN_EARLY_STOPPED),
                    acc_val=before_val, acc_tr=before_tr,
                    history=[], params=snapshot)
            start_epoch = last_epoch + 1
    t0 = time.time()
    for step in range(start_epoch, max_epochs):
        params, opt_state, acc_val, acc_tr, loss = epoch_fn(
            params, opt_state, xtr, ytr, wtr, xval, yval, wval)
        av, at = float(acc_val), float(acc_tr)   # the only host syncs
        secs = time.time() - t0
        t0 = time.time()
        history.append({"epoch": step, "acc_val": av, "acc_tr": at,
                        "loss": float(loss), "secs": secs})
        if on_epoch is not None:
            on_epoch(step, av, at, secs)
        if av < before_val:                      # first strict decrease
            stopped_early = True
            stop_epoch = step - 1
            break
        before_val, before_tr = av, at
        snapshot = params                        # params AFTER this epoch's step
        if checkpoint_dir and (step + 1) % checkpoint_every == 0:
            from g2vec_tpu.train.checkpoint import save_state

            save_state(checkpoint_dir, params, opt_state, snapshot,
                       step, before_val, before_tr)

    if checkpoint_dir:
        from g2vec_tpu.train.checkpoint import (RUN_COMPLETED,
                                                RUN_EARLY_STOPPED, save_state)

        save_state(checkpoint_dir, params, opt_state, snapshot,
                   stop_epoch if stopped_early else max_epochs - 1,
                   before_val, before_tr,
                   done=RUN_EARLY_STOPPED if stopped_early else RUN_COMPLETED)
    w_ih = np.asarray(jax.device_get(snapshot.w_ih), dtype=np.float32)[:n_genes]
    return TrainResult(w_ih=w_ih, stop_epoch=stop_epoch,
                       stopped_early=stopped_early,
                       acc_val=before_val, acc_tr=before_tr,
                       history=history, params=snapshot)
