"""The modified-CBOW trainer (ref: compute_genetovec, G2Vec.py:217-286).

Reference behavior, reproduced exactly (SURVEY.md §7 quirk (c)):

- shuffled 80/20 hold-out (ref: G2Vec.py:219-226) — here with a seeded PRNG
  (the reference is unseeded);
- full-batch training: the whole train split in every optimizer step
  (ref: G2Vec.py:264);
- Adam with TF1 defaults (b1=0.9, b2=0.999, eps=1e-8; ref: G2Vec.py:246);
- after each step, val and train accuracies are evaluated with the UPDATED
  weights (ref: G2Vec.py:264-267);
- early stop on the FIRST strict decrease of val accuracy, returning the
  PREVIOUS epoch's embedding table (the reference fetches W_ih every epoch at
  G2Vec.py:283, after the break check, so on stop the previous epoch's value
  survives);
- ``--epoch`` caps the loop (the reference parses but ignores it,
  hardcoding 500 — SURVEY.md §7 quirk (b); we honor it).

TPU design vs the reference: the TF1 version re-feeds the full dense path
matrix host->runtime three times per epoch through ``feed_dict``
(~1.3 GB/epoch at example scale, ref: G2Vec.py:264-267) and pulls the whole
W_ih back every epoch (G2Vec.py:283). Here the path matrix and parameters
are device-resident, epochs run in device-side chunks of DEFAULT_CHUNK
inside a ``lax.while_loop`` (the early-stop comparison included), and the
host sees one small (state, per-epoch history) transfer per chunk — on a
tunneled TPU that round trip dwarfs the epoch math, so it must be
amortized. The previous-epoch snapshot stays on device (a per-epoch
``jnp.where`` select of the param tree; W_ih only crosses to the host once,
after training). On a single chip the X@W_ih matmuls run through the fused
bit-packed Pallas kernel (ops/packed_matmul.py) so X stays packed in HBM.

The eval FOLDS: the reference re-runs a full train-split forward per
epoch just to report ACC[tr] at the updated weights — but those weights
are exactly the next epoch's entry weights, so that forward is recomputed
verbatim by the next epoch's gradient pass. The chunk body reads the
previous epoch's ACC[tr] out of its own grad forward (``has_aux``) and a
single per-chunk eval backfills the last epoch's; per-epoch train-split
matmul passes drop 3 -> 2 (~31% of epoch FLOPs at the 80/20 split). The
FUSED-EVAL mode (default; --no-fused-eval restores the shipping shape)
extends the same argument to the val split: the val eval rides the SAME
program as the train grad pass — on the packed path the val rows join
the train rows' single kernel launch — so the standalone per-epoch val
program disappears too. One fused program per epoch, with epoch i's
val/train accuracies read out of epoch i+1's entry forward and the
early-stop dip test run there, before epoch i+1's update is applied (see
_make_chunk_fn). Parity contract, float32, measured and test-pinned
(tests/test_trainer_modes.py): every accuracy, every early-stop
decision, and the epoch count are BITWISE the shipping loop's — the
accuracy arithmetic is exact 0/1 counting, immune to scheduling — while
losses and the final embeddings may sit within ~2 ulp on XLA:CPU,
because the fused body is a DIFFERENT program and XLA decides fma
contraction per program (same jaxpr, different codegen; barriers and
hand-pinned Adam arithmetic were both tried and do not close it — the
drift enters through the grad gemm's context). The packed kernel's
forward is M-invariant by construction (fixed per-row-tile fori
accumulation), so the production TPU path does not even pay that. The
superstep and donation modes are fully bitwise vs shipping: selects and
buffer renaming do not touch the arithmetic (pinned across a shape
battery).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from g2vec_tpu.models.cbow import (CBOWParams, accuracy_from_logits,
                                   forward, init_params, masked_bce_loss,
                                   output_logits)
from g2vec_tpu.ops import packed_matmul as pm
from g2vec_tpu.parallel.mesh import MeshContext, make_mesh_context


# Epochs executed per device dispatch when not checkpointing. The host round
# trip to a tunneled TPU is ~90 ms; the epoch math at example scale is ~7 ms
# (BENCH_r02), so syncing every epoch would be >10x overhead and even 64
# epochs/chunk left ~1.4 ms/epoch of sync in the measured steady state. 128
# amortizes the round trip to ~0.7 ms/epoch; the early stop still exits ON
# the dip (the device while_loop tests it every epoch), so a bigger chunk
# wastes no compute — it only coarsens the history delivery cadence.
DEFAULT_CHUNK = 128


def _default_backend() -> str:
    """``jax.default_backend()`` that degrades instead of raising.

    With a dead TPU tunnel the backend query itself raises RuntimeError
    (round-1 postmortem: bench.py died here before any useful error). The
    caller only uses this to pick the Pallas fast path, so "unknown" simply
    means "not tpu" — the subsequent device use will produce the real error
    with full context if the backend is truly gone.
    """
    try:
        return jax.default_backend()
    except RuntimeError:
        return "unknown"


@dataclasses.dataclass
class TrainResult:
    w_ih: np.ndarray            # [n_genes, hidden] float32 — the embeddings
    stop_epoch: int             # reported stop epoch (reference convention)
    stopped_early: bool
    acc_val: float              # accuracy pair at the reported epoch
    acc_tr: float
    history: List[dict]         # per-epoch {epoch, acc_val, acc_tr, loss, secs}
    params: Optional[CBOWParams] = None  # device params (for checkpointing)


def _tree_select(pred, on_true, on_false):
    """Elementwise ``jnp.where`` over a whole pytree (scalar predicate)."""
    return jax.tree.map(lambda a, b: jnp.where(pred, a, b),
                        on_true, on_false)


def _ensure_optbar_batching() -> None:
    """Register a vmap batching rule for ``lax.optimization_barrier``.

    The pinned jax 0.4.x ships none, and the lane-batched trainer vmaps
    the fused chunk program — whose bitwise-parity contract rests on
    exactly that barrier (the split views must stay opaque per lane, the
    same isolation the solo program gets). The correct rule is the
    identity one: barrier the batched operands as-is and pass the batch
    dims through — an optimization barrier constrains scheduling, not
    values, so batching it over a leading axis barriers a superset of
    what the per-example programs barrier. No-op when a newer jax already
    registered one.
    """
    try:
        from jax._src.lax import lax as _lax_internal
        from jax.interpreters import batching

        p = _lax_internal.optimization_barrier_p
        if p in batching.primitive_batchers:
            return

        def rule(args, dims):
            return p.bind(*args), dims

        batching.primitive_batchers[p] = rule
    except Exception:  # noqa: BLE001 — private API; a drifted jax that
        pass           # still lacks the rule fails loudly at vmap time


#: Adam hyperparameters, TF1 defaults (ref: G2Vec.py:246). Fixed for the
#: whole repo; only the learning rate is configurable.
_ADAM_B1, _ADAM_B2, _ADAM_EPS = 0.9, 0.999, 1e-8


def _make_chunk_fn(learning_rate: float, compute_dtype,
                   decision_threshold: float, ctx: MeshContext, chunk: int,
                   packed: bool = False, interpret: bool = False,
                   fused: bool = True, superstep: int = 1,
                   donate: bool = True, lanes: int = 0):
    """Compile a device-resident loop over up to ``chunk`` epochs.

    The reference syncs with the host three times per epoch (optimizer run +
    two accuracy evals through feed_dict, ref: G2Vec.py:264-267). A naive JAX
    port still syncs once per epoch to test the early-stop condition — and on
    a remote TPU that round trip (~90 ms over the tunnel) dwarfs the ~15 ms
    of epoch math. So the early-stop comparison itself lives on device inside
    a ``lax.while_loop``; the host sees one transfer of (state, per-epoch
    accuracy history) per ``chunk`` epochs, and the loop exits on the first
    val-accuracy dip no matter where in the chunk it falls.

    Three orthogonal modes, all parity-pinned against the shipping loop
    (float32 — tests/test_trainer_modes.py; superstep/donate bitwise,
    fused bitwise on accuracies/decisions with losses and params within
    ~2 ulp on XLA:CPU — the module docstring has the full contract):

    - ``fused`` (the fused-eval fold): the val split rides the SAME
      program as the train grad pass — a single [tr|val] kernel launch on
      the packed path, per-split gemms inside the one program on the XLA
      path (the bitwise contract note at run_chunk_fused explains the
      asymmetry) — and epoch i's val/train accuracies are read out of
      epoch i+1's entry forward (entry params of epoch i+1 ARE epoch i's
      post-update params). One fused program per epoch instead of grad +
      standalone val eval; a single per-chunk boundary eval backfills the
      final epoch's pair and runs its dip test. Data signature:
      (xall, ytr, wtr, yval, wval).
    - ``superstep`` K: the while_loop body executes K epochs per
      iteration (Python-unrolled), each masked by the live
      ``i < limit & ~stopped`` predicate, so the loop's per-iteration
      dispatch/cond overhead amortizes over K epochs. ``jnp.where`` with a
      true predicate is the identity, so active epochs compute exactly
      the K=1 program's values; the early stop still lands ON the dip
      (post-dip epochs in the same superstep are select-masked out, at
      most K-1 wasted epoch computes on the final iteration).
    - ``donate``: the (params, opt_state, snapshot, hist) carry buffers
      are donated to the chunk program, so Adam's fp32 read/write set
      updates in place instead of double-buffering in HBM
      (jit(..., donate_argnums=(0, 1, 2, 3))).
    """
    logit_threshold = float(np.log(decision_threshold / (1.0 - decision_threshold)))

    if packed:
        # Pallas path: ``x`` is the bit-packed [rows, n_genes/8] uint8 matrix
        # in pack_blockwise layout; the fused kernel unpacks tiles in VMEM
        # (ops/packed_matmul.py) — 16x less HBM traffic than a dense bf16 X.
        # Under a data-parallel mesh the kernel runs per row shard inside
        # shard_map with W replicated; shard_map's transpose psums the
        # per-shard dW cotangents over 'data' automatically.
        def _packed_h(x, w_ih):
            if ctx.mesh is None:
                return pm.packed_matmul(x, w_ih, interpret)
            from jax.sharding import PartitionSpec as P

            from g2vec_tpu.parallel.mesh import shard_map

            return shard_map(
                lambda xs, w: pm.packed_matmul(xs, w, interpret),
                mesh=ctx.mesh,
                in_specs=(ctx.packed_batch_spec, P(None, None)),
                out_specs=ctx.hidden_spec,
                # pallas_call's out_shape carries no varying-axes info;
                # the specs above are the full contract.
                check_vma=False)(x, w_ih)

        def logits_fn(params, x):
            h = _packed_h(x, params.w_ih.astype(compute_dtype))
            return output_logits(h, params.w_ho, compute_dtype)
    else:
        def logits_fn(params, x):
            return forward(params, x, compute_dtype)

    # ``w`` is a [batch, 1] 1/0 mask: 1 for real rows, 0 for shard-even
    # padding rows (see train_cbow) — and, in the fused program, 0 for the
    # val rows riding the train forward. Weighted means make the masked
    # program numerically identical to the unmasked one.
    def loss_fn(params, x, y, w):
        logits = logits_fn(params, x)
        logits = ctx.constrain(logits, ctx.label_spec)
        return masked_bce_loss(logits, y, w), logits

    def acc_from_logits(logits, y, w):
        return accuracy_from_logits(logits, y, w, logit_threshold)

    def accuracy(params, x, y, w):
        return acc_from_logits(logits_fn(params, x), y, w)

    tx = optax.adam(learning_rate, b1=_ADAM_B1, b2=_ADAM_B2, eps=_ADAM_EPS)

    def adam_step(grads, opt_state, params):
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state

    def superstepped(cond, body):
        """Unroll ``superstep`` masked epochs into one while_loop body.

        Each unrolled step recomputes the live loop predicate from the
        CURRENT carry and select-masks the whole next carry with it:
        active steps are the identity on the K=1 program's values
        (jnp.where with a true scalar), epochs past ``limit`` or past a
        dip freeze the carry. K=1 returns ``body`` untouched — the
        shipping program, no extra selects.
        """
        if superstep <= 1:
            return body

        def k_body(carry):
            for _ in range(superstep):
                carry = _tree_select(cond(carry), body(carry), carry)
            return carry

        return k_body

    # Eval-train fold (the MFU work, VERDICT r3 task 4): the reference's
    # epoch runs THREE full train-split matmul passes — grad fwd, dW, and a
    # train-accuracy eval at the UPDATED weights (ref: G2Vec.py:264-267).
    # But epoch i's updated weights are exactly epoch i+1's entry weights,
    # so epoch i's train-accuracy logits are recomputed verbatim by epoch
    # i+1's grad forward. The body therefore reads acc_tr for epoch i-1
    # out of its own grad forward (has_aux) and backfills hist[i-1]; the
    # final executed epoch's acc_tr is computed once per CHUNK after the
    # loop. Per-epoch train-split passes drop 3 -> 2 (~31% of the epoch's
    # matmul FLOPs at the 80/20 split) with bit-identical history: same
    # kernel, same params, same inputs, just computed one body later.
    def epoch(params, opt_state, xtr, ytr, wtr, xval, yval, wval):
        (loss, logits_tr), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, xtr, ytr, wtr)
        acc_tr_prev = acc_from_logits(logits_tr, ytr, wtr)
        params, opt_state = adam_step(grads, opt_state, params)
        if ctx.mesh is not None:
            params = CBOWParams(
                w_ih=ctx.constrain(params.w_ih, ctx.w_ih_spec),
                w_ho=ctx.constrain(params.w_ho, ctx.w_ho_spec))
        # Val accuracy uses the UPDATED weights (ref: G2Vec.py:264-267).
        acc_val = accuracy(params, xval, yval, wval)
        return params, opt_state, acc_val, acc_tr_prev, loss

    def cond_of(limit):
        def cond(carry):
            _, _, _, _, _, i, stopped, _ = carry
            return jnp.logical_and(i < limit, jnp.logical_not(stopped))
        return cond

    def run_chunk(params, opt_state, snapshot, hist, before_val, before_tr,
                  limit, xtr, ytr, wtr, xval, yval, wval):
        # hist [chunk, 3] = [acc_val, acc_tr, loss]: a donated carry buffer
        # the host hands back each chunk. Rows are written before any read
        # the host performs (it slices [:count]), so it is never zeroed.
        cond = cond_of(limit)

        def body(carry):
            params, opt_state, snapshot, before_val, before_tr, i, _, hist = carry
            params, opt_state, acc_val, acc_tr_prev, loss = epoch(
                params, opt_state, xtr, ytr, wtr, xval, yval, wval)
            dip = acc_val < before_val        # first strict decrease → stop
            hist = hist.at[i].set(jnp.stack([acc_val, jnp.float32(0), loss]))
            # acc_tr_prev belongs to epoch i-1 (see the fold note above).
            # i == 0: the entry params' train accuracy was already recorded
            # by the previous chunk's post-loop backfill (or is the init
            # params' — never reported); keep hist[0] untouched then.
            prev = jnp.maximum(i - 1, 0)
            hist = hist.at[prev, 1].set(
                jnp.where(i > 0, acc_tr_prev, hist[prev, 1]))
            # Epoch i-1 completed without a dip (the loop ran body i), so
            # its acc_tr is the current "previous epoch" train accuracy.
            before_tr = jnp.where(i > 0, acc_tr_prev, before_tr)
            # On a dip the dip epoch's update is discarded: the snapshot and
            # best-acc pair keep their previous-epoch values (ref: the
            # fetch-after-break ordering at G2Vec.py:276-283).
            snapshot = jax.tree.map(
                lambda old, new: jnp.where(dip, old, new), snapshot, params)
            before_val = jnp.where(dip, before_val, acc_val)
            return (params, opt_state, snapshot, before_val, before_tr,
                    i + 1, dip, hist)

        init = (params, opt_state, snapshot,
                jnp.float32(before_val), jnp.float32(before_tr),
                jnp.int32(0), jnp.bool_(False), hist)
        (params, opt_state, snapshot, before_val, before_tr, count, dip,
         hist) = jax.lax.while_loop(cond, superstepped(cond, body), init)
        # Backfill the final executed epoch's acc_tr: one eval forward per
        # CHUNK (the fold's only residual cost), at that epoch's post-update
        # params — including a dip epoch's (whose update params still sit in
        # ``params`` even though the snapshot discarded them), exactly what
        # the unfused epoch reported.
        acc_tr_last = accuracy(params, xtr, ytr, wtr)
        last = jnp.maximum(count - 1, 0)
        hist = hist.at[last, 1].set(acc_tr_last)
        before_tr = jnp.where(dip, before_tr, acc_tr_last)
        return (params, opt_state, snapshot, before_val, before_tr, count,
                dip, hist)

    # ---- fused-eval chunk: one fused program per epoch --------------------
    # Epoch i's entry forward computes logits for BOTH splits at epoch
    # i-1's post-update params — exactly the values the reference reports
    # for epoch i-1 (ref: evals at the UPDATED weights, G2Vec.py:264-267).
    # The dip test for epoch i-1 therefore runs at the TOP of body i,
    # BEFORE update i is applied: on a dip, update i is select-discarded
    # (shipping never ran epoch i), params stay at the dip epoch's
    # post-update value (shipping applied the dip epoch's update to params
    # — only the snapshot excludes it), and the loop exits. The standalone
    # per-epoch val program disappears entirely.
    #
    # Bitwise contract, per kernel path:
    #
    # - PACKED (Pallas): the val rows ride the train rows' SINGLE kernel
    #   launch on the concatenated [tr|val] matrix. The kernel is
    #   M-invariant by construction — each row tile accumulates its gene
    #   chunks in a fixed fori order, independent of how many other row
    #   tiles the grid has — so the train rows' bits cannot change. The
    #   backward is sliced to the train rows via custom_vjp (jax.vjp on
    #   the shipping sub-program; the val logits feed only the
    #   non-differentiated accuracies, so they carry no cotangent).
    # - XLA (dense): the two splits are computed as per-split matmuls
    #   INSIDE the one fused program. A concatenated-contraction gemm is
    #   NOT row-stable on this path — XLA:CPU picks its K-blocking per
    #   shape, and appending val rows measurably drifts the train rows'
    #   low bits — while per-split shapes are exactly shipping's, so
    #   every value is bitwise shipping's. The fold still deletes the
    #   standalone eval program: one launch, one schedule.
    def _base_mm(x, w):
        if packed:
            return pm.packed_matmul(x, w.astype(compute_dtype), interpret)
        return jax.lax.dot_general(
            x.astype(compute_dtype), w.astype(compute_dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def _make_fused_mm(tr_rows: int):
        @jax.custom_vjp
        def mm(x, w):
            return _base_mm(x, w)

        def fwd(x, w):
            return _base_mm(x, w), (x, w)

        def bwd(res, dh):
            x, w = res
            _, vjp = jax.vjp(
                lambda ww: _base_mm(
                    jax.lax.slice_in_dim(x, 0, tr_rows), ww), w)
            (dw,) = vjp(jax.lax.slice_in_dim(dh, 0, tr_rows))
            # x is path data, never trained (ref: G2Vec.py:264): float0
            # for the integer packed rows, a dead zero tree otherwise.
            dx = (np.zeros(x.shape, dtype=jax.dtypes.float0) if packed
                  else jnp.zeros_like(x))
            return dx, dw

        mm.defvjp(fwd, bwd)
        return mm

    def run_chunk_fused(params, opt_state, snapshot, hist, before_val,
                        before_tr, limit, xall, ytr, wtr, yval, wval):
        cond = cond_of(limit)
        tr_rows = ytr.shape[0]          # static at trace time
        fused_mm = _make_fused_mm(tr_rows)

        def split_x():
            # Barrier-opaque split views: the slices become plain inputs
            # to the downstream graph, so the grad/eval subgraphs are the
            # SAME jaxpr as shipping's loss_fn/logits_fn on standalone
            # arrays — XLA cannot fold the concatenation context into
            # their arithmetic (the bitwise contract's load-bearing op on
            # the XLA path, where the gemm's compilation is not
            # row-stable under shape changes).
            x_tr = jax.lax.optimization_barrier(
                jax.lax.slice_in_dim(xall, 0, tr_rows))
            x_val = jax.lax.optimization_barrier(
                jax.lax.slice_in_dim(xall, tr_rows, xall.shape[0]))
            return x_tr, x_val

        def fused_loss(params):
            # Packed path only: one [tr|val] kernel launch (M-invariant
            # per row tile), backward sliced to the train rows.
            h_all = fused_mm(xall, params.w_ih.astype(compute_dtype))
            h_tr = jax.lax.slice_in_dim(h_all, 0, tr_rows)
            h_val = jax.lax.slice_in_dim(h_all, tr_rows, h_all.shape[0])
            logits_tr = output_logits(h_tr, params.w_ho, compute_dtype)
            logits_val = output_logits(h_val, params.w_ho, compute_dtype)
            return (masked_bce_loss(logits_tr, ytr, wtr),
                    (logits_tr, logits_val))

        def fused_epoch_forward(params):
            if packed:
                (loss, (logits_tr, logits_val)), grads = jax.value_and_grad(
                    fused_loss, has_aux=True)(params)
            else:
                # XLA path: differentiate EXACTLY shipping's loss_fn on
                # the barriered train slice; the val eval is the same
                # logits_fn forward, outside the autodiff graph, in the
                # same fused program.
                x_tr, x_val = split_x()
                (loss, logits_tr), grads = jax.value_and_grad(
                    loss_fn, has_aux=True)(params, x_tr, ytr, wtr)
                logits_val = logits_fn(params, x_val)
            return (loss, grads,
                    acc_from_logits(logits_val, yval, wval),
                    acc_from_logits(logits_tr, ytr, wtr))

        def split_logits(params, mm):
            # Boundary (non-differentiated) eval, same split rules.
            if packed:
                h_all = mm(xall, params.w_ih)
                h_tr = jax.lax.slice_in_dim(h_all, 0, tr_rows)
                h_val = jax.lax.slice_in_dim(h_all, tr_rows, h_all.shape[0])
                return (output_logits(h_tr, params.w_ho, compute_dtype),
                        output_logits(h_val, params.w_ho, compute_dtype))
            x_tr, x_val = split_x()
            return logits_fn(params, x_tr), logits_fn(params, x_val)

        def body(carry):
            (params, opt_state, snapshot, before_val, before_tr, i, _,
             hist) = carry
            loss, grads, acc_val_prev, acc_tr_prev = fused_epoch_forward(
                params)
            # i == 0: the entry params' accuracies were already reported
            # (and dip-tested) by the previous chunk's boundary eval — or
            # are the init params', never reported. Skip the test then.
            first = i == 0
            dip = jnp.logical_and(jnp.logical_not(first),
                                  acc_val_prev < before_val)
            prev = jnp.maximum(i - 1, 0)
            hist = hist.at[prev, 0].set(
                jnp.where(first, hist[prev, 0], acc_val_prev))
            hist = hist.at[prev, 1].set(
                jnp.where(first, hist[prev, 1], acc_tr_prev))
            # Epoch i-1 survived its dip test: accept its post-update
            # params (the CURRENT entry params) as the snapshot and its
            # accuracies as the best pair (ref: the fetch-after-break
            # ordering at G2Vec.py:276-283).
            keep = jnp.logical_or(first, dip)
            snapshot = _tree_select(keep, snapshot, params)
            before_val = jnp.where(keep, before_val, acc_val_prev)
            before_tr = jnp.where(keep, before_tr, acc_tr_prev)
            # Apply update i unless epoch i-1 just dipped (epoch i then
            # never happens; shipping's loop had already exited).
            new_params, new_opt = adam_step(grads, opt_state, params)
            if ctx.mesh is not None:
                new_params = CBOWParams(
                    w_ih=ctx.constrain(new_params.w_ih, ctx.w_ih_spec),
                    w_ho=ctx.constrain(new_params.w_ho, ctx.w_ho_spec))
            params = _tree_select(dip, params, new_params)
            opt_state = _tree_select(dip, opt_state, new_opt)
            # The loss belongs to epoch i (entry-params forward), exactly
            # the value shipping records from its grad pass — unless epoch
            # i never ran.
            hist = hist.at[i, 2].set(jnp.where(dip, hist[i, 2], loss))
            return (params, opt_state, snapshot, before_val, before_tr,
                    jnp.where(dip, i, i + 1), dip, hist)

        init = (params, opt_state, snapshot,
                jnp.float32(before_val), jnp.float32(before_tr),
                jnp.int32(0), jnp.bool_(False), hist)
        (params, opt_state, snapshot, before_val, before_tr, count, stopped,
         hist) = jax.lax.while_loop(cond, superstepped(cond, body), init)
        # Boundary eval: ONE fused forward per chunk backfills the final
        # executed epoch's accuracy pair and runs its dip test (the fold's
        # only residual cost — the next chunk's body 0 recomputes these
        # logits and discards them). Masked out when a mid-chunk dip
        # already closed the books, and on the limit=0 warm call.
        logits_tr_last, logits_val_last = split_logits(params, _base_mm)
        acc_val_last = acc_from_logits(logits_val_last, yval, wval)
        acc_tr_last = acc_from_logits(logits_tr_last, ytr, wtr)
        valid = jnp.logical_and(count > 0, jnp.logical_not(stopped))
        last = jnp.maximum(count - 1, 0)
        hist = hist.at[last, 0].set(
            jnp.where(valid, acc_val_last, hist[last, 0]))
        hist = hist.at[last, 1].set(
            jnp.where(valid, acc_tr_last, hist[last, 1]))
        dip = jnp.logical_and(valid, acc_val_last < before_val)
        accept = jnp.logical_and(valid, jnp.logical_not(dip))
        snapshot = _tree_select(accept, params, snapshot)
        before_val = jnp.where(accept, acc_val_last, before_val)
        before_tr = jnp.where(accept, acc_tr_last, before_tr)
        return (params, opt_state, snapshot, before_val, before_tr, count,
                jnp.logical_or(stopped, dip), hist)

    fn = run_chunk_fused if fused else run_chunk
    if lanes:
        _ensure_optbar_batching()
        # Lane batching (batch/engine.py): the SAME chunk program lifted
        # over a leading lane axis on every argument — params/opt-state
        # [B, ...], per-lane before/limit scalars [B], per-lane data
        # blocks. vmap's while_loop batching runs the loop while ANY
        # lane's cond holds and select-masks finished lanes' carries, so
        # a lane that early-stops mid-bucket freezes bitwise while its
        # peers keep training — the per-lane values are the solo
        # program's exactly (measured bitwise on XLA:CPU: batched
        # dot_general/reductions/scatters reproduce the per-example
        # programs bit-for-bit; tests/test_batch_engine.py pins it
        # end to end). A finished lane re-entering with limit=0 runs
        # zero epochs and, in the fused path, masks its boundary eval
        # with ``valid`` — the host keeps authoritative per-lane
        # (before_val, before_tr) either way.
        fn = jax.vmap(fn)
    return jax.jit(fn, donate_argnums=(0, 1, 2, 3) if donate else ())


# jit caches live on the function object, so the compiled chunk must be
# reused across train_cbow calls (a fresh closure per call would recompile
# the whole while_loop program every run — ~10 s at example scale). Both
# caches are TRUE LRUs (hits refresh recency, eviction drops the least
# recently USED entry): a long supervised run sweeping shapes/hyperparams
# must neither grow them without bound nor evict the entry it re-hits
# every retry just because it was inserted first.
_CHUNK_FN_CACHE: "OrderedDict" = OrderedDict()
_UNPACK_FN_CACHE: "OrderedDict" = OrderedDict()
_CHUNK_FN_CACHE_MAX = 16   # hyperparameter sweeps must not pin old executables
_UNPACK_FN_CACHE_MAX = 8   # keyed by (mesh, dtype) only — 8 is generous


_CACHE_LOCK = threading.Lock()
_CACHE_PENDING: dict = {}      # (cache id, key) -> Event for in-flight makes


def _lru_get(cache: "OrderedDict", key, limit: int, make):
    """Thread-safe bounded LRU lookup. A second requester of an in-flight
    key BLOCKS until the first finishes and then shares the same fn —
    the overlap scheduler warms the chunk fn in the background while the
    foreground trainer may request the identical key, and two distinct
    jitted wrappers would compile the same program twice."""
    from g2vec_tpu.cache import record_cache_event

    pending_key = (id(cache), key)
    while True:
        with _CACHE_LOCK:
            fn = cache.get(key)
            if fn is not None:
                cache.move_to_end(key)
                record_cache_event("compile", "program_hit")
                return fn
            ev = _CACHE_PENDING.get(pending_key)
            if ev is None:
                ev = threading.Event()
                _CACHE_PENDING[pending_key] = ev
                break
        ev.wait()
    try:
        record_cache_event("compile", "program_miss")
        fn = make()
        with _CACHE_LOCK:
            while len(cache) >= limit:
                cache.popitem(last=False)
            cache[key] = fn
        return fn
    finally:
        with _CACHE_LOCK:
            _CACHE_PENDING.pop(pending_key, None)
        ev.set()


def _get_chunk_fn(learning_rate: float, compute_dtype, decision_threshold: float,
                  ctx: MeshContext, chunk: int, packed: bool = False,
                  interpret: bool = False, fused: bool = True,
                  superstep: int = 1, donate: bool = True, lanes: int = 0):
    # A packed program embeds its kernel tile plan at trace time: key on
    # the autotuner's install counter so a re-tune compiles fresh tiles
    # instead of silently serving the stale executable.
    key = (learning_rate, jnp.dtype(compute_dtype).name, decision_threshold,
           ctx.mesh, chunk, packed, interpret, fused, superstep, donate,
           lanes, pm.tuned_token() if packed else 0)

    def make():
        return _make_chunk_fn(learning_rate, compute_dtype,
                              decision_threshold, ctx, chunk, packed,
                              interpret, fused, superstep, donate, lanes)

    return _lru_get(_CHUNK_FN_CACHE, key, _CHUNK_FN_CACHE_MAX, make)


def _make_stream_fns(learning_rate: float, compute_dtype,
                     decision_threshold: float, packed: bool,
                     interpret: bool):
    """The streaming trainer's two device programs (train/stream.py).

    ``update`` is ONE minibatch-SGD step on one walk shard — the
    matrix-multiply-shaped batch of arXiv:1611.06172: grad of the masked
    BCE over the shard's train rows, one Adam step. The loss is the
    masked MEAN over real rows (padding rows carry weight 0), so the
    update magnitude is invariant to shard padding and to the last
    partial shard's size — the per-batch weighting stays honest in the
    corrected-CBOW sense (arXiv:2012.15332): every context contributes
    equally to its batch's update regardless of batch geometry.
    ``evaluate`` is the shared accuracy forward for the held-out val /
    train-probe buffers at shard-epoch boundaries.

    Single-device by contract (config.py forbids streaming + --mesh);
    the ``packed`` path runs the same fused bit-packed Pallas kernel as
    the full-batch chunk program — shards stay bit-packed in HBM.
    ``update`` donates (params, opt_state) so Adam's state updates in
    place across the thousands of shard steps a big graph produces.
    """
    logit_threshold = float(np.log(decision_threshold
                                   / (1.0 - decision_threshold)))

    if packed:
        def logits_fn(params, x):
            h = pm.packed_matmul(x, params.w_ih.astype(compute_dtype),
                                 interpret)
            return output_logits(h, params.w_ho, compute_dtype)
    else:
        def logits_fn(params, x):
            return forward(params, x, compute_dtype)

    def loss_fn(params, x, y, w):
        return masked_bce_loss(logits_fn(params, x), y, w)

    tx = optax.adam(learning_rate, b1=_ADAM_B1, b2=_ADAM_B2, eps=_ADAM_EPS)

    def update(params, opt_state, x, y, w):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y, w)
        updates, opt_state = tx.update(grads, opt_state, params)
        return optax.apply_updates(params, updates), opt_state, loss

    def evaluate(params, x, y, w):
        return accuracy_from_logits(logits_fn(params, x), y, w,
                                    logit_threshold)

    return (jax.jit(update, donate_argnums=(0, 1)), jax.jit(evaluate))


_STREAM_FN_CACHE: "OrderedDict" = OrderedDict()
_STREAM_FN_CACHE_MAX = 8


def _get_stream_fns(learning_rate: float, compute_dtype,
                    decision_threshold: float, packed: bool = False,
                    interpret: bool = False):
    """LRU-cached (update, evaluate) pair — same reuse contract as
    :func:`_get_chunk_fn` (jit caches live on the function objects, so
    repeat streaming runs at one config must share them)."""
    key = (learning_rate, jnp.dtype(compute_dtype).name, decision_threshold,
           packed, interpret, pm.tuned_token() if packed else 0)

    def make():
        return _make_stream_fns(learning_rate, compute_dtype,
                                decision_threshold, packed, interpret)

    return _lru_get(_STREAM_FN_CACHE, key, _STREAM_FN_CACHE_MAX, make)


def _get_unpack_fn(ctx: MeshContext, compute_dtype):
    """[rows, n_bytes] uint8 -> [rows, n_bytes*8] compute-dtype multi-hot.

    The multi-hot path matrix crosses host->device as PACKED BITS (8 genes
    per byte, ~42 MB at example scale instead of 546 MB as bf16) and is
    expanded on device, where HBM bandwidth is ~800 GB/s. Bit order matches
    ``np.packbits`` (MSB first)."""
    key = (ctx.mesh, jnp.dtype(compute_dtype).name)

    def make():
        def unpack(packed):
            shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
            bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
            x = bits.reshape(packed.shape[0], -1).astype(compute_dtype)
            return ctx.constrain(x, ctx.batch_spec)
        return jax.jit(unpack)

    return _lru_get(_UNPACK_FN_CACHE, key, _UNPACK_FN_CACHE_MAX, make)


def _pad_rows(arr: np.ndarray, n_rows: int) -> np.ndarray:
    """Zero-pad axis 0 to exactly n_rows."""
    if arr.shape[0] == n_rows:
        return arr
    pad = np.zeros((n_rows - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


def _split_indices(n_paths: int, seed: int, val_fraction: float):
    """The shuffled 80/20 hold-out split (ref: G2Vec.py:219-226), seeded.

    ONE definition shared by :func:`train_cbow` and the lane-batched
    :func:`train_cbow_lanes` — a lane's split must be the byte-exact split
    the same seed produces solo."""
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_paths)
    pivot = int(n_paths * (1.0 - val_fraction))
    if pivot in (0, n_paths):
        raise ValueError(
            f"val_fraction={val_fraction} leaves an empty split for "
            f"{n_paths} paths")
    return perm[:pivot], perm[pivot:]


def _pack_split(paths: np.ndarray, labels: np.ndarray, idx: np.ndarray, *,
                packed_genes: Optional[int], n_genes: int, n_genes_pad: int,
                row_multiple: int, use_pallas: bool,
                row_bucket: int = 0):
    """Host-side packing of one split into the device layout.

    The multi-hot crosses the host->device boundary as packed bits
    (8 genes/byte) and — in the XLA path — is unpacked + cast on device: a
    ~13x smaller transfer than shipping bf16, and no host-side ml_dtypes
    cast of a third of a billion elements. In the pallas path it
    additionally STAYS packed in HBM. Shared verbatim by train_cbow and
    train_cbow_lanes (a lane's packed rows must be the solo run's bytes).
    """
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    n_rows = len(idx)
    y = labels[idx].astype(np.float32).reshape(-1, 1)
    n_pad = pad_to_multiple(n_rows, row_multiple)
    if row_bucket:
        # Round the padded row count up to a coarse bucket (itself kept
        # a multiple of row_multiple, so shard-evenness survives). The
        # extra rows are ordinary weight-0 padding; the win is shape
        # stability — successive fine-tunes whose unique-path counts
        # drift by a handful of rows land in the SAME bucket and reuse
        # the compiled train/eval programs instead of paying a fresh
        # XLA compile per update.
        bucket = pad_to_multiple(row_bucket, row_multiple)
        n_pad = pad_to_multiple(n_pad, bucket)
    w = _pad_rows(np.ones((n_rows, 1), np.float32), n_pad)
    # Repack row chunks into the device layout; host temp memory stays
    # bounded (one chunk of dense bools) even at pod-scale path counts.
    packed = np.zeros((n_pad, n_genes_pad // 8), dtype=np.uint8)
    if (packed_genes is not None and not use_pallas
            and paths.shape[1] == n_genes_pad // 8):
        # Input packbits layout == device layout (single-chip XLA path):
        # no bit round-trip at all, just a row gather.
        packed[:n_rows] = paths[idx]
    else:
        chunk_rows = 8192
        for lo in range(0, n_rows, chunk_rows):
            sel = idx[lo:lo + chunk_rows]
            if packed_genes is not None:
                rows = np.unpackbits(paths[sel], axis=1)[:, :n_genes] != 0
            else:
                rows = paths[sel] != 0
            # One zeroed buffer provides the gene padding.
            xb = np.zeros((len(sel), n_genes_pad), dtype=bool)
            xb[:, :n_genes] = rows
            packed[lo:lo + len(sel)] = (
                pm.pack_blockwise(xb) if use_pallas
                else np.packbits(xb, axis=1))
    return packed, _pad_rows(y, n_pad), w


_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}


def _autotune_packed_shapes(row_counts, n_genes_pad: int, hidden: int,
                            interpret: bool,
                            cache_path: Optional[str]) -> None:
    """Measure (or cache-load) packed-kernel tile plans for the trainer's
    exact matmul shapes. In-memory hits return without bumping the tuned
    token, so a foreground call after the overlap warm path is free."""
    for m in sorted(set(int(m) for m in row_counts)):
        pm.autotune_packed_matmul(m, n_genes_pad, hidden,
                                  interpret=interpret,
                                  cache_path=cache_path)


@dataclasses.dataclass(frozen=True)
class _Layout:
    """Everything about the device programs that follows from shapes alone.

    ONE derivation shared by :func:`train_cbow` and
    :func:`warm_train_compile` — the background compile warm is only a
    win if it compiles EXACTLY the program the real run then requests,
    so the kernel/padding decision must not be duplicated logic that can
    drift.
    """
    use_pallas: bool
    interpret: bool
    n_genes_pad: int
    row_multiple: int
    data_dim: int
    model_dim: int


def _plan_layout(n_paths: int, n_genes: int, hidden: int,
                 compute_dtype: str, ctx: MeshContext,
                 use_pallas: Optional[bool]) -> _Layout:
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    if ctx.mesh is not None:
        from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        data_dim = ctx.mesh.shape[DATA_AXIS]
        model_dim = ctx.mesh.shape[MODEL_AXIS]
    else:
        data_dim = model_dim = 1

    # Pallas fused packed-matmul path (ops/packed_matmul.py): single-chip,
    # bf16 compute, shapes within the kernel's VMEM budget. The multi-hot
    # stays BIT-PACKED in HBM (16x smaller than dense bf16) and is unpacked
    # tile-by-tile in VMEM fused into the MXU matmul. ``use_pallas=None``
    # auto-detects; True forces it (tests use interpret mode off-TPU).
    if use_pallas is None:
        use_pallas = (
            model_dim == 1 and compute_dtype == "bfloat16"
            and _default_backend() == "tpu"
            and pm.packed_matmul_available(
                n_paths, pad_to_multiple(n_genes, pm.LANE_BLOCK), hidden))
    elif use_pallas:
        # Forced on (tests / power users): enforce the same preconditions the
        # auto-detect checks, loudly — the kernel shards rows (DP), never the
        # gene axis, and computes in bf16.
        if model_dim != 1:
            raise ValueError(
                "use_pallas=True runs per row shard (data parallel); it "
                f"cannot gene-shard — use a Dx1 mesh, got model dim {model_dim}")
        if compute_dtype != "bfloat16":
            raise ValueError("use_pallas=True requires compute_dtype="
                             "'bfloat16' (the kernel computes in bf16)")
        if hidden % 128:
            raise ValueError(f"use_pallas=True requires hidden % 128 == 0, "
                             f"got {hidden}")
    interpret = bool(use_pallas) and _default_backend() != "tpu"

    if use_pallas:
        # Gene axis pads to the kernel's lane block; rows to a full row tile
        # on EVERY data shard.
        n_genes_pad = pad_to_multiple(n_genes, pm.LANE_BLOCK)
        row_multiple = pm.ROW_BLOCK * data_dim
    else:
        # Gene axis pads to a multiple of 8*model_dim so the PACKED byte
        # columns split evenly over the model axis and byte boundaries
        # coincide with shard boundaries.
        n_genes_pad = pad_to_multiple(n_genes, 8 * model_dim)
        row_multiple = data_dim
    return _Layout(bool(use_pallas), interpret, n_genes_pad, row_multiple,
                   data_dim, model_dim)


def train_cbow(paths: np.ndarray, labels: np.ndarray, *,
               hidden: int, learning_rate: float, max_epochs: int,
               val_fraction: float = 0.2, decision_threshold: float = 0.5,
               compute_dtype: str = "bfloat16", param_dtype: str = "float32",
               seed: int = 0, mesh_ctx: Optional[MeshContext] = None,
               on_epoch: Optional[Callable[[int, float, float, float], None]] = None,
               checkpoint_dir: Optional[str] = None, resume: bool = False,
               checkpoint_every: int = 25, use_pallas: Optional[bool] = None,
               packed_genes: Optional[int] = None,
               checkpoint_layout: str = "single",
               pre_compile_hook: Optional[Callable[[], None]] = None,
               fused_eval: bool = True, epoch_superstep: int = 1,
               donate: bool = True, kernel_autotune: bool = False,
               autotune_cache_path: Optional[str] = None,
               check: Optional[Callable[[], None]] = None,
               warm_start: Optional[tuple] = None,
               row_bucket: int = 0,
               ) -> TrainResult:
    """Train the modified CBOW; returns the embedding table and history.

    ``paths``: [n_paths, n_genes] multi-hot (any integer/float dtype) — or,
    with ``packed_genes=G``, the bit-packed [n_paths, ceil(G/8)] uint8 form
    (np.packbits layout, e.g. from ``integrate_path_sets(packed=True)``);
    the dense matrix is then never materialized whole on the host.
    ``labels``: [n_paths] in {0, 1}. ``on_epoch(step, acc_val, acc_tr, secs)``
    fires every epoch so the CLI can render the reference's log cadence.

    ``fused_eval``/``epoch_superstep``/``donate`` select the chunk-program
    variants documented at :func:`_make_chunk_fn`, parity-pinned against
    the shipping loop (module docstring has the float32 contract).
    ``kernel_autotune`` sweeps
    the packed kernel's tile plans at this run's exact shapes before the
    chunk program compiles (persisted under ``autotune_cache_path`` —
    cache.py's --cache-dir autotune tier — so repeat runs skip the sweep);
    it is a no-op on the XLA (non-Pallas) path.

    ``warm_start`` is the incremental-update plane's entry point: a
    ``(w_ih [n_genes, hidden], w_ho [hidden, 1])`` float array pair
    that REPLACES the seeded draw as the initial parameters (the
    caller owns the PR 4 init contract — incremental.py draws the full
    seeded init at the new gene count and overwrites carried-over rows
    with the prior bundle's embedding). Padding to the layout's
    ``n_genes_pad`` happens here with zero rows, exactly as
    ``init_params(pad_to=...)`` pads, so warm starts are as
    layout-independent as cold ones. Optimizer state is fresh (Adam
    moments restart — fine-tunes are short and the prior moments are
    not in the bundle).

    ``row_bucket`` (0 = off) rounds each split's padded row count up to
    a multiple of the bucket with ordinary weight-0 rows. The padding
    is inert (masked means, zero-weight eval) but pins the program
    shapes: repeated fine-tunes whose deduplicated path counts drift by
    a few rows hit the in-process compile cache instead of recompiling
    — the incremental update plane's per-update wall is dominated by
    exactly that recompile without it.
    """
    if paths.shape[0] < 2:
        raise ValueError(f"need at least 2 paths to split, got {paths.shape[0]}")
    if epoch_superstep < 1:
        raise ValueError(
            f"epoch_superstep must be >= 1, got {epoch_superstep}")
    ctx = mesh_ctx if mesh_ctx is not None else make_mesh_context(None)
    if compute_dtype not in _DTYPES:
        raise ValueError(
            f"compute_dtype must be one of {sorted(_DTYPES)}, got {compute_dtype!r}")
    if param_dtype not in _DTYPES:
        raise ValueError(
            f"param_dtype must be one of {sorted(_DTYPES)}, got {param_dtype!r}")
    cdtype = _DTYPES[compute_dtype]
    pdtype = _DTYPES[param_dtype]
    if packed_genes is not None:
        n_paths, nb_in = paths.shape
        n_genes = packed_genes
        if nb_in != (n_genes + 7) // 8 or paths.dtype != np.uint8:
            raise ValueError(
                f"packed_genes={n_genes} expects uint8 paths of width "
                f"{(n_genes + 7) // 8}, got {paths.dtype} width {nb_in}")
    else:
        n_paths, n_genes = paths.shape

    # ---- shuffled hold-out split (ref: G2Vec.py:219-226) ----
    tr_idx, vl_idx = _split_indices(n_paths, seed, val_fraction)

    # ---- shard-even padding (SPMD needs dims divisible by mesh axes) ----
    # Rows pad to a multiple of the data axis, the gene axis to a multiple of
    # the model axis. Padding rows carry weight 0 (masked means above);
    # padding gene columns are all-zero in X, so the matching W_ih rows get
    # exactly zero gradient and are sliced off before returning. The whole
    # kernel/padding decision lives in _plan_layout — shared with
    # warm_train_compile, which must predict this run's programs exactly.
    plan = _plan_layout(n_paths, n_genes, hidden, compute_dtype, ctx,
                        use_pallas)
    use_pallas = plan.use_pallas
    pallas_interpret = plan.interpret
    n_genes_pad = plan.n_genes_pad
    row_multiple = plan.row_multiple
    if not use_pallas:
        unpack_fn = _get_unpack_fn(ctx, cdtype)

    def _pack_host(idx):
        return _pack_split(paths, labels, idx, packed_genes=packed_genes,
                           n_genes=n_genes, n_genes_pad=n_genes_pad,
                           row_multiple=row_multiple, use_pallas=use_pallas,
                           row_bucket=row_bucket)

    def _put_x(packed_np):
        if use_pallas:
            return ctx.put(packed_np, ctx.packed_batch_spec)
        return unpack_fn(ctx.put(packed_np, ctx.batch_spec))

    ptr_np, ytr_np, wtr_np = _pack_host(tr_idx)
    pval_np, yval_np, wval_np = _pack_host(vl_idx)
    # Fused eval is a single-device program shape: the [tr|val] row
    # concatenation does not align with data-shard boundaries, so a mesh
    # run would reshard the hidden activations every epoch just to split
    # them back. Meshes keep the shipping split program (bitwise-identical
    # history in float32 anyway — the parity contract).
    fused = fused_eval and ctx.mesh is None
    if fused:
        # Fused-eval layout: ONE [tr_pad + val_pad] path matrix — the two
        # padded blocks concatenated, train rows keeping their exact
        # offsets (per-row forward results cannot regroup). Labels and
        # masks stay per-split: the chunk program slices the hidden
        # activations back apart, and the custom-vjp backward never sees
        # the val block at all.
        data = (_put_x(np.concatenate([ptr_np, pval_np], axis=0)),
                ctx.put(ytr_np, ctx.label_spec),
                ctx.put(wtr_np, ctx.label_spec),
                ctx.put(yval_np, ctx.label_spec),
                ctx.put(wval_np, ctx.label_spec))
    else:
        data = (_put_x(ptr_np), ctx.put(ytr_np, ctx.label_spec),
                ctx.put(wtr_np, ctx.label_spec),
                _put_x(pval_np), ctx.put(yval_np, ctx.label_spec),
                ctx.put(wval_np, ctx.label_spec))

    # ---- params + optimizer ----
    key = jax.random.key(seed)
    # pad_to: the draw covers the real genes only, so the same seed gives
    # the same trajectory under ANY layout's padding (pallas vs XLA, any
    # mesh shape) — the parity tests compare runs across layouts.
    params = init_params(key, n_genes, hidden, param_dtype=pdtype,
                         pad_to=n_genes_pad)
    if warm_start is not None:
        wi = np.asarray(warm_start[0], dtype=np.float32)
        wo = np.asarray(warm_start[1], dtype=np.float32).reshape(
            hidden, 1)
        if wi.shape != (n_genes, hidden):
            raise ValueError(
                f"warm_start w_ih {wi.shape} vs ({n_genes}, {hidden})")
        if n_genes_pad > n_genes:
            wi = np.concatenate(
                [wi, np.zeros((n_genes_pad - n_genes, hidden),
                              dtype=np.float32)], axis=0)
        params = CBOWParams(w_ih=jnp.asarray(wi, dtype=pdtype),
                            w_ho=jnp.asarray(wo, dtype=pdtype))
    if ctx.mesh is not None:
        params = CBOWParams(w_ih=ctx.put(params.w_ih, ctx.w_ih_spec),
                            w_ho=ctx.put(params.w_ho, ctx.w_ho_spec))
    # tx here only initializes the optimizer state; the cached chunk fn
    # builds an identical transformation from the same hyperparameters.
    tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
    opt_state = tx.init(params)
    if ctx.mesh is not None:
        # Adam's mu/nu inherit the params' shardings through tree_map, but
        # the step-count scalar lands on the default device. Replicate it
        # over the mesh NOW: jit would do so transparently, but a sharded
        # checkpoint restore uses this state as its sharding template, and
        # a single-device template forces an (unsupported on multi-host
        # CPU) cross-host transfer at resume.
        from jax.sharding import PartitionSpec as P

        opt_state = jax.tree.map(
            lambda sub: (sub if isinstance(sub, CBOWParams)
                         else ctx.put(sub, P())),
            opt_state, is_leaf=lambda x: isinstance(x, CBOWParams))
    # Epochs per device dispatch: align to the checkpoint cadence when
    # checkpointing (a chunk boundary is a save point), else amortize the
    # host round trip over DEFAULT_CHUNK epochs.
    chunk = checkpoint_every if checkpoint_dir else DEFAULT_CHUNK
    chunk = max(1, min(chunk, max_epochs))
    superstep = max(1, min(epoch_superstep, chunk))
    if pre_compile_hook is not None:
        # The overlap scheduler joins its background warm_train_compile
        # here — AFTER the host-side _prep packing it overlapped, right
        # before the chunk-fn request that wants the warmed executable.
        pre_compile_hook()
    if kernel_autotune and use_pallas:
        # Measure tile plans at THIS run's exact matmul shapes before the
        # chunk program traces (an install bumps pm.tuned_token(), which
        # the chunk-fn key embeds). When the overlap warm path already
        # swept these shapes, the in-memory hit returns without touching
        # the token — the warmed executable stays valid. Fused mode runs
        # its fwd at [tr+val] rows and its bwd at [tr] rows; unfused runs
        # fwd+bwd at [tr] and an eval fwd at [val].
        _autotune_packed_shapes(
            [ptr_np.shape[0] + pval_np.shape[0], ptr_np.shape[0]] if fused
            else [ptr_np.shape[0], pval_np.shape[0]],
            n_genes_pad, hidden, pallas_interpret, autotune_cache_path)
    chunk_fn = _get_chunk_fn(learning_rate, cdtype, decision_threshold, ctx,
                             chunk, packed=use_pallas,
                             interpret=pallas_interpret, fused=fused,
                             superstep=superstep, donate=donate)

    # ---- epoch loop with first-val-dip early stopping ----
    history: List[dict] = []
    before_val, before_tr = -1.0, -1.0
    snapshot = params            # device-side reference, no copy
    start_epoch = 0
    stopped_early = False
    stop_epoch = max_epochs - 1
    # Recorded in the checkpoint manifest and checked on resume: same-shape
    # config drift (changed lr/seed/dtype) must fail loudly, not blend two
    # runs. max_epochs is deliberately absent — extending it is supported.
    ckpt_fingerprint = {
        "hidden": hidden, "learning_rate": learning_rate,
        "compute_dtype": compute_dtype, "param_dtype": param_dtype,
        "seed": seed, "val_fraction": val_fraction,
        "decision_threshold": decision_threshold,
        "n_genes_pad": int(n_genes_pad),
    }
    if checkpoint_dir and resume:
        from g2vec_tpu.train.checkpoint import (RUN_EARLY_STOPPED,
                                                RUN_IN_PROGRESS, load_state)

        restored = load_state(checkpoint_dir, params, opt_state,
                              layout=checkpoint_layout,
                              fingerprint=ckpt_fingerprint)
        if restored is not None:
            (params, opt_state, snapshot, last_epoch,
             before_val, before_tr, done) = restored
            if ctx.mesh is not None:
                # Re-apply the DP/TP shardings the fresh-init path declares,
                # or the resumed program compiles with replicated (possibly
                # OOM-ing) params. Single layout hands back host arrays;
                # sharded layout hands back device arrays already on the
                # right shardings for the big leaves (device_put is then a
                # no-op) but its scalar leaves (Adam count) restore onto
                # the fresh init's single-device placement and must be
                # re-replicated over the mesh. Classification is by tree
                # position (CBOWParams containers inside
                # params/opt_state/snapshot), never by shape — shapes are
                # ambiguous when hidden == n_genes_pad.
                from jax.sharding import PartitionSpec as P

                def _reshard_params(p: CBOWParams) -> CBOWParams:
                    return CBOWParams(
                        w_ih=ctx.put(p.w_ih, ctx.w_ih_spec),
                        w_ho=ctx.put(p.w_ho, ctx.w_ho_spec))

                params = _reshard_params(params)
                snapshot = _reshard_params(snapshot)
                opt_state = jax.tree.map(
                    lambda sub: (_reshard_params(sub)
                                 if isinstance(sub, CBOWParams)
                                 else ctx.put(sub, P())),
                    opt_state,
                    is_leaf=lambda x: isinstance(x, CBOWParams))
            if (done == RUN_EARLY_STOPPED
                    or (done != RUN_IN_PROGRESS and last_epoch + 1 >= max_epochs)):
                # Terminal state: an early stop is final (stepping on would
                # re-apply the dip epoch's update — the saved params are
                # post-dip, the snapshot pre-dip), and a completed run with
                # no additional epoch budget has nothing to do. A completed
                # run CAN continue when max_epochs was raised.
                from g2vec_tpu.parallel.distributed import fetch_global

                w_ih = fetch_global(snapshot.w_ih).astype(np.float32)[:n_genes]
                return TrainResult(
                    w_ih=w_ih, stop_epoch=last_epoch,
                    stopped_early=(done == RUN_EARLY_STOPPED),
                    acc_val=before_val, acc_tr=before_tr,
                    history=[], params=snapshot)
            start_epoch = last_epoch + 1
    from jax.sharding import PartitionSpec as P

    if donate:
        # Donated arguments must be distinct buffers: the fresh-init
        # snapshot aliases params (and a restored one may share leaves).
        # One small copy up front; every later chunk hands back fresh
        # outputs whose buffers the next call donates again.
        snapshot = jax.tree.map(jnp.copy, snapshot)
    # The per-chunk history buffer is part of the donated carry: allocated
    # once, updated in place on device, device_get'd (a host copy) after
    # each chunk, then handed straight back.
    hist_dev = ctx.put(np.zeros((chunk, 3), np.float32), P())
    t0 = time.time()
    step = step_start = start_epoch
    while step < max_epochs and not stopped_early:
        # Cooperative interruption (resilience/lifecycle.py): the chunk
        # boundary is where device state is host-consistent, so a serve
        # cancel/deadline/drain raised here never tears a run.
        if check is not None:
            check()
        limit = min(chunk, max_epochs - step)
        (params, opt_state, snapshot, bv_d, bt_d, count_d, dip_d, hist_dev
         ) = chunk_fn(params, opt_state, snapshot, hist_dev, before_val,
                      before_tr, limit, *data)
        count = int(count_d)                     # the only host sync per chunk
        stopped_early = bool(dip_d)
        before_val, before_tr = float(bv_d), float(bt_d)
        hist = np.asarray(jax.device_get(hist_dev))[:count]
        secs = (time.time() - t0) / max(count, 1)
        t0 = time.time()
        from g2vec_tpu.resilience.faults import fault_point

        for j in range(count):
            av, at, ls = (float(hist[j, 0]), float(hist[j, 1]), float(hist[j, 2]))
            history.append({"epoch": step + j, "acc_val": av, "acc_tr": at,
                            "loss": ls, "secs": secs})
            if on_epoch is not None:
                on_epoch(step + j, av, at, secs)
            # The train-loop fault seam: fires at the host-side epoch
            # callback (the epoch's device work is done, its checkpoint may
            # not be) — the exact place a preemption hurts most.
            fault_point("train", epoch=step + j)
        step += count
        if stopped_early:
            stop_epoch = step - 2                # dip epoch minus one
        elif checkpoint_dir and step > step_start:
            from g2vec_tpu.train.checkpoint import save_state

            save_state(checkpoint_dir, params, opt_state, snapshot,
                       step - 1, before_val, before_tr,
                       layout=checkpoint_layout,
                       fingerprint=ckpt_fingerprint)

    if checkpoint_dir:
        from g2vec_tpu.train.checkpoint import (RUN_COMPLETED,
                                                RUN_EARLY_STOPPED, save_state)

        save_state(checkpoint_dir, params, opt_state, snapshot,
                   stop_epoch if stopped_early else max_epochs - 1,
                   before_val, before_tr,
                   done=RUN_EARLY_STOPPED if stopped_early else RUN_COMPLETED,
                   layout=checkpoint_layout,
                   fingerprint=ckpt_fingerprint)
    from g2vec_tpu.parallel.distributed import fetch_global

    w_ih = fetch_global(snapshot.w_ih).astype(np.float32)[:n_genes]
    return TrainResult(w_ih=w_ih, stop_epoch=stop_epoch,
                       stopped_early=stopped_early,
                       acc_val=before_val, acc_tr=before_tr,
                       history=history, params=snapshot)


@dataclasses.dataclass
class LaneTrainSpec:
    """One lane's inputs for :func:`train_cbow_lanes` — the per-lane data
    plus the split/init seed; every other hyperparameter is bucket-shared
    (it is baked into the one batched program)."""
    paths: np.ndarray
    labels: np.ndarray
    seed: int


def train_cbow_lanes(lanes, *, packed_genes: Optional[int] = None,
                     hidden: int, learning_rate: float, max_epochs: int,
                     val_fraction: float = 0.2,
                     decision_threshold: float = 0.5,
                     compute_dtype: str = "float32",
                     param_dtype: str = "float32",
                     on_epoch: Optional[Callable[[int, int, float, float, float], None]] = None,
                     fused_eval: bool = True, epoch_superstep: int = 1,
                     donate: bool = True,
                     pre_compile_hook: Optional[Callable[[], None]] = None,
                     check: Optional[Callable[[], None]] = None,
                     ):
    """Train B same-shape CBOW lanes as ONE batched device program.

    The batch engine's trainer half (batch/engine.py): ``lanes`` is a
    bucket of :class:`LaneTrainSpec` whose path matrices share one shape
    and whose hyperparameters are identical — only the (split, init) seed
    and the data bits differ per lane. The chunk program is the solo
    trainer's, lifted over a leading lane axis by ``jax.vmap``
    (_make_chunk_fn ``lanes=B``): params/opt-state/snapshot carry
    ``[B, ...]`` leaves, the while_loop runs while ANY lane is live, and
    finished lanes freeze through vmap's select masking. Per-lane early
    stop needs no recompile — a stopped lane re-enters later chunks with
    ``limit=0`` and executes zero epochs.

    Parity contract (tested end to end in tests/test_batch_engine.py):
    in float32 on a given backend, every lane's history, early-stop
    decision, stop epoch, and final embedding table are BITWISE the solo
    :func:`train_cbow` run's at the same config — batched dot_general /
    reductions / scatters on this backend reproduce the per-example
    programs bit-for-bit, and the host-side split/pack/init code is
    shared verbatim. Lanes always run the XLA (non-Pallas) path: the
    batched program is shape-uniform across backends, and the parity
    target is the solo XLA run.

    Returns ``(results, emb_stack)``: per-lane :class:`TrainResult`s
    (their ``w_ih`` are views of ONE stacked host transfer) and the
    ``[B, n_genes, hidden]`` float32 embedding stack still ON DEVICE —
    stage 5 consumes it without a host round trip (analysis.py).

    ``on_epoch(lane, step, acc_val, acc_tr, secs)`` fires per lane per
    epoch; ``secs`` is the chunk's wall divided by the epochs the whole
    bucket executed in it (per-lane wall is not separable inside one
    batched dispatch).
    """
    B = len(lanes)
    if B < 1:
        raise ValueError("train_cbow_lanes needs at least one lane")
    if epoch_superstep < 1:
        raise ValueError(
            f"epoch_superstep must be >= 1, got {epoch_superstep}")
    if compute_dtype not in _DTYPES or param_dtype not in _DTYPES:
        raise ValueError(
            f"dtypes must be one of {sorted(_DTYPES)}, got "
            f"{compute_dtype!r}/{param_dtype!r}")
    shapes = {spec.paths.shape for spec in lanes}
    if len(shapes) != 1:
        raise ValueError(
            f"train_cbow_lanes is one shape bucket: all lanes must share "
            f"one path-matrix shape, got {sorted(shapes)}")
    if lanes[0].paths.shape[0] < 2:
        raise ValueError(
            f"need at least 2 paths to split, got {lanes[0].paths.shape[0]}")
    ctx = make_mesh_context(None)
    cdtype = _DTYPES[compute_dtype]
    pdtype = _DTYPES[param_dtype]
    if packed_genes is not None:
        n_paths, nb_in = lanes[0].paths.shape
        n_genes = packed_genes
        if nb_in != (n_genes + 7) // 8 or lanes[0].paths.dtype != np.uint8:
            raise ValueError(
                f"packed_genes={n_genes} expects uint8 paths of width "
                f"{(n_genes + 7) // 8}, got {lanes[0].paths.dtype} width "
                f"{nb_in}")
    else:
        n_paths, n_genes = lanes[0].paths.shape

    plan = _plan_layout(n_paths, n_genes, hidden, compute_dtype, ctx,
                        use_pallas=False)
    n_genes_pad, row_multiple = plan.n_genes_pad, plan.row_multiple
    unpack_fn = _get_unpack_fn(ctx, cdtype)
    fused = bool(fused_eval)

    def _unpack_stack(stack: np.ndarray):
        # [B, rows, nb] uint8 -> [B, rows, G_pad] compute dtype, via the
        # SAME jitted unpack program the solo path uses (flattened over
        # the lane axis — bit expansion is elementwise, values exact).
        b, rows, nb = stack.shape
        dense = unpack_fn(ctx.put(stack.reshape(b * rows, nb),
                                  ctx.batch_spec))
        return dense.reshape(b, rows, nb * 8)

    # ---- per-lane split + pack (the solo code, per lane), then stack ----
    packed_tr, y_tr, w_tr = [], [], []
    packed_vl, y_vl, w_vl = [], [], []
    for spec in lanes:
        tr_idx, vl_idx = _split_indices(n_paths, spec.seed, val_fraction)
        p, y, w = _pack_split(spec.paths, spec.labels, tr_idx,
                              packed_genes=packed_genes, n_genes=n_genes,
                              n_genes_pad=n_genes_pad,
                              row_multiple=row_multiple, use_pallas=False)
        packed_tr.append(p), y_tr.append(y), w_tr.append(w)
        p, y, w = _pack_split(spec.paths, spec.labels, vl_idx,
                              packed_genes=packed_genes, n_genes=n_genes,
                              n_genes_pad=n_genes_pad,
                              row_multiple=row_multiple, use_pallas=False)
        packed_vl.append(p), y_vl.append(y), w_vl.append(w)
    ytr = ctx.put(np.stack(y_tr), None)
    wtr = ctx.put(np.stack(w_tr), None)
    yval = ctx.put(np.stack(y_vl), None)
    wval = ctx.put(np.stack(w_vl), None)
    if fused:
        xall = _unpack_stack(np.concatenate(
            [np.stack(packed_tr), np.stack(packed_vl)], axis=1))
        data = (xall, ytr, wtr, yval, wval)
    else:
        data = (_unpack_stack(np.stack(packed_tr)), ytr, wtr,
                _unpack_stack(np.stack(packed_vl)), yval, wval)

    # ---- stacked params + optimizer state ----
    per_lane = [init_params(jax.random.key(spec.seed), n_genes, hidden,
                            param_dtype=pdtype, pad_to=n_genes_pad)
                for spec in lanes]
    params = CBOWParams(w_ih=jnp.stack([p.w_ih for p in per_lane]),
                        w_ho=jnp.stack([p.w_ho for p in per_lane]))
    tx = optax.adam(learning_rate, b1=_ADAM_B1, b2=_ADAM_B2, eps=_ADAM_EPS)
    opt_state = jax.vmap(tx.init)(params)

    chunk = max(1, min(DEFAULT_CHUNK, max_epochs))
    superstep = max(1, min(epoch_superstep, chunk))
    if pre_compile_hook is not None:
        pre_compile_hook()
    chunk_fn = _get_chunk_fn(learning_rate, cdtype, decision_threshold, ctx,
                             chunk, packed=False, interpret=False,
                             fused=fused, superstep=superstep,
                             donate=donate, lanes=B)

    snapshot = jax.tree.map(jnp.copy, params) if donate else params
    hist_dev = jnp.zeros((B, chunk, 3), jnp.float32)

    # ---- per-lane host bookkeeping (authoritative across chunks) ----
    step = np.zeros(B, dtype=np.int64)
    alive = np.ones(B, dtype=bool)
    stopped = np.zeros(B, dtype=bool)
    before_val = np.full(B, -1.0, dtype=np.float32)
    before_tr = np.full(B, -1.0, dtype=np.float32)
    stop_epoch = np.full(B, max_epochs - 1, dtype=np.int64)
    histories: List[List[dict]] = [[] for _ in range(B)]
    t0 = time.time()
    while alive.any():
        # Cooperative interruption at the batched chunk boundary — the
        # same seam the solo trainer checks (resilience/lifecycle.py).
        if check is not None:
            check()
        limits = np.where(alive,
                          np.minimum(chunk, max_epochs - step),
                          0).astype(np.int32)
        (params, opt_state, snapshot, bv_d, bt_d, count_d, dip_d, hist_dev
         ) = chunk_fn(params, opt_state, snapshot, hist_dev,
                      jnp.asarray(before_val), jnp.asarray(before_tr),
                      jnp.asarray(limits), *data)
        counts = np.asarray(count_d)             # one host sync per chunk
        dips = np.asarray(dip_d)
        bv, bt = np.asarray(bv_d), np.asarray(bt_d)
        hist = np.asarray(jax.device_get(hist_dev))
        wall = time.time() - t0
        t0 = time.time()
        secs = wall / max(int(counts[alive].sum()), 1)
        for b in np.nonzero(alive)[0]:
            c = int(counts[b])
            # A finished lane's device-side (before_val, before_tr) may be
            # scribbled by the unfused backfill on later limit=0 chunks —
            # the HOST copy is only refreshed while the lane is alive.
            before_val[b], before_tr[b] = float(bv[b]), float(bt[b])
            for j in range(c):
                av, at, ls = (float(hist[b, j, 0]), float(hist[b, j, 1]),
                              float(hist[b, j, 2]))
                histories[b].append(
                    {"epoch": int(step[b]) + j, "acc_val": av,
                     "acc_tr": at, "loss": ls, "secs": secs})
                if on_epoch is not None:
                    on_epoch(int(b), int(step[b]) + j, av, at, secs)
            step[b] += c
            if dips[b]:
                stopped[b] = True
                alive[b] = False
                stop_epoch[b] = step[b] - 2      # dip epoch minus one
            elif step[b] >= max_epochs:
                alive[b] = False

    # ONE stacked device cast/slice; the single host transfer below is the
    # writer-boundary materialization every lane shares.
    emb_stack = snapshot.w_ih.astype(jnp.float32)[:, :n_genes]
    emb_host = np.asarray(emb_stack)
    results = []
    for b in range(B):
        results.append(TrainResult(
            w_ih=emb_host[b], stop_epoch=int(stop_epoch[b]),
            stopped_early=bool(stopped[b]),
            acc_val=float(before_val[b]), acc_tr=float(before_tr[b]),
            history=histories[b],
            params=CBOWParams(w_ih=snapshot.w_ih[b],
                              w_ho=snapshot.w_ho[b])))
    return results, emb_stack


def warm_train_compile(n_paths: int, n_genes: int, *, hidden: int,
                       learning_rate: float, max_epochs: int,
                       val_fraction: float = 0.2,
                       decision_threshold: float = 0.5,
                       compute_dtype: str = "bfloat16",
                       param_dtype: str = "float32",
                       mesh_ctx: Optional[MeshContext] = None,
                       checkpoint_dir: Optional[str] = None,
                       checkpoint_every: int = 25,
                       use_pallas: Optional[bool] = None,
                       fused_eval: bool = True, epoch_superstep: int = 1,
                       donate: bool = True, kernel_autotune: bool = False,
                       autotune_cache_path: Optional[str] = None,
                       lanes: int = 0) -> bool:
    """Compile the chunk (and unpack) programs train_cbow will run at
    these shapes, without training anything.

    The overlap scheduler (parallel/overlap.py) calls this in the
    BACKGROUND the moment ``n_paths`` is known (right after
    integrate_path_sets), so the multi-second XLA compile runs while the
    foreground is still counting gene frequencies and bit-packing the
    path matrix — by the time train_cbow asks for the chunk fn, the LRU
    already holds the compiled executable. Identity with the real
    request is structural: the same _plan_layout/_get_chunk_fn derivation
    from the same arguments produces the same cache key, and the dummy
    zero inputs here have exactly the shapes/dtypes/shardings _prep
    produces (the jit executable cache keys on those, never on values).

    The warm call runs the chunk program once with ``limit=0``: the
    device while_loop exits before epoch 0 and only the per-chunk
    accuracy backfill executes — one eval forward, trivial next to the
    compile it buys. Returns True when the programs were warmed, False
    for degenerate shapes train_cbow would reject anyway (its own error
    messages are the better report).
    """
    if n_paths < 2 or compute_dtype not in _DTYPES \
            or param_dtype not in _DTYPES:
        return False
    pivot = int(n_paths * (1.0 - val_fraction))
    if pivot in (0, n_paths):
        return False
    ctx = mesh_ctx if mesh_ctx is not None else make_mesh_context(None)
    cdtype = _DTYPES[compute_dtype]
    pdtype = _DTYPES[param_dtype]
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    plan = _plan_layout(n_paths, n_genes, hidden, compute_dtype, ctx,
                        False if lanes else use_pallas)
    chunk = checkpoint_every if checkpoint_dir else DEFAULT_CHUNK
    chunk = max(1, min(chunk, max_epochs))
    superstep = max(1, min(epoch_superstep, chunk))
    tr_pad = pad_to_multiple(pivot, plan.row_multiple)
    val_pad = pad_to_multiple(n_paths - pivot, plan.row_multiple)
    fused = fused_eval and ctx.mesh is None     # same gate as train_cbow
    if kernel_autotune and plan.use_pallas:
        # Sweep (or cache-load) the tile plans FIRST: the chunk-fn key
        # embeds pm.tuned_token(), so warming before the install would
        # compile an executable the real run can never hit.
        _autotune_packed_shapes(
            [tr_pad + val_pad, tr_pad] if fused else [tr_pad, val_pad],
            plan.n_genes_pad, hidden, plan.interpret, autotune_cache_path)
    chunk_fn = _get_chunk_fn(learning_rate, cdtype, decision_threshold, ctx,
                             chunk, packed=plan.use_pallas,
                             interpret=plan.interpret, fused=fused,
                             superstep=superstep, donate=donate,
                             lanes=lanes)

    def _stack(x):
        # lanes > 0 warms the vmapped bucket program: every argument gains
        # a leading [B] axis (values are irrelevant — the jit executable
        # cache keys on shapes/dtypes/shardings only).
        return jnp.broadcast_to(x[None], (lanes,) + x.shape) + 0 \
            if lanes else x

    def dummy_x(n_pad):
        packed = np.zeros((n_pad, plan.n_genes_pad // 8), dtype=np.uint8)
        if plan.use_pallas:
            return _stack(ctx.put(packed, ctx.packed_batch_spec))
        return _stack(_get_unpack_fn(ctx, cdtype)(
            ctx.put(packed, ctx.batch_spec)))

    def dummy_yw(n_rows, n_pad):
        return (_stack(ctx.put(np.zeros((n_pad, 1), np.float32),
                               ctx.label_spec)),
                _stack(ctx.put(_pad_rows(np.ones((n_rows, 1), np.float32),
                                         n_pad), ctx.label_spec)))

    if fused:
        data = (dummy_x(tr_pad + val_pad),
                *dummy_yw(pivot, tr_pad),
                *dummy_yw(n_paths - pivot, val_pad))
    else:
        data = (dummy_x(tr_pad), *dummy_yw(pivot, tr_pad),
                dummy_x(val_pad), *dummy_yw(n_paths - pivot, val_pad))
    params = init_params(jax.random.key(0), n_genes, hidden,
                         param_dtype=pdtype, pad_to=plan.n_genes_pad)
    if ctx.mesh is not None:
        params = CBOWParams(w_ih=ctx.put(params.w_ih, ctx.w_ih_spec),
                            w_ho=ctx.put(params.w_ho, ctx.w_ho_spec))
    tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
    if lanes:
        params = jax.tree.map(_stack, params)
        opt_state = jax.vmap(tx.init)(params)
    else:
        opt_state = tx.init(params)
    from jax.sharding import PartitionSpec as P

    if ctx.mesh is not None:
        opt_state = jax.tree.map(
            lambda sub: (sub if isinstance(sub, CBOWParams)
                         else ctx.put(sub, P())),
            opt_state, is_leaf=lambda x: isinstance(x, CBOWParams))
    # Donation wants distinct buffers per donated argument (params is
    # reused as the snapshot here).
    snapshot = jax.tree.map(jnp.copy, params) if donate else params
    hist = ctx.put(np.zeros(((lanes, chunk, 3) if lanes else (chunk, 3)),
                            np.float32), P())
    if lanes:
        zero = (np.full(lanes, -1.0, np.float32),
                np.full(lanes, -1.0, np.float32),
                np.zeros(lanes, np.int32))
    else:
        zero = (-1.0, -1.0, 0)
    out = chunk_fn(params, opt_state, snapshot, hist, *zero, *data)
    jax.block_until_ready(out[5])      # the epoch count — compile is done
    return True
