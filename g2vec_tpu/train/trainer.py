"""The modified-CBOW trainer (ref: compute_genetovec, G2Vec.py:217-286).

Reference behavior, reproduced exactly (SURVEY.md §7 quirk (c)):

- shuffled 80/20 hold-out (ref: G2Vec.py:219-226) — here with a seeded PRNG
  (the reference is unseeded);
- full-batch training: the whole train split in every optimizer step
  (ref: G2Vec.py:264);
- Adam with TF1 defaults (b1=0.9, b2=0.999, eps=1e-8; ref: G2Vec.py:246);
- after each step, val and train accuracies are evaluated with the UPDATED
  weights (ref: G2Vec.py:264-267);
- early stop on the FIRST strict decrease of val accuracy, returning the
  PREVIOUS epoch's embedding table (the reference fetches W_ih every epoch at
  G2Vec.py:283, after the break check, so on stop the previous epoch's value
  survives);
- ``--epoch`` caps the loop (the reference parses but ignores it,
  hardcoding 500 — SURVEY.md §7 quirk (b); we honor it).

TPU design vs the reference: the TF1 version re-feeds the full dense path
matrix host->runtime three times per epoch through ``feed_dict``
(~1.3 GB/epoch at example scale, ref: G2Vec.py:264-267) and pulls the whole
W_ih back every epoch (G2Vec.py:283). Here the path matrix and parameters
are device-resident, epochs run in device-side chunks of DEFAULT_CHUNK
inside a ``lax.while_loop`` (the early-stop comparison included), and the
host sees one small (state, per-epoch history) transfer per chunk — on a
tunneled TPU that round trip dwarfs the epoch math, so it must be
amortized. The previous-epoch snapshot stays on device (a per-epoch
``jnp.where`` select of the param tree; W_ih only crosses to the host once,
after training). On a single chip the X@W_ih matmuls run through the fused
bit-packed Pallas kernel (ops/packed_matmul.py) so X stays packed in HBM.

The eval-train FOLD: the reference re-runs a full train-split forward per
epoch just to report ACC[tr] at the updated weights — but those weights
are exactly the next epoch's entry weights, so that forward is recomputed
verbatim by the next epoch's gradient pass. The chunk body reads the
previous epoch's ACC[tr] out of its own grad forward (``has_aux``) and a
single per-chunk eval backfills the last epoch's; per-epoch train-split
matmul passes drop 3 -> 2 (~31% of epoch FLOPs at the 80/20 split). The
history is the same computation at the same params/inputs as the unfused
3-pass epoch — bitwise so in float32 (test-pinned); under bfloat16 XLA may
compile the grad-forward and the standalone eval to different programs, so
the chunk-boundary backfill can differ from the in-chunk value in low bits
(accuracies stay correct and the early stop reads only acc_val, so
training behavior is unaffected).
"""
from __future__ import annotations

import dataclasses
import threading
import time
from collections import OrderedDict
from typing import Callable, List, Optional

import jax
import jax.numpy as jnp
import numpy as np
import optax

from g2vec_tpu.models.cbow import (CBOWParams, forward, init_params,
                                   output_logits)
from g2vec_tpu.ops import packed_matmul as pm
from g2vec_tpu.parallel.mesh import MeshContext, make_mesh_context


# Epochs executed per device dispatch when not checkpointing. The host round
# trip to a tunneled TPU is ~90 ms; the epoch math at example scale is ~7 ms
# (BENCH_r02), so syncing every epoch would be >10x overhead and even 64
# epochs/chunk left ~1.4 ms/epoch of sync in the measured steady state. 128
# amortizes the round trip to ~0.7 ms/epoch; the early stop still exits ON
# the dip (the device while_loop tests it every epoch), so a bigger chunk
# wastes no compute — it only coarsens the history delivery cadence.
DEFAULT_CHUNK = 128


def _default_backend() -> str:
    """``jax.default_backend()`` that degrades instead of raising.

    With a dead TPU tunnel the backend query itself raises RuntimeError
    (round-1 postmortem: bench.py died here before any useful error). The
    caller only uses this to pick the Pallas fast path, so "unknown" simply
    means "not tpu" — the subsequent device use will produce the real error
    with full context if the backend is truly gone.
    """
    try:
        return jax.default_backend()
    except RuntimeError:
        return "unknown"


@dataclasses.dataclass
class TrainResult:
    w_ih: np.ndarray            # [n_genes, hidden] float32 — the embeddings
    stop_epoch: int             # reported stop epoch (reference convention)
    stopped_early: bool
    acc_val: float              # accuracy pair at the reported epoch
    acc_tr: float
    history: List[dict]         # per-epoch {epoch, acc_val, acc_tr, loss, secs}
    params: Optional[CBOWParams] = None  # device params (for checkpointing)


def _make_chunk_fn(tx: optax.GradientTransformation, compute_dtype,
                   decision_threshold: float, ctx: MeshContext, chunk: int,
                   packed: bool = False, interpret: bool = False):
    """Compile a device-resident loop over up to ``chunk`` epochs.

    The reference syncs with the host three times per epoch (optimizer run +
    two accuracy evals through feed_dict, ref: G2Vec.py:264-267). A naive JAX
    port still syncs once per epoch to test the early-stop condition — and on
    a remote TPU that round trip (~90 ms over the tunnel) dwarfs the ~15 ms
    of epoch math. So the early-stop comparison itself lives on device inside
    a ``lax.while_loop``; the host sees one transfer of (state, per-epoch
    accuracy history) per ``chunk`` epochs, and the loop exits on the first
    val-accuracy dip no matter where in the chunk it falls.
    """
    logit_threshold = float(np.log(decision_threshold / (1.0 - decision_threshold)))

    if packed:
        # Pallas path: ``x`` is the bit-packed [rows, n_genes/8] uint8 matrix
        # in pack_blockwise layout; the fused kernel unpacks tiles in VMEM
        # (ops/packed_matmul.py) — 16x less HBM traffic than a dense bf16 X.
        # Under a data-parallel mesh the kernel runs per row shard inside
        # shard_map with W replicated; shard_map's transpose psums the
        # per-shard dW cotangents over 'data' automatically.
        def _packed_h(x, w_ih):
            if ctx.mesh is None:
                return pm.packed_matmul(x, w_ih, interpret)
            from jax.sharding import PartitionSpec as P

            from g2vec_tpu.parallel.mesh import shard_map

            return shard_map(
                lambda xs, w: pm.packed_matmul(xs, w, interpret),
                mesh=ctx.mesh,
                in_specs=(ctx.packed_batch_spec, P(None, None)),
                out_specs=ctx.hidden_spec,
                # pallas_call's out_shape carries no varying-axes info;
                # the specs above are the full contract.
                check_vma=False)(x, w_ih)

        def logits_fn(params, x):
            h = _packed_h(x, params.w_ih.astype(compute_dtype))
            return output_logits(h, params.w_ho, compute_dtype)
    else:
        def logits_fn(params, x):
            return forward(params, x, compute_dtype)

    # ``w`` is a [batch, 1] 1/0 mask: 1 for real rows, 0 for shard-even
    # padding rows (see train_cbow). Weighted means make the padded program
    # numerically identical to the unpadded one.
    def loss_fn(params, x, y, w):
        logits = logits_fn(params, x)
        logits = ctx.constrain(logits, ctx.label_spec)
        bce = optax.sigmoid_binary_cross_entropy(logits, y)
        return jnp.sum(bce * w) / jnp.sum(w), logits

    def acc_from_logits(logits, y, w):
        pred = (logits > logit_threshold).astype(jnp.float32)
        return jnp.sum((pred == y).astype(jnp.float32) * w) / jnp.sum(w)

    def accuracy(params, x, y, w):
        return acc_from_logits(logits_fn(params, x), y, w)

    # Eval-train fold (the MFU work, VERDICT r3 task 4): the reference's
    # epoch runs THREE full train-split matmul passes — grad fwd, dW, and a
    # train-accuracy eval at the UPDATED weights (ref: G2Vec.py:264-267).
    # But epoch i's updated weights are exactly epoch i+1's entry weights,
    # so epoch i's train-accuracy logits are recomputed verbatim by epoch
    # i+1's grad forward. The body therefore reads acc_tr for epoch i-1
    # out of its own grad forward (has_aux) and backfills hist[i-1]; the
    # final executed epoch's acc_tr is computed once per CHUNK after the
    # loop. Per-epoch train-split passes drop 3 -> 2 (~31% of the epoch's
    # matmul FLOPs at the 80/20 split) with bit-identical history: same
    # kernel, same params, same inputs, just computed one body later.
    def epoch(params, opt_state, xtr, ytr, wtr, xval, yval, wval):
        (loss, logits_tr), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params, xtr, ytr, wtr)
        acc_tr_prev = acc_from_logits(logits_tr, ytr, wtr)
        updates, opt_state = tx.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        if ctx.mesh is not None:
            params = CBOWParams(
                w_ih=ctx.constrain(params.w_ih, ctx.w_ih_spec),
                w_ho=ctx.constrain(params.w_ho, ctx.w_ho_spec))
        # Val accuracy uses the UPDATED weights (ref: G2Vec.py:264-267).
        acc_val = accuracy(params, xval, yval, wval)
        return params, opt_state, acc_val, acc_tr_prev, loss

    def run_chunk(params, opt_state, snapshot, before_val, before_tr, limit,
                  xtr, ytr, wtr, xval, yval, wval):
        hist = jnp.zeros((chunk, 3), jnp.float32)   # [acc_val, acc_tr, loss]

        def cond(carry):
            _, _, _, _, _, i, stopped, _ = carry
            return jnp.logical_and(i < limit, jnp.logical_not(stopped))

        def body(carry):
            params, opt_state, snapshot, before_val, before_tr, i, _, hist = carry
            params, opt_state, acc_val, acc_tr_prev, loss = epoch(
                params, opt_state, xtr, ytr, wtr, xval, yval, wval)
            dip = acc_val < before_val        # first strict decrease → stop
            hist = hist.at[i].set(jnp.stack([acc_val, jnp.float32(0), loss]))
            # acc_tr_prev belongs to epoch i-1 (see the fold note above).
            # i == 0: the entry params' train accuracy was already recorded
            # by the previous chunk's post-loop backfill (or is the init
            # params' — never reported); keep hist[0] untouched then.
            prev = jnp.maximum(i - 1, 0)
            hist = hist.at[prev, 1].set(
                jnp.where(i > 0, acc_tr_prev, hist[prev, 1]))
            # Epoch i-1 completed without a dip (the loop ran body i), so
            # its acc_tr is the current "previous epoch" train accuracy.
            before_tr = jnp.where(i > 0, acc_tr_prev, before_tr)
            # On a dip the dip epoch's update is discarded: the snapshot and
            # best-acc pair keep their previous-epoch values (ref: the
            # fetch-after-break ordering at G2Vec.py:276-283).
            snapshot = jax.tree.map(
                lambda old, new: jnp.where(dip, old, new), snapshot, params)
            before_val = jnp.where(dip, before_val, acc_val)
            return (params, opt_state, snapshot, before_val, before_tr,
                    i + 1, dip, hist)

        init = (params, opt_state, snapshot,
                jnp.float32(before_val), jnp.float32(before_tr),
                jnp.int32(0), jnp.bool_(False), hist)
        (params, opt_state, snapshot, before_val, before_tr, count, dip,
         hist) = jax.lax.while_loop(cond, body, init)
        # Backfill the final executed epoch's acc_tr: one eval forward per
        # CHUNK (the fold's only residual cost), at that epoch's post-update
        # params — including a dip epoch's (whose update params still sit in
        # ``params`` even though the snapshot discarded them), exactly what
        # the unfused epoch reported.
        acc_tr_last = accuracy(params, xtr, ytr, wtr)
        last = jnp.maximum(count - 1, 0)
        hist = hist.at[last, 1].set(acc_tr_last)
        before_tr = jnp.where(dip, before_tr, acc_tr_last)
        return (params, opt_state, snapshot, before_val, before_tr, count,
                dip, hist)

    return jax.jit(run_chunk)


# jit caches live on the function object, so the compiled chunk must be
# reused across train_cbow calls (a fresh closure per call would recompile
# the whole while_loop program every run — ~10 s at example scale). Both
# caches are TRUE LRUs (hits refresh recency, eviction drops the least
# recently USED entry): a long supervised run sweeping shapes/hyperparams
# must neither grow them without bound nor evict the entry it re-hits
# every retry just because it was inserted first.
_CHUNK_FN_CACHE: "OrderedDict" = OrderedDict()
_UNPACK_FN_CACHE: "OrderedDict" = OrderedDict()
_CHUNK_FN_CACHE_MAX = 16   # hyperparameter sweeps must not pin old executables
_UNPACK_FN_CACHE_MAX = 8   # keyed by (mesh, dtype) only — 8 is generous


_CACHE_LOCK = threading.Lock()
_CACHE_PENDING: dict = {}      # (cache id, key) -> Event for in-flight makes


def _lru_get(cache: "OrderedDict", key, limit: int, make):
    """Thread-safe bounded LRU lookup. A second requester of an in-flight
    key BLOCKS until the first finishes and then shares the same fn —
    the overlap scheduler warms the chunk fn in the background while the
    foreground trainer may request the identical key, and two distinct
    jitted wrappers would compile the same program twice."""
    pending_key = (id(cache), key)
    while True:
        with _CACHE_LOCK:
            fn = cache.get(key)
            if fn is not None:
                cache.move_to_end(key)
                return fn
            ev = _CACHE_PENDING.get(pending_key)
            if ev is None:
                ev = threading.Event()
                _CACHE_PENDING[pending_key] = ev
                break
        ev.wait()
    try:
        fn = make()
        with _CACHE_LOCK:
            while len(cache) >= limit:
                cache.popitem(last=False)
            cache[key] = fn
        return fn
    finally:
        with _CACHE_LOCK:
            _CACHE_PENDING.pop(pending_key, None)
        ev.set()


def _get_chunk_fn(learning_rate: float, compute_dtype, decision_threshold: float,
                  ctx: MeshContext, chunk: int, packed: bool = False,
                  interpret: bool = False):
    key = (learning_rate, jnp.dtype(compute_dtype).name, decision_threshold,
           ctx.mesh, chunk, packed, interpret)

    def make():
        tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
        return _make_chunk_fn(tx, compute_dtype, decision_threshold, ctx,
                              chunk, packed, interpret)

    return _lru_get(_CHUNK_FN_CACHE, key, _CHUNK_FN_CACHE_MAX, make)


def _get_unpack_fn(ctx: MeshContext, compute_dtype):
    """[rows, n_bytes] uint8 -> [rows, n_bytes*8] compute-dtype multi-hot.

    The multi-hot path matrix crosses host->device as PACKED BITS (8 genes
    per byte, ~42 MB at example scale instead of 546 MB as bf16) and is
    expanded on device, where HBM bandwidth is ~800 GB/s. Bit order matches
    ``np.packbits`` (MSB first)."""
    key = (ctx.mesh, jnp.dtype(compute_dtype).name)

    def make():
        def unpack(packed):
            shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
            bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
            x = bits.reshape(packed.shape[0], -1).astype(compute_dtype)
            return ctx.constrain(x, ctx.batch_spec)
        return jax.jit(unpack)

    return _lru_get(_UNPACK_FN_CACHE, key, _UNPACK_FN_CACHE_MAX, make)


def _pad_rows(arr: np.ndarray, n_rows: int) -> np.ndarray:
    """Zero-pad axis 0 to exactly n_rows."""
    if arr.shape[0] == n_rows:
        return arr
    pad = np.zeros((n_rows - arr.shape[0],) + arr.shape[1:], dtype=arr.dtype)
    return np.concatenate([arr, pad], axis=0)


_DTYPES = {"bfloat16": jnp.bfloat16, "float32": jnp.float32}


@dataclasses.dataclass(frozen=True)
class _Layout:
    """Everything about the device programs that follows from shapes alone.

    ONE derivation shared by :func:`train_cbow` and
    :func:`warm_train_compile` — the background compile warm is only a
    win if it compiles EXACTLY the program the real run then requests,
    so the kernel/padding decision must not be duplicated logic that can
    drift.
    """
    use_pallas: bool
    interpret: bool
    n_genes_pad: int
    row_multiple: int
    data_dim: int
    model_dim: int


def _plan_layout(n_paths: int, n_genes: int, hidden: int,
                 compute_dtype: str, ctx: MeshContext,
                 use_pallas: Optional[bool]) -> _Layout:
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    if ctx.mesh is not None:
        from g2vec_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

        data_dim = ctx.mesh.shape[DATA_AXIS]
        model_dim = ctx.mesh.shape[MODEL_AXIS]
    else:
        data_dim = model_dim = 1

    # Pallas fused packed-matmul path (ops/packed_matmul.py): single-chip,
    # bf16 compute, shapes within the kernel's VMEM budget. The multi-hot
    # stays BIT-PACKED in HBM (16x smaller than dense bf16) and is unpacked
    # tile-by-tile in VMEM fused into the MXU matmul. ``use_pallas=None``
    # auto-detects; True forces it (tests use interpret mode off-TPU).
    if use_pallas is None:
        use_pallas = (
            model_dim == 1 and compute_dtype == "bfloat16"
            and _default_backend() == "tpu"
            and pm.packed_matmul_available(
                n_paths, pad_to_multiple(n_genes, pm.LANE_BLOCK), hidden))
    elif use_pallas:
        # Forced on (tests / power users): enforce the same preconditions the
        # auto-detect checks, loudly — the kernel shards rows (DP), never the
        # gene axis, and computes in bf16.
        if model_dim != 1:
            raise ValueError(
                "use_pallas=True runs per row shard (data parallel); it "
                f"cannot gene-shard — use a Dx1 mesh, got model dim {model_dim}")
        if compute_dtype != "bfloat16":
            raise ValueError("use_pallas=True requires compute_dtype="
                             "'bfloat16' (the kernel computes in bf16)")
        if hidden % 128:
            raise ValueError(f"use_pallas=True requires hidden % 128 == 0, "
                             f"got {hidden}")
    interpret = bool(use_pallas) and _default_backend() != "tpu"

    if use_pallas:
        # Gene axis pads to the kernel's lane block; rows to a full row tile
        # on EVERY data shard.
        n_genes_pad = pad_to_multiple(n_genes, pm.LANE_BLOCK)
        row_multiple = pm.ROW_BLOCK * data_dim
    else:
        # Gene axis pads to a multiple of 8*model_dim so the PACKED byte
        # columns split evenly over the model axis and byte boundaries
        # coincide with shard boundaries.
        n_genes_pad = pad_to_multiple(n_genes, 8 * model_dim)
        row_multiple = data_dim
    return _Layout(bool(use_pallas), interpret, n_genes_pad, row_multiple,
                   data_dim, model_dim)


def train_cbow(paths: np.ndarray, labels: np.ndarray, *,
               hidden: int, learning_rate: float, max_epochs: int,
               val_fraction: float = 0.2, decision_threshold: float = 0.5,
               compute_dtype: str = "bfloat16", param_dtype: str = "float32",
               seed: int = 0, mesh_ctx: Optional[MeshContext] = None,
               on_epoch: Optional[Callable[[int, float, float, float], None]] = None,
               checkpoint_dir: Optional[str] = None, resume: bool = False,
               checkpoint_every: int = 25, use_pallas: Optional[bool] = None,
               packed_genes: Optional[int] = None,
               checkpoint_layout: str = "single",
               pre_compile_hook: Optional[Callable[[], None]] = None,
               ) -> TrainResult:
    """Train the modified CBOW; returns the embedding table and history.

    ``paths``: [n_paths, n_genes] multi-hot (any integer/float dtype) — or,
    with ``packed_genes=G``, the bit-packed [n_paths, ceil(G/8)] uint8 form
    (np.packbits layout, e.g. from ``integrate_path_sets(packed=True)``);
    the dense matrix is then never materialized whole on the host.
    ``labels``: [n_paths] in {0, 1}. ``on_epoch(step, acc_val, acc_tr, secs)``
    fires every epoch so the CLI can render the reference's log cadence.
    """
    if paths.shape[0] < 2:
        raise ValueError(f"need at least 2 paths to split, got {paths.shape[0]}")
    ctx = mesh_ctx if mesh_ctx is not None else make_mesh_context(None)
    if compute_dtype not in _DTYPES:
        raise ValueError(
            f"compute_dtype must be one of {sorted(_DTYPES)}, got {compute_dtype!r}")
    if param_dtype not in _DTYPES:
        raise ValueError(
            f"param_dtype must be one of {sorted(_DTYPES)}, got {param_dtype!r}")
    cdtype = _DTYPES[compute_dtype]
    pdtype = _DTYPES[param_dtype]
    if packed_genes is not None:
        n_paths, nb_in = paths.shape
        n_genes = packed_genes
        if nb_in != (n_genes + 7) // 8 or paths.dtype != np.uint8:
            raise ValueError(
                f"packed_genes={n_genes} expects uint8 paths of width "
                f"{(n_genes + 7) // 8}, got {paths.dtype} width {nb_in}")
    else:
        n_paths, n_genes = paths.shape

    # ---- shuffled hold-out split (ref: G2Vec.py:219-226) ----
    rng = np.random.default_rng(seed)
    perm = rng.permutation(n_paths)
    pivot = int(n_paths * (1.0 - val_fraction))
    if pivot in (0, n_paths):
        raise ValueError(
            f"val_fraction={val_fraction} leaves an empty split for {n_paths} paths")
    tr_idx, vl_idx = perm[:pivot], perm[pivot:]

    # ---- shard-even padding (SPMD needs dims divisible by mesh axes) ----
    # Rows pad to a multiple of the data axis, the gene axis to a multiple of
    # the model axis. Padding rows carry weight 0 (masked means above);
    # padding gene columns are all-zero in X, so the matching W_ih rows get
    # exactly zero gradient and are sliced off before returning. The whole
    # kernel/padding decision lives in _plan_layout — shared with
    # warm_train_compile, which must predict this run's programs exactly.
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    plan = _plan_layout(n_paths, n_genes, hidden, compute_dtype, ctx,
                        use_pallas)
    use_pallas = plan.use_pallas
    pallas_interpret = plan.interpret
    n_genes_pad = plan.n_genes_pad
    row_multiple = plan.row_multiple
    if not use_pallas:
        unpack_fn = _get_unpack_fn(ctx, cdtype)

    def _prep(idx):
        # The multi-hot crosses the host->device boundary as packed bits
        # (8 genes/byte) and — in the XLA path — is unpacked + cast on
        # device: a ~13x smaller transfer than shipping bf16, and no
        # host-side ml_dtypes cast of a third of a billion elements. In the
        # pallas path it additionally STAYS packed in HBM.
        n_rows = len(idx)
        y = labels[idx].astype(np.float32).reshape(-1, 1)
        n_pad = pad_to_multiple(n_rows, row_multiple)
        w = _pad_rows(np.ones((n_rows, 1), np.float32), n_pad)
        # Repack row chunks into the device layout; host temp memory stays
        # bounded (one chunk of dense bools) even at pod-scale path counts.
        packed = np.zeros((n_pad, n_genes_pad // 8), dtype=np.uint8)
        if (packed_genes is not None and not use_pallas
                and paths.shape[1] == n_genes_pad // 8):
            # Input packbits layout == device layout (single-chip XLA path):
            # no bit round-trip at all, just a row gather.
            packed[:n_rows] = paths[idx]
        else:
            chunk_rows = 8192
            for lo in range(0, n_rows, chunk_rows):
                sel = idx[lo:lo + chunk_rows]
                if packed_genes is not None:
                    rows = np.unpackbits(paths[sel], axis=1)[:, :n_genes] != 0
                else:
                    rows = paths[sel] != 0
                # One zeroed buffer provides the gene padding.
                xb = np.zeros((len(sel), n_genes_pad), dtype=bool)
                xb[:, :n_genes] = rows
                packed[lo:lo + len(sel)] = (
                    pm.pack_blockwise(xb) if use_pallas
                    else np.packbits(xb, axis=1))
        y_dev = ctx.put(_pad_rows(y, n_pad), ctx.label_spec)
        w_dev = ctx.put(w, ctx.label_spec)
        if use_pallas:
            return ctx.put(packed, ctx.packed_batch_spec), y_dev, w_dev
        return unpack_fn(ctx.put(packed, ctx.batch_spec)), y_dev, w_dev

    xtr, ytr, wtr = _prep(tr_idx)
    xval, yval, wval = _prep(vl_idx)

    # ---- params + optimizer ----
    key = jax.random.key(seed)
    params = init_params(key, n_genes_pad, hidden, param_dtype=pdtype)
    if ctx.mesh is not None:
        params = CBOWParams(w_ih=ctx.put(params.w_ih, ctx.w_ih_spec),
                            w_ho=ctx.put(params.w_ho, ctx.w_ho_spec))
    # tx here only initializes the optimizer state; the cached chunk fn
    # builds an identical transformation from the same hyperparameters.
    tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
    opt_state = tx.init(params)
    if ctx.mesh is not None:
        # Adam's mu/nu inherit the params' shardings through tree_map, but
        # the step-count scalar lands on the default device. Replicate it
        # over the mesh NOW: jit would do so transparently, but a sharded
        # checkpoint restore uses this state as its sharding template, and
        # a single-device template forces an (unsupported on multi-host
        # CPU) cross-host transfer at resume.
        from jax.sharding import PartitionSpec as P

        opt_state = jax.tree.map(
            lambda sub: (sub if isinstance(sub, CBOWParams)
                         else ctx.put(sub, P())),
            opt_state, is_leaf=lambda x: isinstance(x, CBOWParams))
    # Epochs per device dispatch: align to the checkpoint cadence when
    # checkpointing (a chunk boundary is a save point), else amortize the
    # host round trip over DEFAULT_CHUNK epochs.
    chunk = checkpoint_every if checkpoint_dir else DEFAULT_CHUNK
    chunk = max(1, min(chunk, max_epochs))
    if pre_compile_hook is not None:
        # The overlap scheduler joins its background warm_train_compile
        # here — AFTER the host-side _prep packing it overlapped, right
        # before the chunk-fn request that wants the warmed executable.
        pre_compile_hook()
    chunk_fn = _get_chunk_fn(learning_rate, cdtype, decision_threshold, ctx,
                             chunk, packed=use_pallas,
                             interpret=pallas_interpret)

    # ---- epoch loop with first-val-dip early stopping ----
    history: List[dict] = []
    before_val, before_tr = -1.0, -1.0
    snapshot = params            # device-side reference, no copy
    start_epoch = 0
    stopped_early = False
    stop_epoch = max_epochs - 1
    # Recorded in the checkpoint manifest and checked on resume: same-shape
    # config drift (changed lr/seed/dtype) must fail loudly, not blend two
    # runs. max_epochs is deliberately absent — extending it is supported.
    ckpt_fingerprint = {
        "hidden": hidden, "learning_rate": learning_rate,
        "compute_dtype": compute_dtype, "param_dtype": param_dtype,
        "seed": seed, "val_fraction": val_fraction,
        "decision_threshold": decision_threshold,
        "n_genes_pad": int(n_genes_pad),
    }
    if checkpoint_dir and resume:
        from g2vec_tpu.train.checkpoint import (RUN_EARLY_STOPPED,
                                                RUN_IN_PROGRESS, load_state)

        restored = load_state(checkpoint_dir, params, opt_state,
                              layout=checkpoint_layout,
                              fingerprint=ckpt_fingerprint)
        if restored is not None:
            (params, opt_state, snapshot, last_epoch,
             before_val, before_tr, done) = restored
            if ctx.mesh is not None:
                # Re-apply the DP/TP shardings the fresh-init path declares,
                # or the resumed program compiles with replicated (possibly
                # OOM-ing) params. Single layout hands back host arrays;
                # sharded layout hands back device arrays already on the
                # right shardings for the big leaves (device_put is then a
                # no-op) but its scalar leaves (Adam count) restore onto
                # the fresh init's single-device placement and must be
                # re-replicated over the mesh. Classification is by tree
                # position (CBOWParams containers inside
                # params/opt_state/snapshot), never by shape — shapes are
                # ambiguous when hidden == n_genes_pad.
                from jax.sharding import PartitionSpec as P

                def _reshard_params(p: CBOWParams) -> CBOWParams:
                    return CBOWParams(
                        w_ih=ctx.put(p.w_ih, ctx.w_ih_spec),
                        w_ho=ctx.put(p.w_ho, ctx.w_ho_spec))

                params = _reshard_params(params)
                snapshot = _reshard_params(snapshot)
                opt_state = jax.tree.map(
                    lambda sub: (_reshard_params(sub)
                                 if isinstance(sub, CBOWParams)
                                 else ctx.put(sub, P())),
                    opt_state,
                    is_leaf=lambda x: isinstance(x, CBOWParams))
            if (done == RUN_EARLY_STOPPED
                    or (done != RUN_IN_PROGRESS and last_epoch + 1 >= max_epochs)):
                # Terminal state: an early stop is final (stepping on would
                # re-apply the dip epoch's update — the saved params are
                # post-dip, the snapshot pre-dip), and a completed run with
                # no additional epoch budget has nothing to do. A completed
                # run CAN continue when max_epochs was raised.
                from g2vec_tpu.parallel.distributed import fetch_global

                w_ih = fetch_global(snapshot.w_ih).astype(np.float32)[:n_genes]
                return TrainResult(
                    w_ih=w_ih, stop_epoch=last_epoch,
                    stopped_early=(done == RUN_EARLY_STOPPED),
                    acc_val=before_val, acc_tr=before_tr,
                    history=[], params=snapshot)
            start_epoch = last_epoch + 1
    t0 = time.time()
    step = step_start = start_epoch
    while step < max_epochs and not stopped_early:
        limit = min(chunk, max_epochs - step)
        (params, opt_state, snapshot, bv_d, bt_d, count_d, dip_d, hist_d
         ) = chunk_fn(params, opt_state, snapshot, before_val, before_tr,
                      limit, xtr, ytr, wtr, xval, yval, wval)
        count = int(count_d)                     # the only host sync per chunk
        stopped_early = bool(dip_d)
        before_val, before_tr = float(bv_d), float(bt_d)
        hist = np.asarray(jax.device_get(hist_d))[:count]
        secs = (time.time() - t0) / max(count, 1)
        t0 = time.time()
        from g2vec_tpu.resilience.faults import fault_point

        for j in range(count):
            av, at, ls = (float(hist[j, 0]), float(hist[j, 1]), float(hist[j, 2]))
            history.append({"epoch": step + j, "acc_val": av, "acc_tr": at,
                            "loss": ls, "secs": secs})
            if on_epoch is not None:
                on_epoch(step + j, av, at, secs)
            # The train-loop fault seam: fires at the host-side epoch
            # callback (the epoch's device work is done, its checkpoint may
            # not be) — the exact place a preemption hurts most.
            fault_point("train", epoch=step + j)
        step += count
        if stopped_early:
            stop_epoch = step - 2                # dip epoch minus one
        elif checkpoint_dir and step > step_start:
            from g2vec_tpu.train.checkpoint import save_state

            save_state(checkpoint_dir, params, opt_state, snapshot,
                       step - 1, before_val, before_tr,
                       layout=checkpoint_layout,
                       fingerprint=ckpt_fingerprint)

    if checkpoint_dir:
        from g2vec_tpu.train.checkpoint import (RUN_COMPLETED,
                                                RUN_EARLY_STOPPED, save_state)

        save_state(checkpoint_dir, params, opt_state, snapshot,
                   stop_epoch if stopped_early else max_epochs - 1,
                   before_val, before_tr,
                   done=RUN_EARLY_STOPPED if stopped_early else RUN_COMPLETED,
                   layout=checkpoint_layout,
                   fingerprint=ckpt_fingerprint)
    from g2vec_tpu.parallel.distributed import fetch_global

    w_ih = fetch_global(snapshot.w_ih).astype(np.float32)[:n_genes]
    return TrainResult(w_ih=w_ih, stop_epoch=stop_epoch,
                       stopped_early=stopped_early,
                       acc_val=before_val, acc_tr=before_tr,
                       history=history, params=snapshot)


def warm_train_compile(n_paths: int, n_genes: int, *, hidden: int,
                       learning_rate: float, max_epochs: int,
                       val_fraction: float = 0.2,
                       decision_threshold: float = 0.5,
                       compute_dtype: str = "bfloat16",
                       param_dtype: str = "float32",
                       mesh_ctx: Optional[MeshContext] = None,
                       checkpoint_dir: Optional[str] = None,
                       checkpoint_every: int = 25,
                       use_pallas: Optional[bool] = None) -> bool:
    """Compile the chunk (and unpack) programs train_cbow will run at
    these shapes, without training anything.

    The overlap scheduler (parallel/overlap.py) calls this in the
    BACKGROUND the moment ``n_paths`` is known (right after
    integrate_path_sets), so the multi-second XLA compile runs while the
    foreground is still counting gene frequencies and bit-packing the
    path matrix — by the time train_cbow asks for the chunk fn, the LRU
    already holds the compiled executable. Identity with the real
    request is structural: the same _plan_layout/_get_chunk_fn derivation
    from the same arguments produces the same cache key, and the dummy
    zero inputs here have exactly the shapes/dtypes/shardings _prep
    produces (the jit executable cache keys on those, never on values).

    The warm call runs the chunk program once with ``limit=0``: the
    device while_loop exits before epoch 0 and only the per-chunk
    accuracy backfill executes — one eval forward, trivial next to the
    compile it buys. Returns True when the programs were warmed, False
    for degenerate shapes train_cbow would reject anyway (its own error
    messages are the better report).
    """
    if n_paths < 2 or compute_dtype not in _DTYPES \
            or param_dtype not in _DTYPES:
        return False
    pivot = int(n_paths * (1.0 - val_fraction))
    if pivot in (0, n_paths):
        return False
    ctx = mesh_ctx if mesh_ctx is not None else make_mesh_context(None)
    cdtype = _DTYPES[compute_dtype]
    pdtype = _DTYPES[param_dtype]
    from g2vec_tpu.parallel.mesh import pad_to_multiple

    plan = _plan_layout(n_paths, n_genes, hidden, compute_dtype, ctx,
                        use_pallas)
    chunk = checkpoint_every if checkpoint_dir else DEFAULT_CHUNK
    chunk = max(1, min(chunk, max_epochs))
    chunk_fn = _get_chunk_fn(learning_rate, cdtype, decision_threshold, ctx,
                             chunk, packed=plan.use_pallas,
                             interpret=plan.interpret)

    def dummy(n_rows):
        n_pad = pad_to_multiple(n_rows, plan.row_multiple)
        y = ctx.put(np.zeros((n_pad, 1), np.float32), ctx.label_spec)
        w = ctx.put(_pad_rows(np.ones((n_rows, 1), np.float32), n_pad),
                    ctx.label_spec)
        packed = np.zeros((n_pad, plan.n_genes_pad // 8), dtype=np.uint8)
        if plan.use_pallas:
            return ctx.put(packed, ctx.packed_batch_spec), y, w
        return _get_unpack_fn(ctx, cdtype)(
            ctx.put(packed, ctx.batch_spec)), y, w

    xtr, ytr, wtr = dummy(pivot)
    xval, yval, wval = dummy(n_paths - pivot)
    params = init_params(jax.random.key(0), plan.n_genes_pad, hidden,
                         param_dtype=pdtype)
    if ctx.mesh is not None:
        params = CBOWParams(w_ih=ctx.put(params.w_ih, ctx.w_ih_spec),
                            w_ho=ctx.put(params.w_ho, ctx.w_ho_spec))
    tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
    opt_state = tx.init(params)
    if ctx.mesh is not None:
        from jax.sharding import PartitionSpec as P

        opt_state = jax.tree.map(
            lambda sub: (sub if isinstance(sub, CBOWParams)
                         else ctx.put(sub, P())),
            opt_state, is_leaf=lambda x: isinstance(x, CBOWParams))
    out = chunk_fn(params, opt_state, params, -1.0, -1.0, 0,
                   xtr, ytr, wtr, xval, yval, wval)
    jax.block_until_ready(out[5])      # the epoch count — compile is done
    return True
