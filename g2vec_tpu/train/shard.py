"""Gene-range-sharded CBOW step programs (ROADMAP item 2).

The unsharded streaming step (trainer._make_stream_fns) is ONE jitted
program: forward both matmuls, grad, Adam. That program needs the full
``[G, H]`` embedding on one device — the exact memory cap this module
removes. Here the step is split at the only point where cross-rank data
flows: the hidden activations. Each rank holds the byte-aligned gene
range ``[lo, hi)`` of ``W_ih`` (parallel/shard.ShardSpec) plus a
REPLICATED head ``w_ho``, and one minibatch step is:

1. :func:`partial_hidden` (local jit): unpack the rank's packed byte
   columns of the shard and contract them with the local ``W_ih`` slice
   — ``h_part = X_local @ W_ih_local`` in f32.
2. Host allreduce-sum of ``h_part`` across ranks (the "psum"; on CPU
   fleets it rides the KV transport — parallel/shard.ShardContext).
   Rank-order summation makes the reduced ``h`` bit-identical on every
   rank.
3. :func:`head_grads` (local jit, replicated math): loss + gradients of
   the masked BCE w.r.t. ``(w_ho, h)``. Identical inputs on every rank
   produce identical ``dw_ho``/``dh`` — which is what keeps ``w_ho``
   replicated with NO second collective.
4. :func:`embed_grad` (local jit): ``dW_local = X_local^T @ dh`` — each
   rank computes exactly its own slice's gradient; nothing to reduce.
5. A local Adam step over ``(W_ih_local, w_ho)`` (the caller owns the
   optax state; train/stream.py jits the apply).

Dtype discipline mirrors models/cbow.py verbatim: inputs cast to the
compute dtype, every contraction accumulates f32 via
``preferred_element_type``, the decision threshold is applied in logit
space. One step costs ONE collective of ``[rows, H]`` f32 — independent
of G, the property that makes the per-rank footprint ``O(G/R * H)``.

The single-rank sharded path never reaches this module (train/stream.py
routes R == 1 through the plain programs — the byte-identity contract);
at R > 1 the reduction order of ``h`` differs from the one-matmul
program, so parity vs unsharded is the PR 7 statistical contract.
"""
from __future__ import annotations

from math import sqrt
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from g2vec_tpu.models.cbow import (CBOWParams, accuracy_from_logits,
                                   masked_bce_loss, output_logits)


class SplitStepFns(NamedTuple):
    partial_hidden: object   # (w_ih_local, x_packed) -> [rows, H] f32
    head_grads: object       # (w_ho, h, y, w) -> (loss, dw_ho, dh)
    embed_grad: object       # (x_packed, dh) -> [g_pad_local, H] f32
    head_eval: object        # (w_ho, h, y, w) -> accuracy f32


def _unpack_bits(packed: jax.Array, compute_dtype) -> jax.Array:
    """[rows, nb] uint8 -> [rows, nb*8] compute-dtype multi-hot — the
    trainer's device-side unpack (np.packbits order, MSB first) over a
    rank's byte-column slice. Bits past the last real gene are zero in
    the data, so the trailing pad columns contract against (and
    gradient into) the zero pad rows of the local table — dead weight,
    the init_params pad-row argument applied to a range slice."""
    shifts = jnp.arange(7, -1, -1, dtype=jnp.uint8)
    bits = (packed[:, :, None] >> shifts) & jnp.uint8(1)
    return bits.reshape(packed.shape[0], -1).astype(compute_dtype)


def make_split_fns(compute_dtype, decision_threshold: float) -> SplitStepFns:
    """The four jitted halves of one sharded step (module docstring).
    Built per run (train/stream.py holds them for the run's lifetime, so
    the jit caches live exactly as long as they are useful)."""
    logit_threshold = float(np.log(decision_threshold
                                   / (1.0 - decision_threshold)))

    def partial_hidden(w_ih_local, x_packed):
        x = _unpack_bits(x_packed, compute_dtype)
        return jax.lax.dot_general(
            x, w_ih_local.astype(compute_dtype),
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def head_loss(w_ho, h, y, w):
        return masked_bce_loss(output_logits(h, w_ho, compute_dtype), y, w)

    def head_grads(w_ho, h, y, w):
        loss, (dw_ho, dh) = jax.value_and_grad(
            head_loss, argnums=(0, 1))(w_ho, h, y, w)
        return loss, dw_ho, dh

    def embed_grad(x_packed, dh):
        x = _unpack_bits(x_packed, compute_dtype)
        # dW_local = X_local^T @ dh, contracted over the row axis — the
        # same cast-to-compute/accumulate-f32 recipe as the forward.
        return jax.lax.dot_general(
            x, dh.astype(compute_dtype),
            (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    def head_eval(w_ho, h, y, w):
        return accuracy_from_logits(output_logits(h, w_ho, compute_dtype),
                                    y, w, logit_threshold)

    return SplitStepFns(partial_hidden=jax.jit(partial_hidden),
                        head_grads=jax.jit(head_grads),
                        embed_grad=jax.jit(embed_grad),
                        head_eval=jax.jit(head_eval))


def init_split_params(key, n_genes: int, hidden: int, spec,
                      param_dtype=jnp.float32) -> CBOWParams:
    """The rank-local twin of models/cbow.init_params: ``w_ih`` holds
    this rank's gene range of THE SAME [G, H] truncated-normal draw the
    unsharded init makes for this seed, padded with zero rows to the
    byte-aligned local width; ``w_ho`` is drawn from k2 identically on
    every rank (replicated by construction, kept replicated by the
    deterministic reduction — module docstring).

    Slice-of-the-same-draw matters: jax.random counts over the
    flattened full shape, so per-rank keys would start every rank in an
    UNRELATED embedding space — the sharded run would then converge to
    embeddings uncorrelated with the unsharded run's and the biomarker-
    overlap half of the parity contract would be vacuous. Each rank
    therefore materializes the full [G, H] init ONCE, slices its range
    and drops the rest — a transient (512 MB at 1M x 128), init-only,
    and the price of keeping sharded-vs-unsharded a perturbation (the
    reduced-h summation order) instead of a different model.
    """
    k1, k2 = jax.random.split(key)
    std = 1.0 / sqrt(hidden)
    blo, bhi = spec.byte_range()
    g_pad_local = (bhi - blo) * 8
    full = jax.random.truncated_normal(k1, -2.0, 2.0, (n_genes, hidden))
    w_ih = full[spec.lo:spec.hi] * std
    del full
    if g_pad_local > spec.g_local:
        w_ih = jnp.concatenate(
            [w_ih, jnp.zeros((g_pad_local - spec.g_local, hidden),
                             w_ih.dtype)], axis=0)
    w_ho = jax.random.truncated_normal(k2, -2.0, 2.0, (hidden, 1)) * std
    return CBOWParams(w_ih=w_ih.astype(param_dtype),
                      w_ho=w_ho.astype(param_dtype))
