"""L4 — training: the modified-CBOW trainer, checkpointing."""
from g2vec_tpu.train.trainer import TrainResult, train_cbow  # noqa: F401
