"""Checkpoint / resume for the CBOW trainer.

The reference has no checkpointing at all — training state lives only inside
the TF session and dies with the process (SURVEY.md §5 "Checkpoint/resume").
Here the full trainer state — params, Adam state, the early-stopping
snapshot/accuracy pair, and the epoch counter — round-trips through a single
``.npz`` so an interrupted run resumes mid-epoch-loop with identical
numerics (full-batch training has no data-order state to restore).

Format: pytree leaves flattened in deterministic order and keyed by index,
plus a scalar metadata array. Restoring unflattens against a freshly
initialized state's treedef, so the format never hard-codes optax internals.
Writes are atomic (tmp file + ``os.replace``) so a crash mid-write can't
corrupt the latest checkpoint.
"""
from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

CKPT_NAME = "cbow_state.npz"


# ``done`` codes in the meta record: the trainer refuses to continue a
# finished run on --resume (it would re-apply updates on top of themselves
# after an early stop — the saved params are post-dip, the snapshot pre-dip).
RUN_IN_PROGRESS = 0
RUN_COMPLETED = 1      # reached max_epochs
RUN_EARLY_STOPPED = 2  # first val-accuracy dip


def save_state(directory: str, params: Any, opt_state: Any, snapshot: Any,
               epoch: int, before_val: float, before_tr: float,
               done: int = RUN_IN_PROGRESS) -> str:
    """Atomically write the full trainer state under ``directory``.

    Multi-host safe: gathering the (possibly cross-process-sharded) leaves
    is a collective every process performs; only process 0 touches the
    filesystem, so N hosts on a shared checkpoint_dir never race.
    """
    from g2vec_tpu.parallel.distributed import fetch_global

    leaves, _ = jax.tree_util.tree_flatten((params, opt_state, snapshot))
    arrays = {f"leaf_{i}": fetch_global(leaf) for i, leaf in enumerate(leaves)}
    arrays["meta"] = np.array([float(epoch), before_val, before_tr, float(done)])
    path = os.path.join(directory, CKPT_NAME)
    if jax.process_index() != 0:
        return path
    os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    np.savez(tmp, **arrays)
    # np.savez appends .npz to names without it.
    os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp, path)
    return path


def _read_leaves(path: str, like_leaves) -> Optional[Tuple[list, np.ndarray]]:
    """Read + validate the npz against the expected leaf shapes/dtypes."""
    if not os.path.exists(path):
        return None
    with np.load(path) as data:
        leaves = [data[f"leaf_{i}"] for i in range(len(like_leaves))]
        meta = data["meta"]
    if meta.shape[0] == 3:      # legacy pre-`done` meta: normalize the shape
        meta = np.append(meta, float(RUN_IN_PROGRESS))
    for i, (got, want) in enumerate(zip(leaves, like_leaves)):
        if hasattr(want, "shape") and tuple(got.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"checkpoint {path}: leaf {i} has shape {got.shape}, current "
                f"model expects {np.shape(want)} — was the config changed "
                "between save and resume?")
        # np.savez stores ml_dtypes types (bfloat16 et al.) as raw void
        # bytes; reinterpret them against the expected leaf's dtype so a
        # bf16-param checkpoint round-trips instead of surfacing as '|V2'.
        want_dtype = _leaf_dtype(want)
        if got.dtype.kind == "V" and got.dtype != want_dtype:
            leaves[i] = got.view(want_dtype)
    return leaves, meta


def _leaf_dtype(want) -> np.dtype:
    """Expected dtype of a template leaf WITHOUT materializing its value
    (np.asarray on a cross-process-sharded array raises)."""
    return np.dtype(want.dtype) if hasattr(want, "dtype") else \
        np.asarray(want).dtype


def load_state(directory: str, params_like: Any, opt_state_like: Any
               ) -> Optional[Tuple[Any, Any, Any, int, float, float, int]]:
    """Restore (params, opt_state, snapshot, epoch, before_val, before_tr, done).

    ``params_like`` / ``opt_state_like`` supply the treedefs (from a fresh
    init at the same shapes). Returns None when no checkpoint exists; raises
    with a clear message on a shape mismatch (e.g. resuming with a different
    ``--sizeHiddenlayer``).

    Multi-host safe on BOTH sides (ADVICE.md round 1): only process 0 reads
    the file, then the state is broadcast — so ``checkpoint_dir`` need not
    be a shared filesystem, and a stale worker copy can never produce
    silently divergent parameters. This is a collective: every process must
    call it.
    """
    path = os.path.join(directory, CKPT_NAME)
    like = (params_like, opt_state_like, params_like)
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if jax.process_count() > 1:
        loaded = _broadcast_from_coordinator(path, like_leaves)
    else:
        loaded = _read_leaves(path, like_leaves)
    if loaded is None:
        return None
    leaves, meta = loaded
    params, opt_state, snapshot = jax.tree_util.tree_unflatten(treedef, leaves)
    done = int(meta[3]) if meta.shape[0] > 3 else RUN_IN_PROGRESS
    return (params, opt_state, snapshot,
            int(meta[0]), float(meta[1]), float(meta[2]), done)


def _broadcast_from_coordinator(path: str, like_leaves
                                ) -> Optional[Tuple[list, np.ndarray]]:
    """Process 0 reads the npz; every process receives the same state.

    The status scalar goes first so a missing file or a validation error on
    the coordinator surfaces as the SAME outcome on every process instead of
    a hang in a half-entered collective.
    """
    from jax.experimental import multihost_utils

    status = 0          # 0 = no checkpoint, 1 = ok, 2 = coordinator error
    leaves, meta, err = None, None, ""
    if jax.process_index() == 0:
        try:
            loaded = _read_leaves(path, like_leaves)
            if loaded is not None:
                leaves, meta = loaded
                status = 1
        # Broad on purpose: ANY coordinator-side read failure (corrupt zip,
        # missing key, shape mismatch) must still reach the status
        # broadcast, or the other processes hang in a half-entered
        # collective.
        except Exception as e:  # noqa: BLE001
            status, err = 2, f"{type(e).__name__}: {e}"
    status = int(multihost_utils.broadcast_one_to_all(np.int32(status)))
    if status == 0:
        return None
    if status == 2:
        raise ValueError(
            f"checkpoint restore failed on the coordinator: "
            f"{err or '(see process 0 logs)'}")
    # One collective for the whole state: non-coordinators contribute
    # shape/dtype-matched zero protos (their values are ignored).
    if leaves is None:
        leaves = [np.zeros(np.shape(w), _leaf_dtype(w)) for w in like_leaves]
        meta = np.zeros(4, np.float64)
    out, meta_b = multihost_utils.broadcast_one_to_all((leaves, meta))
    return [np.asarray(x) for x in out], np.asarray(meta_b)
