"""Checkpoint / resume for the CBOW trainer.

The reference has no checkpointing at all — training state lives only inside
the TF session and dies with the process (SURVEY.md §5 "Checkpoint/resume").
Here the full trainer state — params, Adam state, the early-stopping
snapshot/accuracy pair, and the epoch counter — round-trips so an
interrupted run resumes mid-epoch-loop with identical numerics (full-batch
training has no data-order state to restore). Two layouts:

- ``layout="single"`` (default): one atomic ``.npz``. The save gathers the
  full state (a collective) and process 0 writes; the restore is
  coordinator-read + broadcast, so ``checkpoint_dir`` need NOT be shared
  across hosts. Right for example-scale states (a few hundred MB).
- ``layout="sharded"``: orbax/tensorstore OCDBT — every process writes only
  its own addressable shards (``ocdbt.process_N`` files) and restores only
  what its devices need, with shardings preserved; the full state NEVER
  materializes on any single host (round-1 verdict #7: at pod scale the
  gather is multi-GB of host traffic per save). Requires a SHARED
  checkpoint_dir across processes, like any sharded checkpoint format.

Both layouts store pytree leaves flattened in deterministic order and keyed
by index, plus a scalar metadata array — the format never hard-codes optax
internals. Writes are atomic in both (tmp + rename; orbax does its own
finalize-rename dance).
"""
from __future__ import annotations

import os
from typing import Any, Optional, Tuple

import jax
import numpy as np

CKPT_NAME = "cbow_state.npz"
SHARDED_NAME = "cbow_state_ocdbt"


# ``done`` codes in the meta record: the trainer refuses to continue a
# finished run on --resume (it would re-apply updates on top of themselves
# after an early stop — the saved params are post-dip, the snapshot pre-dip).
RUN_IN_PROGRESS = 0
RUN_COMPLETED = 1      # reached max_epochs
RUN_EARLY_STOPPED = 2  # first val-accuracy dip


def save_state(directory: str, params: Any, opt_state: Any, snapshot: Any,
               epoch: int, before_val: float, before_tr: float,
               done: int = RUN_IN_PROGRESS, layout: str = "single") -> str:
    """Atomically write the full trainer state under ``directory``.

    Collective: every process must call it. ``layout="single"`` gathers and
    process 0 writes one npz; ``layout="sharded"`` writes per-process orbax
    shards and never gathers (see module docstring for the trade-off).
    """
    meta = np.array([float(epoch), before_val, before_tr, float(done)])
    if layout == "sharded":
        return _save_sharded(directory, (params, opt_state, snapshot), meta)
    if layout != "single":
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    from g2vec_tpu.parallel.distributed import fetch_global

    leaves, _ = jax.tree_util.tree_flatten((params, opt_state, snapshot))
    arrays = {f"leaf_{i}": fetch_global(leaf) for i, leaf in enumerate(leaves)}
    arrays["meta"] = meta
    path = os.path.join(directory, CKPT_NAME)
    if jax.process_index() != 0:
        return path
    os.makedirs(directory, exist_ok=True)
    tmp = path + ".tmp"
    np.savez(tmp, **arrays)
    # np.savez appends .npz to names without it.
    os.replace(tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp, path)
    return path


def _leaf_dict(tree: Any, meta: Optional[np.ndarray] = None) -> dict:
    """Index-keyed flat dict — names custom pytree nodes (NamedTuples,
    optax states) out of the storage format entirely."""
    leaves, _ = jax.tree_util.tree_flatten(tree)
    d = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
    if meta is not None:
        d["meta"] = meta
    return d


_LATEST_NAME = SHARDED_NAME + ".LATEST"


def _save_sharded(directory: str, state: Any, meta: np.ndarray) -> str:
    """Keep-previous atomic save: each save goes to a FRESH numbered dir,
    then the LATEST pointer file flips atomically and process 0 prunes the
    older dirs. A crash mid-save leaves the previous checkpoint fully
    intact (orbax's force=True would rmtree it BEFORE committing the new
    one — the exact window checkpointing exists to survive)."""
    import orbax.checkpoint as ocp

    base = os.path.abspath(directory)
    os.makedirs(base, exist_ok=True)
    # Every process lists the same shared dir BEFORE the collective save
    # creates anything, so all agree on the next index (orphans from an
    # earlier crash only push the index up, never collide).
    existing = [int(n.rsplit(".", 1)[1]) for n in os.listdir(base)
                if n.startswith(SHARDED_NAME + ".")
                and n.rsplit(".", 1)[1].isdigit()]
    name = f"{SHARDED_NAME}.{max(existing, default=-1) + 1}"
    path = os.path.join(base, name)
    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, args=ocp.args.PyTreeSave(_leaf_dict(state, meta)))
    if jax.process_index() == 0:
        tmp = os.path.join(base, _LATEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            f.write(name)
        os.replace(tmp, os.path.join(base, _LATEST_NAME))
        for idx in existing:
            import shutil

            shutil.rmtree(os.path.join(base, f"{SHARDED_NAME}.{idx}"),
                          ignore_errors=True)
    return path


def _latest_sharded_dir(directory: str) -> Optional[str]:
    pointer = os.path.join(os.path.abspath(directory), _LATEST_NAME)
    if not os.path.exists(pointer):
        return None
    with open(pointer) as f:
        name = f.read().strip()
    path = os.path.join(os.path.abspath(directory), name)
    return path if os.path.isdir(path) else None


def _load_sharded(directory: str, like_leaves
                  ) -> Optional[Tuple[list, np.ndarray]]:
    """Restore per-process shards with the LIKE tree's shardings preserved.

    ``like_leaves`` must be device arrays (a freshly initialized, correctly
    sharded state) — orbax restores each leaf directly onto those shardings,
    so every process reads only its own devices' slices.
    """
    import orbax.checkpoint as ocp

    path = _latest_sharded_dir(directory)
    if path is None:
        return None
    like = _leaf_dict(like_leaves, np.zeros(4, np.float64))
    with ocp.PyTreeCheckpointer() as ckptr:
        # Validate shapes against the stored metadata FIRST, so a config
        # change surfaces as the same clear error the single layout raises
        # instead of an obscure tensorstore chunk mismatch.
        stored = ckptr.metadata(path).item_metadata.tree
        for i, want in enumerate(like_leaves):
            got = stored.get(f"leaf_{i}")
            got_shape = tuple(getattr(got, "shape", ()) or ())
            if (hasattr(want, "shape")
                    and got_shape != tuple(np.shape(want))):
                raise ValueError(
                    f"checkpoint {path}: leaf {i} has shape {got_shape}, "
                    f"current model expects {np.shape(want)} — was the "
                    "config changed between save and resume?")
        restore_args = ocp.checkpoint_utils.construct_restore_args(like)
        out = ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=like, restore_args=restore_args))
    leaves = [out[f"leaf_{i}"] for i in range(len(like_leaves))]
    return leaves, np.asarray(out["meta"])


def _read_leaves(path: str, like_leaves) -> Optional[Tuple[list, np.ndarray]]:
    """Read + validate the npz against the expected leaf shapes/dtypes."""
    if not os.path.exists(path):
        return None
    with np.load(path) as data:
        leaves = [data[f"leaf_{i}"] for i in range(len(like_leaves))]
        meta = data["meta"]
    if meta.shape[0] == 3:      # legacy pre-`done` meta: normalize the shape
        meta = np.append(meta, float(RUN_IN_PROGRESS))
    for i, (got, want) in enumerate(zip(leaves, like_leaves)):
        if hasattr(want, "shape") and tuple(got.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"checkpoint {path}: leaf {i} has shape {got.shape}, current "
                f"model expects {np.shape(want)} — was the config changed "
                "between save and resume?")
        # np.savez stores ml_dtypes types (bfloat16 et al.) as raw void
        # bytes; reinterpret them against the expected leaf's dtype so a
        # bf16-param checkpoint round-trips instead of surfacing as '|V2'.
        want_dtype = _leaf_dtype(want)
        if got.dtype.kind == "V" and got.dtype != want_dtype:
            leaves[i] = got.view(want_dtype)
    return leaves, meta


def _leaf_dtype(want) -> np.dtype:
    """Expected dtype of a template leaf WITHOUT materializing its value
    (np.asarray on a cross-process-sharded array raises)."""
    return np.dtype(want.dtype) if hasattr(want, "dtype") else \
        np.asarray(want).dtype


def load_state(directory: str, params_like: Any, opt_state_like: Any,
               layout: str = "single"
               ) -> Optional[Tuple[Any, Any, Any, int, float, float, int]]:
    """Restore (params, opt_state, snapshot, epoch, before_val, before_tr, done).

    ``params_like`` / ``opt_state_like`` supply the treedefs (from a fresh
    init at the same shapes; for ``layout="sharded"`` they must be the
    correctly sharded device arrays — restored leaves land straight on
    those shardings). Returns None when no checkpoint exists; raises with a
    clear message on a shape mismatch (e.g. resuming with a different
    ``--sizeHiddenlayer``).

    Multi-host safe on BOTH sides (ADVICE.md round 1): the single layout is
    coordinator-read + broadcast (checkpoint_dir need not be shared); the
    sharded layout reads per-process slices of one shared dir. Collective
    either way: every process must call it.
    """
    path = os.path.join(directory, CKPT_NAME)
    like = (params_like, opt_state_like, params_like)
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if layout == "sharded":
        loaded = _load_sharded(directory, like_leaves)
    elif layout != "single":
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    elif jax.process_count() > 1:
        loaded = _broadcast_from_coordinator(path, like_leaves)
    else:
        loaded = _read_leaves(path, like_leaves)
    if loaded is None:
        # A resume that silently starts over because the OTHER layout's
        # artifact sits in the directory would bypass the terminal
        # done-state guard — fail loudly instead.
        if layout == "single" and _latest_sharded_dir(directory) is not None:
            raise ValueError(
                f"{directory} holds a 'sharded' checkpoint but the resume "
                "asked for layout 'single' — pass --checkpoint-layout "
                "sharded (or the matching checkpoint_layout argument)")
        if layout == "sharded" and os.path.exists(path):
            raise ValueError(
                f"{directory} holds a 'single' checkpoint but the resume "
                "asked for layout 'sharded' — pass --checkpoint-layout "
                "single (or the matching checkpoint_layout argument)")
        return None
    leaves, meta = loaded
    params, opt_state, snapshot = jax.tree_util.tree_unflatten(treedef, leaves)
    done = int(meta[3]) if meta.shape[0] > 3 else RUN_IN_PROGRESS
    return (params, opt_state, snapshot,
            int(meta[0]), float(meta[1]), float(meta[2]), done)


def _broadcast_from_coordinator(path: str, like_leaves
                                ) -> Optional[Tuple[list, np.ndarray]]:
    """Process 0 reads the npz; every process receives the same state.

    The status scalar goes first so a missing file or a validation error on
    the coordinator surfaces as the SAME outcome on every process instead of
    a hang in a half-entered collective.
    """
    from jax.experimental import multihost_utils

    status = 0          # 0 = no checkpoint, 1 = ok, 2 = coordinator error
    leaves, meta, err = None, None, ""
    if jax.process_index() == 0:
        try:
            loaded = _read_leaves(path, like_leaves)
            if loaded is not None:
                leaves, meta = loaded
                status = 1
        # Broad on purpose: ANY coordinator-side read failure (corrupt zip,
        # missing key, shape mismatch) must still reach the status
        # broadcast, or the other processes hang in a half-entered
        # collective.
        except Exception as e:  # noqa: BLE001
            status, err = 2, f"{type(e).__name__}: {e}"
    status = int(multihost_utils.broadcast_one_to_all(np.int32(status)))
    if status == 0:
        return None
    if status == 2:
        raise ValueError(
            f"checkpoint restore failed on the coordinator: "
            f"{err or '(see process 0 logs)'}")
    # One collective for the whole state: non-coordinators contribute
    # shape/dtype-matched zero protos (their values are ignored).
    if leaves is None:
        leaves = [np.zeros(np.shape(w), _leaf_dtype(w)) for w in like_leaves]
        meta = np.zeros(4, np.float64)
    out, meta_b = multihost_utils.broadcast_one_to_all((leaves, meta))
    return [np.asarray(x) for x in out], np.asarray(meta_b)
