"""Checkpoint / resume for the CBOW trainer.

The reference has no checkpointing at all — training state lives only inside
the TF session and dies with the process (SURVEY.md §5 "Checkpoint/resume").
Here the full trainer state — params, Adam state, the early-stopping
snapshot/accuracy pair, and the epoch counter — round-trips so an
interrupted run resumes mid-epoch-loop with identical numerics (full-batch
training has no data-order state to restore). Two layouts:

- ``layout="single"`` (default): one atomic ``.npz``. The save gathers the
  full state (a collective) and process 0 writes; the restore is
  coordinator-read + broadcast, so ``checkpoint_dir`` need NOT be shared
  across hosts. Right for example-scale states (a few hundred MB).
- ``layout="sharded"``: orbax/tensorstore OCDBT — every process writes only
  its own addressable shards (``ocdbt.process_N`` files) and restores only
  what its devices need, with shardings preserved; the full state NEVER
  materializes on any single host (round-1 verdict #7: at pod scale the
  gather is multi-GB of host traffic per save). Requires a SHARED
  checkpoint_dir across processes, like any sharded checkpoint format.

Both layouts store pytree leaves flattened in deterministic order and keyed
by index, plus a scalar metadata array — the format never hard-codes optax
internals. Writes are atomic in both (tmp + rename; orbax does its own
finalize-rename dance).

Integrity (resilience subsystem): every save also writes a sidecar manifest
— schema version, per-leaf sha256 + shape + dtype, whole-file sha256
(single layout) or per-shard-file sha256 (sharded layout), and an optional
config fingerprint. ``load_state`` verifies the manifest BEFORE trusting
the checkpoint: a torn or corrupted write is detected up front and the
load falls back to the kept-previous checkpoint (``.prev`` twin in the
single layout; the previous numbered dir in the sharded layout) with a
clear warning, instead of surfacing an opaque unpickling error — and a
manifest-less checkpoint from an older version still loads (legacy path).
"""
from __future__ import annotations

import contextlib
import json
import os
import time
import warnings
from typing import Any, List, Optional, Tuple

import jax
import numpy as np

from g2vec_tpu.resilience.faults import fault_point
# Shared sha256/atomic-write machinery (also the walk-artifact cache's —
# g2vec_tpu/cache.py — which must import it without jax in the process).
from g2vec_tpu.utils.integrity import (sha256_array as _sha256_array,
                                       sha256_file as _sha256_file,
                                       write_json_atomic as _write_json_atomic)

CKPT_NAME = "cbow_state.npz"
SHARDED_NAME = "cbow_state_ocdbt"
MANIFEST_SUFFIX = ".manifest.json"
PREV_SUFFIX = ".prev"
SCHEMA_VERSION = 1


# ``done`` codes in the meta record: the trainer refuses to continue a
# finished run on --resume (it would re-apply updates on top of themselves
# after an early stop — the saved params are post-dip, the snapshot pre-dip).
RUN_IN_PROGRESS = 0
RUN_COMPLETED = 1      # reached max_epochs
RUN_EARLY_STOPPED = 2  # first val-accuracy dip


def _load_manifest(ckpt_path: str) -> Optional[dict]:
    """The sidecar manifest for ``ckpt_path``, or None (legacy/unreadable —
    unreadable is reported by _verify_single, not here)."""
    try:
        with open(ckpt_path + MANIFEST_SUFFIX) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def _check_fingerprint(ckpt_path: str, manifest: Optional[dict],
                       fingerprint: Optional[dict]) -> None:
    """Raise when the manifest records a DIFFERENT config fingerprint than
    the resuming run's — same-shape config drift (a changed learning rate,
    seed, dtype) would otherwise silently blend two runs."""
    stored = (manifest or {}).get("fingerprint")
    if not stored or not fingerprint:
        return      # legacy checkpoint or caller without a fingerprint
    diffs = {k: (stored.get(k), fingerprint.get(k))
             for k in set(stored) | set(fingerprint)
             if stored.get(k) != fingerprint.get(k)}
    if diffs:
        shapes = " (these change the checkpoint leaf shapes)" \
            if {"hidden", "n_genes_pad"} & set(diffs) else ""
        raise ValueError(
            f"checkpoint {ckpt_path} was written under a different config — "
            + "; ".join(f"{k}: checkpoint={a!r} vs current={b!r}"
                        for k, (a, b) in sorted(diffs.items()))
            + f"{shapes} — restore the original flags or point "
              "--checkpoint-dir at a fresh directory")


def save_state(directory: str, params: Any, opt_state: Any, snapshot: Any,
               epoch: int, before_val: float, before_tr: float,
               done: int = RUN_IN_PROGRESS, layout: str = "single",
               fingerprint: Optional[dict] = None) -> str:
    """Atomically write the full trainer state under ``directory``.

    Collective: every process must call it. ``layout="single"`` gathers and
    process 0 writes one npz; ``layout="sharded"`` writes per-process orbax
    shards and never gathers (see module docstring for the trade-off).
    ``fingerprint`` (a flat dict of config scalars) is recorded in the
    integrity manifest and checked on resume.
    """
    meta = np.array([float(epoch), before_val, before_tr, float(done)])
    if layout == "sharded":
        from g2vec_tpu.parallel.distributed import cpu_fleet

        state = (params, opt_state, snapshot)
        if cpu_fleet():
            return _save_sharded_cpu_fleet(directory, state, meta,
                                           fingerprint)
        return _save_sharded(directory, state, meta, fingerprint)
    if layout != "single":
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    from g2vec_tpu.parallel.distributed import fetch_global

    leaves, _ = jax.tree_util.tree_flatten((params, opt_state, snapshot))
    arrays = {f"leaf_{i}": fetch_global(leaf) for i, leaf in enumerate(leaves)}
    arrays["meta"] = meta
    path = os.path.join(directory, CKPT_NAME)
    if jax.process_index() != 0:
        return path
    os.makedirs(directory, exist_ok=True)
    fault_point("checkpoint_write", path=path, epoch=epoch)
    tmp = path + ".tmp"
    np.savez(tmp, **arrays)
    # np.savez appends .npz to names without it.
    written = tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp
    manifest = {
        "schema": SCHEMA_VERSION, "layout": "single",
        "file_sha256": _sha256_file(written),
        "leaves": [{"name": f"leaf_{i}",
                    "sha256": _sha256_array(arrays[f"leaf_{i}"]),
                    "shape": list(np.shape(arrays[f"leaf_{i}"])),
                    "dtype": str(arrays[f"leaf_{i}"].dtype)}
                   for i in range(len(leaves))],
        "meta": [float(x) for x in meta],
        "fingerprint": fingerprint,
        "written_unix": int(time.time()),
    }
    if os.path.exists(path):
        # Keep-previous: the last committed checkpoint (and its manifest)
        # survives as ``.prev`` until the new one is fully in place — the
        # fallback load_state consults when the latest fails verification.
        os.replace(path, path + PREV_SUFFIX)
        if os.path.exists(path + MANIFEST_SUFFIX):
            os.replace(path + MANIFEST_SUFFIX,
                       path + PREV_SUFFIX + MANIFEST_SUFFIX)
    os.replace(written, path)
    _write_json_atomic(path + MANIFEST_SUFFIX, manifest)
    fault_point("checkpoint_finalize", path=path, epoch=epoch)
    return path


STREAM_NAME = "stream_state.npz"


def save_stream_state(directory: str, arrays: dict, cursor: dict,
                      fingerprint: Optional[dict] = None) -> str:
    """Atomically write the streaming trainer's durable state.

    ``arrays`` is a flat name -> host ndarray dict (params/opt/snapshot
    leaves plus the host-side byproducts the full-batch format has no slot
    for); ``cursor`` is the JSON-serializable (epoch, shard, spool) record
    that makes mid-epoch resume possible — it rides in the MANIFEST, next
    to the integrity data, because the cursor is only meaningful when the
    state it points into verifies. Same machinery as :func:`save_state`:
    tmp + rename, keep-previous ``.prev`` twin, per-leaf sha256 + whole
    file sha256, fingerprint drift check on load. Single-process by
    construction (the streaming trainer is a single-device loop).

    The ``stream_ckpt`` fault seam fires after the manifest commits — a
    sigkill there models the worst case the resume drill pins: death with
    a fully durable checkpoint whose progress must not be repeated.
    """
    os.makedirs(directory, exist_ok=True)
    path = os.path.join(directory, STREAM_NAME)
    epoch = int(cursor.get("epoch", 0))
    fault_point("checkpoint_write", path=path, epoch=epoch)
    tmp = path + ".tmp"
    np.savez(tmp, **arrays)
    written = tmp + ".npz" if os.path.exists(tmp + ".npz") else tmp
    manifest = {
        "schema": SCHEMA_VERSION, "layout": "stream",
        "file_sha256": _sha256_file(written),
        "leaves": [{"name": k, "sha256": _sha256_array(np.asarray(v)),
                    "shape": list(np.shape(v)),
                    "dtype": str(np.asarray(v).dtype)}
                   for k, v in arrays.items()],
        "cursor": cursor,
        "fingerprint": fingerprint,
        "written_unix": int(time.time()),
    }
    if os.path.exists(path):
        os.replace(path, path + PREV_SUFFIX)
        if os.path.exists(path + MANIFEST_SUFFIX):
            os.replace(path + MANIFEST_SUFFIX,
                       path + PREV_SUFFIX + MANIFEST_SUFFIX)
    os.replace(written, path)
    _write_json_atomic(path + MANIFEST_SUFFIX, manifest)
    fault_point("checkpoint_finalize", path=path, epoch=epoch)
    fault_point("stream_ckpt", path=path, epoch=epoch)
    return path


def _stream_dtype(name: str) -> np.dtype:
    """Manifest dtype string -> dtype, including the ml_dtypes family
    (np.savez stores bfloat16 as raw void bytes; the manifest remembers
    what they mean)."""
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes

        return np.dtype(getattr(ml_dtypes, name))


def load_stream_state(directory: str,
                      fingerprint: Optional[dict] = None
                      ) -> Optional[Tuple[dict, dict]]:
    """Restore ``(arrays, cursor)`` written by :func:`save_stream_state`.

    Verification order mirrors :func:`_read_single`: whole-file sha first,
    then per-leaf sha, then the fingerprint drift check — and a latest
    checkpoint that fails any of them falls back to the ``.prev`` twin
    with a warning (at most one checkpoint interval is repeated). Returns
    None when no stream checkpoint exists; raises when every kept
    generation is corrupt.
    """
    path = os.path.join(directory, STREAM_NAME)
    failures = []
    for cand in (path, path + PREV_SUFFIX):
        if not os.path.exists(cand):
            continue
        reason = _verify_single(cand)
        man = _load_manifest(cand) if reason is None else None
        if reason is None and (man is None or "cursor" not in man):
            reason = "missing or cursor-less manifest"
        if reason is None:
            try:
                with np.load(cand) as data:
                    arrays = {k: data[k] for k in data.files}
            except Exception as e:  # noqa: BLE001 — corrupt zip
                reason = f"unreadable ({type(e).__name__}: {e})"
        if reason is None:
            records = {r["name"]: r for r in man.get("leaves", [])}
            if set(records) != set(arrays):
                reason = (f"manifest names {sorted(records)} but checkpoint "
                          f"holds {sorted(arrays)}")
            else:
                for k, arr in arrays.items():
                    if records[k].get("sha256") and \
                            _sha256_array(arr) != records[k]["sha256"]:
                        reason = f"{k} sha256 mismatch"
                        break
        if reason is None:
            _check_fingerprint(cand, man, fingerprint)
            for k, arr in arrays.items():
                want = _stream_dtype(records[k]["dtype"])
                if arr.dtype.kind == "V" and arr.dtype != want:
                    arrays[k] = arr.view(want)
            if cand != path:
                warnings.warn(
                    f"resuming from the previous checkpoint {cand} (the "
                    "latest failed verification) — at most one checkpoint "
                    "interval of progress is repeated", RuntimeWarning)
            return arrays, man["cursor"]
        failures.append(f"{os.path.basename(cand)}: {reason}")
        warnings.warn(
            f"checkpoint {cand} failed integrity verification ({reason}); "
            "falling back to the previous checkpoint", RuntimeWarning)
    if failures:
        raise ValueError(
            f"no intact stream checkpoint under {directory} — "
            + "; ".join(failures)
            + " — every kept generation is corrupt; restart without "
              "--resume to retrain from scratch")
    return None


def _leaf_dict(tree: Any, meta: Optional[np.ndarray] = None) -> dict:
    """Index-keyed flat dict — names custom pytree nodes (NamedTuples,
    optax states) out of the storage format entirely."""
    leaves, _ = jax.tree_util.tree_flatten(tree)
    d = {f"leaf_{i}": leaf for i, leaf in enumerate(leaves)}
    if meta is not None:
        d["meta"] = meta
    return d


_LATEST_NAME = SHARDED_NAME + ".LATEST"


def _write_sharded_manifest(path: str, meta: np.ndarray,
                            fingerprint: Optional[dict]) -> None:
    """Integrity manifest for one numbered OCDBT dir: every file with its
    size and sha256. The full state never materializes on one host in this
    layout, so integrity is per shard FILE, not per logical leaf. The
    manifest is a SIBLING (``<dir>.manifest.json``) — orbax owns the dir's
    contents and must not find foreign files inside it."""
    files = {}
    for root, _, names in os.walk(path):
        for n in sorted(names):
            fp = os.path.join(root, n)
            files[os.path.relpath(fp, path)] = {
                "size": os.path.getsize(fp), "sha256": _sha256_file(fp)}
    _write_json_atomic(path + MANIFEST_SUFFIX, {
        "schema": SCHEMA_VERSION, "layout": "sharded", "files": files,
        "meta": [float(x) for x in meta], "fingerprint": fingerprint,
        "written_unix": int(time.time())})


def _save_sharded(directory: str, state: Any, meta: np.ndarray,
                  fingerprint: Optional[dict] = None) -> str:
    """Keep-previous atomic save: each save goes to a FRESH numbered dir,
    then the LATEST pointer file flips atomically and process 0 prunes all
    but the newest PREVIOUS dir — one generation is kept on purpose, as
    the fallback the restore consults when the latest dir fails manifest
    verification. A crash mid-save leaves the previous checkpoint fully
    intact (orbax's force=True would rmtree it BEFORE committing the new
    one — the exact window checkpointing exists to survive)."""
    import orbax.checkpoint as ocp

    base = os.path.abspath(directory)
    os.makedirs(base, exist_ok=True)
    # Every process lists the same shared dir BEFORE the collective save
    # creates anything, so all agree on the next index (orphans from an
    # earlier crash only push the index up, never collide).
    existing = [int(n.rsplit(".", 1)[1]) for n in os.listdir(base)
                if n.startswith(SHARDED_NAME + ".")
                and n.rsplit(".", 1)[1].isdigit()]
    name = f"{SHARDED_NAME}.{max(existing, default=-1) + 1}"
    path = os.path.join(base, name)
    fault_point("checkpoint_write", path=path, epoch=int(meta[0]))
    with _orbax_local_io(), ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(path, args=ocp.args.PyTreeSave(_leaf_dict(state, meta)))
    if jax.process_index() == 0:
        _write_sharded_manifest(path, meta, fingerprint)
        tmp = os.path.join(base, _LATEST_NAME + ".tmp")
        with open(tmp, "w") as f:
            f.write(name)
        os.replace(tmp, os.path.join(base, _LATEST_NAME))
        for idx in sorted(existing)[:-1]:
            import shutil

            stale = os.path.join(base, f"{SHARDED_NAME}.{idx}")
            shutil.rmtree(stale, ignore_errors=True)
            if os.path.exists(stale + MANIFEST_SUFFIX):
                os.unlink(stale + MANIFEST_SUFFIX)
        fault_point("checkpoint_finalize", path=_largest_file(path),
                    epoch=int(meta[0]))
    return path


@contextlib.contextmanager
def _orbax_local_io():
    """On CPU fleets orbax's end-of-op process sync lowers to an XLA
    collective the CPU backend cannot run (``Multiprocess computations
    aren't implemented``). Checkpoint I/O there is coordinator-write /
    local-read by construction (see :func:`_save_sharded_cpu_fleet`), so
    the sync is disabled for the duration of the orbax call; fleet-level
    ordering is enforced by the KV barrier instead. No-op everywhere
    else."""
    from g2vec_tpu.parallel.distributed import cpu_fleet

    if not cpu_fleet():
        yield
        return
    from orbax.checkpoint.multihost import utils as _omh

    orig = _omh.should_skip_process_sync
    _omh.should_skip_process_sync = lambda: True
    try:
        yield
    finally:
        _omh.should_skip_process_sync = orig


def _save_sharded_cpu_fleet(directory: str, state: Any, meta: np.ndarray,
                            fingerprint: Optional[dict] = None) -> str:
    """Sharded save for CPU fleets, where ranks train REPLICATED on
    process-local meshes (the CPU backend has no cross-process XLA — see
    parallel/distributed.cpu_fleet). Every rank holds the identical full
    state, so the coordinator alone writes it — as host numpy leaves,
    since orbax refuses host-local jax.Arrays in a multi-process runtime —
    into the shared dir; peers rendezvous on the KV barrier so none races
    ahead of a durable save. Every rank then passes the
    ``checkpoint_finalize`` fault seam — the boundary ``process=K``
    kill/stall tests target, guaranteed to sit AFTER the save committed on
    all ranks. Restores reshard these leaves onto whatever mesh the
    (possibly degraded) resuming run brings."""
    import jax

    from g2vec_tpu.parallel import hostcomm
    from g2vec_tpu.resilience import fleet

    path = directory
    if jax.process_index() == 0:
        host_state = jax.tree.map(
            lambda leaf: np.asarray(jax.device_get(leaf)), state)
        path = _save_sharded(directory, host_state, meta, fingerprint)
    hostcomm.barrier("checkpoint_save",
                     deadline=fleet.config().watchdog_deadline or None)
    if jax.process_index() != 0:
        fault_point("checkpoint_finalize", epoch=int(meta[0]))
    return path


def _largest_file(dirpath: str) -> Optional[str]:
    """The biggest payload file under ``dirpath`` — the corrupt-fault
    target for the sharded layout (flipping manifest bytes would test the
    manifest, not the data path)."""
    best, best_size = None, -1
    for root, _, names in os.walk(dirpath):
        for n in names:
            fp = os.path.join(root, n)
            size = os.path.getsize(fp)
            if size > best_size:
                best, best_size = fp, size
    return best


def _verify_sharded(dirpath: str) -> Optional[str]:
    """None when ``dirpath`` passes manifest verification (or predates
    manifests); else the human-readable failure reason."""
    mpath = dirpath + MANIFEST_SUFFIX
    if not os.path.exists(mpath):
        return None      # legacy dir: no integrity data to check against
    try:
        with open(mpath) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return f"manifest unreadable ({type(e).__name__}: {e})"
    if man.get("schema") != SCHEMA_VERSION:
        return f"unknown manifest schema {man.get('schema')!r}"
    for rel, want in man.get("files", {}).items():
        fp = os.path.join(dirpath, rel)
        if not os.path.exists(fp):
            return f"missing shard file {rel}"
        if os.path.getsize(fp) != want.get("size"):
            return (f"shard file {rel} is {os.path.getsize(fp)} bytes, "
                    f"manifest says {want.get('size')} (torn write)")
        if want.get("sha256") and _sha256_file(fp) != want["sha256"]:
            return f"shard file {rel} sha256 mismatch (corrupted bytes)"
    return None


def _latest_sharded_dir(directory: str) -> Optional[str]:
    pointer = os.path.join(os.path.abspath(directory), _LATEST_NAME)
    if not os.path.exists(pointer):
        return None
    with open(pointer) as f:
        name = f.read().strip()
    path = os.path.join(os.path.abspath(directory), name)
    return path if os.path.isdir(path) else None


def _sharded_candidates(directory: str) -> List[str]:
    """Restore candidates, best first: the LATEST-pointer dir, then the
    remaining numbered dirs newest-first (the keep-previous fallbacks)."""
    base = os.path.abspath(directory)
    ordered = []
    pointed = _latest_sharded_dir(directory)
    if pointed is not None:
        ordered.append(pointed)
    if not os.path.isdir(base):
        return ordered
    numbered = sorted(
        ((int(n.rsplit(".", 1)[1]), n) for n in os.listdir(base)
         if n.startswith(SHARDED_NAME + ".") and n.rsplit(".", 1)[1].isdigit()),
        reverse=True)
    for _, n in numbered:
        p = os.path.join(base, n)
        if p != pointed and os.path.isdir(p):
            ordered.append(p)
    return ordered


def _load_sharded(directory: str, like_leaves,
                  fingerprint: Optional[dict] = None
                  ) -> Optional[Tuple[list, np.ndarray]]:
    """Restore per-process shards with the LIKE tree's shardings preserved.

    ``like_leaves`` must be device arrays (a freshly initialized, correctly
    sharded state) — orbax restores each leaf directly onto those shardings,
    so every process reads only its own devices' slices. A candidate dir
    that fails manifest verification is skipped with a warning and the
    previous numbered dir is tried instead.
    """
    candidates = _sharded_candidates(directory)
    if not candidates:
        return None
    path, failures = None, []
    for cand in candidates:
        reason = _verify_sharded(cand)
        if reason is None:
            path = cand
            break
        failures.append(f"{os.path.basename(cand)}: {reason}")
        warnings.warn(
            f"checkpoint {cand} failed integrity verification ({reason}); "
            "trying the previous numbered checkpoint", RuntimeWarning)
    if path is None:
        raise ValueError(
            f"no intact sharded checkpoint under {directory} — "
            + "; ".join(failures)
            + " — every kept generation is corrupt; restart without "
              "--resume to retrain from scratch")
    if path != candidates[0]:
        warnings.warn(
            f"resuming from the previous checkpoint {path} (the latest "
            "failed verification) — at most one checkpoint interval of "
            "progress is repeated", RuntimeWarning)
    sharded_manifest = None
    mpath = path + MANIFEST_SUFFIX
    if os.path.exists(mpath):
        with open(mpath) as f:
            sharded_manifest = json.load(f)
    _check_fingerprint(path, sharded_manifest, fingerprint)
    return _restore_sharded_dir(path, like_leaves)


def _restore_sharded_dir(path: str, like_leaves
                         ) -> Tuple[list, np.ndarray]:
    import orbax.checkpoint as ocp

    like = _leaf_dict(like_leaves, np.zeros(4, np.float64))
    with _orbax_local_io(), ocp.PyTreeCheckpointer() as ckptr:
        # Validate shapes against the stored metadata FIRST, so a config
        # change surfaces as the same clear error the single layout raises
        # instead of an obscure tensorstore chunk mismatch. Older orbax
        # (<=0.7) returns the name->ArrayMetadata dict directly; newer
        # versions wrap it in .item_metadata.tree.
        stored = ckptr.metadata(path)
        if hasattr(stored, "item_metadata"):
            stored = stored.item_metadata.tree
        for i, want in enumerate(like_leaves):
            got = stored.get(f"leaf_{i}")
            got_shape = tuple(getattr(got, "shape", ()) or ())
            if (hasattr(want, "shape")
                    and got_shape != tuple(np.shape(want))):
                raise ValueError(
                    f"checkpoint {path}: leaf {i} has shape {got_shape}, "
                    f"current model expects {np.shape(want)} — was the "
                    "config changed between save and resume?")
        restore_args = ocp.checkpoint_utils.construct_restore_args(like)
        out = ckptr.restore(path, args=ocp.args.PyTreeRestore(
            item=like, restore_args=restore_args))
    leaves = [out[f"leaf_{i}"] for i in range(len(like_leaves))]
    return leaves, np.asarray(out["meta"])


def _read_leaves(path: str, like_leaves) -> Optional[Tuple[list, np.ndarray]]:
    """Read + validate the npz against the expected leaf shapes/dtypes."""
    if not os.path.exists(path):
        return None
    with np.load(path) as data:
        leaves = [data[f"leaf_{i}"] for i in range(len(like_leaves))]
        meta = data["meta"]
    if meta.shape[0] == 3:      # legacy pre-`done` meta: normalize the shape
        meta = np.append(meta, float(RUN_IN_PROGRESS))
    for i, (got, want) in enumerate(zip(leaves, like_leaves)):
        if hasattr(want, "shape") and tuple(got.shape) != tuple(np.shape(want)):
            raise ValueError(
                f"checkpoint {path}: leaf {i} has shape {got.shape}, current "
                f"model expects {np.shape(want)} — was the config changed "
                "between save and resume?")
        # np.savez stores ml_dtypes types (bfloat16 et al.) as raw void
        # bytes; reinterpret them against the expected leaf's dtype so a
        # bf16-param checkpoint round-trips instead of surfacing as '|V2'.
        want_dtype = _leaf_dtype(want)
        if got.dtype.kind == "V" and got.dtype != want_dtype:
            leaves[i] = got.view(want_dtype)
    return leaves, meta


def _leaf_dtype(want) -> np.dtype:
    """Expected dtype of a template leaf WITHOUT materializing its value
    (np.asarray on a cross-process-sharded array raises)."""
    return np.dtype(want.dtype) if hasattr(want, "dtype") else \
        np.asarray(want).dtype


def _verify_single(ckpt_path: str) -> Optional[str]:
    """None when ``ckpt_path`` passes manifest verification (or predates
    manifests); else the human-readable failure reason."""
    mpath = ckpt_path + MANIFEST_SUFFIX
    if not os.path.exists(mpath):
        return None     # legacy checkpoint: nothing to verify against
    try:
        with open(mpath) as f:
            man = json.load(f)
    except (OSError, ValueError) as e:
        return f"manifest unreadable ({type(e).__name__}: {e})"
    if man.get("schema") != SCHEMA_VERSION:
        return f"unknown manifest schema {man.get('schema')!r}"
    want = man.get("file_sha256")
    if want and _sha256_file(ckpt_path) != want:
        return "file sha256 mismatch (truncated or corrupted write)"
    return None


def _verify_leaves(ckpt_path: str, leaves: list) -> Optional[str]:
    """Per-leaf hash check against the manifest (defense in depth behind
    the whole-file hash — catches a stale manifest paired with the wrong
    npz)."""
    man = _load_manifest(ckpt_path)
    if man is None:
        return None
    records = man.get("leaves", [])
    if len(records) != len(leaves):
        return (f"manifest lists {len(records)} leaves, checkpoint holds "
                f"{len(leaves)}")
    for rec, leaf in zip(records, leaves):
        if rec.get("sha256") and _sha256_array(leaf) != rec["sha256"]:
            return f"{rec.get('name', 'leaf')} sha256 mismatch"
    return None


def _read_single(directory: str, like_leaves,
                 fingerprint: Optional[dict] = None
                 ) -> Optional[Tuple[list, np.ndarray]]:
    """Single-layout read with integrity verification and keep-previous
    fallback: a latest checkpoint that fails its manifest (or is an
    unreadable zip) is skipped WITH A WARNING and the ``.prev`` twin is
    restored instead; only when every kept generation is bad does this
    raise — with the verification reasons, not an opaque zip error."""
    path = os.path.join(directory, CKPT_NAME)
    failures = []
    for cand in (path, path + PREV_SUFFIX):
        if not os.path.exists(cand):
            continue
        reason = _verify_single(cand)
        if reason is None:
            try:
                loaded = _read_leaves(cand, like_leaves)
            except ValueError:
                # Shape/config mismatch: structural, not corruption — the
                # .prev twin has the same shapes, so propagate as-is.
                raise
            except Exception as e:  # noqa: BLE001 — corrupt legacy zip
                reason = f"unreadable ({type(e).__name__}: {e})"
            else:
                reason = _verify_leaves(cand, loaded[0])
                if reason is None:
                    _check_fingerprint(cand, _load_manifest(cand), fingerprint)
                    if cand != path:
                        warnings.warn(
                            f"resuming from the previous checkpoint {cand} "
                            "(the latest failed verification) — at most one "
                            "checkpoint interval of progress is repeated",
                            RuntimeWarning)
                    return loaded
        failures.append(f"{os.path.basename(cand)}: {reason}")
        warnings.warn(
            f"checkpoint {cand} failed integrity verification ({reason}); "
            "falling back to the previous checkpoint", RuntimeWarning)
    if failures:
        raise ValueError(
            f"no intact checkpoint under {directory} — " + "; ".join(failures)
            + " — every kept generation is corrupt; restart without "
              "--resume to retrain from scratch")
    return None


def load_state(directory: str, params_like: Any, opt_state_like: Any,
               layout: str = "single",
               fingerprint: Optional[dict] = None
               ) -> Optional[Tuple[Any, Any, Any, int, float, float, int]]:
    """Restore (params, opt_state, snapshot, epoch, before_val, before_tr, done).

    ``params_like`` / ``opt_state_like`` supply the treedefs (from a fresh
    init at the same shapes; for ``layout="sharded"`` they must be the
    correctly sharded device arrays — restored leaves land straight on
    those shardings). Returns None when no checkpoint exists; raises with a
    clear message on a shape mismatch (e.g. resuming with a different
    ``--sizeHiddenlayer``).

    Multi-host safe on BOTH sides (ADVICE.md round 1): the single layout is
    coordinator-read + broadcast (checkpoint_dir need not be shared); the
    sharded layout reads per-process slices of one shared dir. Collective
    either way: every process must call it.
    """
    path = os.path.join(directory, CKPT_NAME)
    like = (params_like, opt_state_like, params_like)
    like_leaves, treedef = jax.tree_util.tree_flatten(like)
    if layout == "sharded":
        loaded = _load_sharded(directory, like_leaves, fingerprint)
    elif layout != "single":
        raise ValueError(f"unknown checkpoint layout {layout!r}")
    elif jax.process_count() > 1:
        loaded = _broadcast_from_coordinator(directory, like_leaves,
                                             fingerprint)
    else:
        loaded = _read_single(directory, like_leaves, fingerprint)
    if loaded is None:
        # A resume that silently starts over because the OTHER layout's
        # artifact sits in the directory would bypass the terminal
        # done-state guard — fail loudly instead.
        if layout == "single" and _latest_sharded_dir(directory) is not None:
            raise ValueError(
                f"{directory} holds a 'sharded' checkpoint but the resume "
                "asked for layout 'single' — pass --checkpoint-layout "
                "sharded (or the matching checkpoint_layout argument)")
        if layout == "sharded" and os.path.exists(path):
            raise ValueError(
                f"{directory} holds a 'single' checkpoint but the resume "
                "asked for layout 'sharded' — pass --checkpoint-layout "
                "single (or the matching checkpoint_layout argument)")
        return None
    leaves, meta = loaded
    params, opt_state, snapshot = jax.tree_util.tree_unflatten(treedef, leaves)
    done = int(meta[3]) if meta.shape[0] > 3 else RUN_IN_PROGRESS
    return (params, opt_state, snapshot,
            int(meta[0]), float(meta[1]), float(meta[2]), done)


def _broadcast_from_coordinator(directory: str, like_leaves,
                                fingerprint: Optional[dict] = None
                                ) -> Optional[Tuple[list, np.ndarray]]:
    """Process 0 reads the npz (with integrity verification + keep-previous
    fallback); every process receives the same state.

    The status travels WITH the payload so a missing file or a validation
    error on the coordinator surfaces as the SAME outcome on every process
    instead of a hang in a half-entered collective. CPU fleets ship the
    state over the KV transport (one serialized npz, deadline-aware);
    backends with cross-process XLA broadcast device-side under the fleet
    watchdog.
    """
    status = 0          # 0 = no checkpoint, 1 = ok, 2 = coordinator error
    leaves, meta, err = None, None, ""
    if jax.process_index() == 0:
        try:
            loaded = _read_single(directory, like_leaves, fingerprint)
            if loaded is not None:
                leaves, meta = loaded
                status = 1
        # Broad on purpose: ANY coordinator-side read failure (corrupt zip,
        # missing key, shape mismatch) must still reach the status
        # broadcast, or the other processes hang in a half-entered
        # collective.
        except Exception as e:  # noqa: BLE001
            status, err = 2, f"{type(e).__name__}: {e}"
    from g2vec_tpu.parallel.distributed import cpu_fleet

    if cpu_fleet():
        status, leaves, meta, err = _kv_broadcast_state(
            status, leaves, meta, err, like_leaves)
    else:
        from jax.experimental import multihost_utils

        from g2vec_tpu.resilience import fleet

        status = int(fleet.collective_watchdog(
            "checkpoint_restore_status",
            lambda: multihost_utils.broadcast_one_to_all(np.int32(status))))
        if status == 1:
            # One collective for the whole state: non-coordinators
            # contribute shape/dtype-matched zero protos (values ignored).
            if leaves is None:
                leaves = [np.zeros(np.shape(w), _leaf_dtype(w))
                          for w in like_leaves]
                meta = np.zeros(4, np.float64)
            out, meta_b = fleet.collective_watchdog(
                "checkpoint_restore_state",
                lambda: multihost_utils.broadcast_one_to_all((leaves, meta)))
            leaves = [np.asarray(x) for x in out]
            meta = np.asarray(meta_b)
    if status == 0:
        return None
    if status == 2:
        raise ValueError(
            f"checkpoint restore failed on the coordinator: "
            f"{err or '(see process 0 logs)'}")
    return leaves, meta


def _kv_broadcast_state(status: int, leaves, meta, err: str, like_leaves
                        ) -> Tuple[int, Optional[list],
                                   Optional[np.ndarray], str]:
    """Serialize (status, leaves, meta, err) on the coordinator into one
    npz payload and ship it over the KV transport — the CPU-fleet stand-in
    for ``broadcast_one_to_all``. ml_dtypes leaves (bfloat16) survive the
    round trip the same way the on-disk format does: raw void bytes
    reinterpreted against the expected leaf dtype on receive."""
    import io

    from g2vec_tpu.parallel import hostcomm
    from g2vec_tpu.resilience import fleet

    deadline = fleet.config().watchdog_deadline or None
    payload = None
    if jax.process_index() == 0:
        buf = io.BytesIO()
        arrays = {"status": np.int32(status), "err": np.array(err or "")}
        if status == 1:
            arrays.update({f"leaf_{i}": np.asarray(leaf)
                           for i, leaf in enumerate(leaves)})
            arrays["meta"] = np.asarray(meta)
        np.savez(buf, **arrays)
        payload = buf.getvalue()
    payload = hostcomm.broadcast_bytes("checkpoint_restore", payload,
                                       deadline=deadline)
    with np.load(io.BytesIO(payload)) as data:
        status = int(data["status"])
        err = str(data["err"])
        if status != 1:
            return status, None, None, err
        leaves = [data[f"leaf_{i}"] for i in range(len(like_leaves))]
        meta = np.asarray(data["meta"])
    for i, want in enumerate(like_leaves):
        want_dtype = _leaf_dtype(want)
        if leaves[i].dtype.kind == "V" and leaves[i].dtype != want_dtype:
            leaves[i] = leaves[i].view(want_dtype)
    return status, leaves, meta, err
