"""Streaming minibatch trainer — sampling overlapped with training,
bounded host memory, big graphs (ROADMAP item 3).

Every other trainer mode (solo, lanes, serve) is full-batch: ALL walk
paths for both groups materialize on host and pack onto device before
epoch 0, which hard-caps graph size at host RAM, forces strict stage
3 -> stage 4 sequencing, and makes a resident daemon's footprint
proportional to the largest job it ever saw. This module is the
shared-memory minibatch-SGD recipe of "Parallelizing Word2Vec in
Multi-Core and Many-Core Architectures" (arXiv:1611.06172) applied to
the walk pipeline:

- The PR 3 multicore sampler pool emits fixed-size **walk shards**
  (packed context/target blocks: each shard is the same walker-index
  range of BOTH groups' axes, so labels mix evenly —
  ops/host_walker.py ``ShardPlan``). Shard order is deterministic by
  shard index; shard contents are bit-identical at any thread count.
- A bounded host **ring** (:class:`ShardRing`, ``--prefetch-depth``)
  carries shards from the producer (an overlap-scheduler task) to the
  trainer. A full ring BLOCKS the producer — backpressure — so peak
  host path memory is O(shard x depth), never O(total paths).
- A **double-buffered device prefetch** stage uploads shard ``b+1``
  while the jitted minibatch-SGD step consumes shard ``b`` (JAX's
  async dispatch does the overlap; the feed just keeps one upload in
  flight ahead of the step).
- Epoch 0 consumes the ring — training starts the moment shard 0
  lands, long before sampling finishes. Shards are spooled to disk
  (sha256-manifested — :class:`ShardSpool`) as they pass, and epochs
  1..N replay the spool; a replayed shard whose bytes fail
  verification is re-walked once (determinism makes the retry exact)
  and the run dies cleanly if even the re-walk mismatches.
- The early stop evaluates the SAME metric as full-batch — held-out
  val accuracy, first strict dip, previous epoch's snapshot returned —
  at shard-epoch boundaries, on a bounded val buffer accumulated from
  per-shard held-out rows during epoch 0.

Contract vs full-batch: STATISTICAL, not bitwise. The stream trains on
the raw walk rows (no global dedup, no common-path drop — both need
the full set) with per-shard Adam steps, so trajectories differ; the
pinned contract is a val-ACC parity band plus top-N biomarker overlap
(tests/test_stream.py), while the full-batch path remains the
bitwise-golden reference. WITHIN streaming mode the trajectory is
bitwise-deterministic: same seed + same shard size reproduce it at any
``--sampler-threads`` and any ring depth.

Durability (PR 9): with ``checkpoint_dir`` set the trainer carries an
(epoch, shard) CURSOR through the sha256-manifest machinery
(train/checkpoint.py ``save_stream_state``): every ``checkpoint_every``
shard updates — and at every epoch boundary — the full device state
(params/Adam/snapshot), the epoch-0 byproducts (gene counts, bounded
eval buffers, kept-row count), the history, and the partial-epoch loss
list all land atomically next to a cursor naming the NEXT shard to
train. The spool becomes durable (``<checkpoint_dir>/spool``) and the
cursor records each spooled shard's sha256, so ``resume=True`` restarts
mid-epoch: epochs > 0 replay the verified spool from the cursor shard;
a mid-epoch-0 resume restarts the deterministic producer AT the cursor
shard. Because the in-stream trajectory is bitwise-deterministic and
every checkpoint cuts at a shard boundary (where device state is
host-consistent), a resumed run's final outputs are byte-identical to
an uninterrupted one — the contract tests/test_stream.py and the serve
SIGKILL drill pin.
"""
from __future__ import annotations

import contextlib
import dataclasses
import io
import os
import shutil
import tempfile
import threading
import time
import warnings
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Dict, Iterator, List, Optional

import numpy as np

from g2vec_tpu.ops.host_walker import (ShardPlan, edges_to_csr, plan_shards,
                                       walk_shard)
from g2vec_tpu.parallel.shard import subset_starts
from g2vec_tpu.resilience.faults import fault_point
from g2vec_tpu.resilience.lifecycle import DrainRequested
from g2vec_tpu.utils.integrity import sha256_file

# ---------------------------------------------------------------------------
# Process-wide stream accounting (the serve /status "how warm/busy is the
# streaming path" currency, beside cache.cache_stats()).
# ---------------------------------------------------------------------------

_STATS_LOCK = threading.Lock()
_TOTALS: Dict[str, float] = {}


def _record_totals(**fields) -> None:
    with _STATS_LOCK:
        _TOTALS["runs"] = _TOTALS.get("runs", 0) + 1
        for k, v in fields.items():
            if k.startswith("last_"):
                _TOTALS[k] = v
            elif k.startswith("max_"):
                _TOTALS[k] = max(_TOTALS.get(k, 0), v)
            else:
                _TOTALS[k] = _TOTALS.get(k, 0) + v


def stream_stats() -> Dict[str, float]:
    """Snapshot of every streaming run's counters since process start
    (batch/engine.py surfaces it on the engine status -> serve /status)."""
    with _STATS_LOCK:
        return dict(_TOTALS)


@dataclasses.dataclass
class Shard:
    """One in-flight walk shard: group-g rows then group-p rows, still in
    the walker's np.packbits layout (8 genes/byte)."""

    index: int
    x: np.ndarray            # [rows, ceil(G/8)] uint8
    y: np.ndarray            # [rows] int32 labels (0 good, 1 poor)

    @property
    def nbytes(self) -> int:
        return self.x.nbytes + self.y.nbytes


class ShardRing:
    """Bounded producer->consumer shard queue with explicit failure edges.

    The no-deadlock contract (tests/test_stream.py pins all four edges):

    - full ring: ``put`` BLOCKS (backpressure — the sampler cannot run
      ahead of the trainer by more than ``depth`` shards);
    - producer failure: ``fail(exc)`` parks the exception; the consumer's
      next ``get`` re-raises it (same surface as an overlap join);
    - producer done: ``finish``; ``get`` returns None after the queue
      drains;
    - consumer death: ``cancel`` wakes a blocked producer, whose ``put``
      returns False (the producer task then exits instead of wedging the
      overlap drain — the scheduler runs ``cancel`` as a close-time
      closer, parallel/overlap.py ``add_closer``).
    """

    def __init__(self, depth: int):
        if depth < 1:
            raise ValueError(f"ring depth must be >= 1, got {depth}")
        self.depth = depth
        self._items: deque = deque()            # guarded-by: _cv
        self._cv = threading.Condition()
        self._error: Optional[BaseException] = None    # guarded-by: _cv
        self._finished = False                  # guarded-by: _cv
        self._cancelled = False                 # guarded-by: _cv
        # Accounting (read after the run; the lock covers writes).
        self.occupancy_hw = 0        # guarded-by: _cv — max shards resident
        self.peak_bytes = 0          # guarded-by: _cv — max bytes resident
        self.shards_put = 0          # guarded-by: _cv
        self.wait_put_s = 0.0        # guarded-by: _cv — blocked on full ring
        self.wait_get_s = 0.0        # guarded-by: _cv — blocked on empty one

    def put(self, shard: Shard) -> bool:
        """Enqueue; blocks while full. False = ring cancelled (consumer
        gone) — the producer should stop emitting."""
        t0 = time.perf_counter()
        with self._cv:
            while len(self._items) >= self.depth and not self._cancelled:
                self._cv.wait(timeout=0.1)
            self.wait_put_s += time.perf_counter() - t0
            if self._cancelled:
                return False
            self._items.append(shard)
            self.shards_put += 1
            self.occupancy_hw = max(self.occupancy_hw, len(self._items))
            self.peak_bytes = max(self.peak_bytes,
                                  sum(s.nbytes for s in self._items))
            self._cv.notify_all()
        return True

    def get(self) -> Optional[Shard]:
        """Dequeue the next shard (emission order); blocks while empty.
        None = producer finished and queue drained; a producer failure
        re-raises here."""
        t0 = time.perf_counter()
        with self._cv:
            while True:
                if self._error is not None:
                    self.wait_get_s += time.perf_counter() - t0
                    raise self._error
                if self._items:
                    self.wait_get_s += time.perf_counter() - t0
                    shard = self._items.popleft()
                    self._cv.notify_all()
                    return shard
                if self._finished or self._cancelled:
                    self.wait_get_s += time.perf_counter() - t0
                    return None
                self._cv.wait(timeout=0.1)

    def fail(self, exc: BaseException) -> None:
        with self._cv:
            self._error = exc
            self._cv.notify_all()

    def finish(self) -> None:
        with self._cv:
            self._finished = True
            self._cv.notify_all()

    def cancel(self) -> None:
        """Consumer-side teardown: unblock and stop the producer. Idempotent
        and safe after finish()."""
        with self._cv:
            self._cancelled = True
            self._items.clear()
            self._cv.notify_all()

    @property
    def cancelled(self) -> bool:
        """True once the consumer is gone. Producers blocked OUTSIDE the
        ring (the sharded walk exchange waits on a remote rank's publish,
        not on ``put``) poll this between bounded waits so a trainer that
        stopped early — or died — doesn't leave them wedged on a
        multi-day transport deadline."""
        with self._cv:
            return self._cancelled


class SpoolIntegrityError(ValueError):
    """A spooled shard failed sha256 verification and its deterministic
    re-walk did not reproduce the recorded bytes — the inputs changed
    under the run, a fatal condition (never retried)."""


class SpoolWriteError(RuntimeError):
    """A shard failed to SPOOL — ENOSPC, EIO, or a short write under the
    spool directory. Structured (shard index, path, errno) so the failure
    names the disk problem instead of surfacing as a bare OSError from an
    anonymous worker thread; a RuntimeError so the serve classifier calls
    it retryable (space may free) while the job still fails cleanly and
    the daemon stays up."""

    def __init__(self, index: int, path: str, detail: str,
                 errno: Optional[int] = None):
        self.index, self.path, self.errno = index, path, errno
        super().__init__(
            f"failed to spool shard {index} to {path}: {detail} — "
            f"the streaming job cannot replay epochs without its spool")


def _spool_write(index: int, path: str, arr: np.ndarray) -> None:
    """np.save with the failure modes named: OSError (ENOSPC et al.)
    and the silent short write a full-but-not-failing filesystem can
    leave behind both raise :class:`SpoolWriteError`."""
    try:
        np.save(path, arr)
        size = os.path.getsize(path)
    except OSError as e:
        with contextlib.suppress(OSError):
            os.unlink(path)
        raise SpoolWriteError(index, path,
                              f"{type(e).__name__}: {e}",
                              errno=getattr(e, "errno", None)) from e
    if size < arr.nbytes:        # .npy = header + raw bytes, so >= nbytes
        with contextlib.suppress(OSError):
            os.unlink(path)
        raise SpoolWriteError(
            index, path,
            f"short write ({size} bytes on disk < {arr.nbytes} data bytes)")


class ShardSpool:
    """Disk spool for the epoch-0 shard stream, replayed by epochs 1..N.

    One ``.npy`` pair per shard under a run-private temp dir, each with
    its sha256 recorded AT EMISSION (utils/integrity.py — the same
    trust-nothing stance as the walk cache and checkpoint manifests). A
    replay whose bytes mismatch (torn write, bitrot, an injected
    ``shard_ring`` corrupt fault) is re-walked ONCE through the
    deterministic sampler — the retry must reproduce the recorded hash
    exactly, else :class:`SpoolIntegrityError`. Host memory never holds
    more than the shards in flight; the spool is why epochs > 0 cost
    sequential file reads instead of a full re-sample.
    """

    def __init__(self, directory: str):
        self.directory = directory
        self._sha: Dict[int, str] = {}
        self.rewalks = 0

    def x_path(self, index: int) -> str:
        return os.path.join(self.directory, f"shard{index:06d}_x.npy")

    def save(self, shard: Shard) -> str:
        _spool_write(shard.index, self.x_path(shard.index), shard.x)
        self._sha[shard.index] = sha256_file(self.x_path(shard.index))
        return self.x_path(shard.index)

    def load(self, index: int,
             rewalk: Callable[[int], np.ndarray]) -> np.ndarray:
        """Shard ``index``'s verified x rows (labels are re-derived by the
        caller — they are a pure function of the plan)."""
        path = self.x_path(index)
        want = self._sha[index]
        if sha256_file(path) != want:
            warnings.warn(
                f"spooled shard {index} failed sha256 verification "
                f"({path}) — re-walking it through the deterministic "
                f"sampler", RuntimeWarning)
            self.rewalks += 1
            _spool_write(index, path, rewalk(index))
            if sha256_file(path) != want:
                raise SpoolIntegrityError(
                    f"shard {index}: deterministic re-walk does not "
                    f"reproduce the bytes recorded at emission — the walk "
                    f"inputs changed under the run; aborting")
        return np.load(path)


@dataclasses.dataclass
class StreamStats:
    """One streaming run's attribution record (metrics `stream` event,
    StageTimer extras, BENCH_STREAM_AB.json)."""

    n_shards: int = 0
    shards_emitted: int = 0
    n_paths: int = 0                 # rows actually trained on (after the
                                     # per-shard common-drop/dedup)
    rows_sampled: int = 0            # raw walker rows emitted (2 x walkers)
    shard_rows: int = 0
    ring_depth: int = 0
    ring_occupancy_hw: int = 0
    ring_peak_bytes: int = 0
    prefetch_wait_ms: float = 0.0
    time_to_first_update_ms: float = 0.0
    shards_at_first_update: int = 0
    sampling_wall_s: float = 0.0
    producer_blocked_s: float = 0.0
    rewalks: int = 0
    epochs: int = 0
    checkpoints: int = 0             # cursor checkpoints written this run
    checkpoint_wall_s: float = 0.0   # wall spent inside save_stream_state
    resumed: int = 0                 # 1 = this run restored a cursor
    feed_mode: str = "ring"          # "ring": sampled rows cross the host
                                     # shard ring + per-shard H2D upload.
                                     # "device": epoch-0 shards sampled ON
                                     # device and consumed device-resident
                                     # (--device-feed; ops/device_walker.py)
    h2d_bytes_saved: int = 0         # packed training bytes that never
                                     # crossed host->device because the
                                     # device feed kept them resident
    device_recomputes: int = 0       # device-walk faults recovered by a
                                     # clean recompute (device_walk seam)

    def as_dict(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class StreamTrainResult:
    train: object                    # train.trainer.TrainResult
    gene_freq: Dict[str, int]        # streaming count_gene_freq twin
    n_paths: int
    stats: StreamStats


def _group_edges_csr(src: np.ndarray, dst: np.ndarray, n_genes: int):
    """One-time bounds check per group (walk_shard skips the per-shard
    O(E) scans when handed a prebuilt CSR)."""
    for name, arr in (("src", src), ("dst", dst)):
        if arr.size and (arr.min() < 0 or arr.max() >= n_genes):
            raise ValueError(
                f"{name} contains node ids outside [0, {n_genes})")


def _shard_split(rows: int, seed: int, shard_index: int,
                 val_fraction: float):
    """The per-shard held-out split: the full-batch shuffled hold-out
    (trainer._split_indices) applied per shard, seeded by (train seed,
    shard index) so it is invariant to thread count and ring depth.
    Every shard keeps at least one row on each side."""
    rng = np.random.default_rng(np.random.SeedSequence((seed, shard_index)))
    perm = rng.permutation(rows)
    pivot = int(rows * (1.0 - val_fraction))
    pivot = max(1, min(pivot, rows - 1))
    return perm[:pivot], perm[pivot:]


#: Bounded eval buffers: the val (and train-probe) sets accumulate
#: per-shard held-out rows in shard order UP TO this many rows, so the
#: epoch-boundary eval stays O(1) in graph size. 4096 rows matches the
#: auto shard size; the full-batch trainer's val set at bundled scale is
#: smaller than this, so at small scale the buffers are effectively
#: uncapped.
EVAL_ROWS_CAP = 4096


def train_cbow_streaming(
        *, groups, n_genes: int, genes, hidden: int, learning_rate: float,
        max_epochs: int, val_fraction: float = 0.2,
        decision_threshold: float = 0.5, compute_dtype: str = "bfloat16",
        param_dtype: str = "float32", seed: int = 0, walk_seed: int = 0,
        len_path: int, reps: int, shard_paths: int = 0,
        prefetch_depth: int = 2, patience: int = 5, sampler_threads: int = 0,
        overlap=None, use_pallas: Optional[bool] = None,
        eval_rows_cap: int = EVAL_ROWS_CAP,
        checkpoint_dir: Optional[str] = None, resume: bool = False,
        checkpoint_every: int = 25,
        check: Optional[Callable[[], None]] = None,
        lifecycle: Optional[Callable[[str, dict], None]] = None,
        on_epoch: Optional[Callable[[int, float, float, float], None]] = None,
        console: Callable[[str], None] = print,
        shard_ctx=None, walk_starts: int = 0, edge_ctx=None,
        walker_backend: str = "native", device_feed: bool = False,
        ) -> StreamTrainResult:
    """Stream walk shards from the sampler pool straight into minibatch
    SGD; returns the trained embeddings plus the streaming twin of the
    stage-3 byproducts (gene frequency votes, total path count).

    ``groups`` is ``[(src_g, dst_g, w_g), (src_p, dst_p, w_p)]`` — the
    two thresholded per-group edge lists (the same arrays the full-batch
    stage 3 hands the walkers). ``overlap`` is the pipeline's
    OverlapScheduler; the producer runs on it as a DAG task under the
    existing drain contract (None spins a private thread). ``seed`` is
    the trainer's split/init seed, ``walk_seed`` the stage-3 walk seed —
    the same split the full-batch config makes.

    Durability: ``checkpoint_dir`` enables the (epoch, shard) cursor
    checkpoint every ``checkpoint_every`` shard updates and at every
    epoch boundary; ``resume=True`` restores the newest verified cursor
    and continues bitwise-identically (module docstring). ``check`` is
    the cooperative-interruption hook (resilience/lifecycle.py), called
    at every shard boundary — a :class:`DrainRequested` raised there
    checkpoints the current consistent state before propagating.
    ``lifecycle(state, info)`` observes "resumed"/"checkpointed".

    Scale-out (ROADMAP item 2): ``shard_ctx`` (parallel/shard.py) turns
    on one or both sharding axes. Graph sharding makes the producer an
    EXCHANGE: the rank owning shard ``si`` samples it and publishes the
    packed rows over the chunked KV transport; the others receive
    instead of sampling — then every rank spools and trains on every
    shard, so the in-ring trajectory is bit-identical to the unsharded
    stream (rewalk-on-corrupt stays local: the CSR is replicated and the
    walker is rank-independent deterministic). Embed sharding swaps the
    one-program SGD step for the split step (train/shard.py): each rank
    uploads only its byte-aligned column slice of every shard, holds
    ``[G/R, H]`` of the embedding, and one host allreduce of the hidden
    activations per step keeps the replicated head in lockstep. At one
    rank both axes route through EXACTLY the unsharded code below —
    byte-identity, pinned by tests/test_shard.py. ``walk_starts`` caps
    the number of start genes (parallel/shard.subset_starts; 0 = every
    gene, the reference semantics). Sharded runs do not compose with
    checkpoint/resume yet — the cursor would have to be a distributed
    snapshot.

    ``edge_ctx`` (parallel/shard.EdgeContext) turns the graph-sharded
    producer's SAMPLE step into a fleet collective: this rank holds only
    its owned gene range's CSR rows (plus halo rows in halo mode), every
    rank joins ``run_edge_walk`` for every shard (mid-walk handoff of
    suspended walk state, termination barrier), and the shard owner
    publishes the assembled rows over the same ``walk/{si}`` exchange —
    so downstream of the producer nothing changes, and the rows are
    byte-identical to the full-CSR mode's (the walk state carries its
    PRNG stream). Requires graph sharding at >1 rank; single-rank
    edge-partitioned runs pass None and use the plain paths below.
    """
    import jax
    import jax.numpy as jnp

    from g2vec_tpu.models.cbow import CBOWParams, init_params
    from g2vec_tpu.ops import packed_matmul as pm
    from g2vec_tpu.parallel.mesh import make_mesh_context, pad_to_multiple
    from g2vec_tpu.train.checkpoint import (RUN_COMPLETED, RUN_EARLY_STOPPED,
                                            RUN_IN_PROGRESS,
                                            load_stream_state,
                                            save_stream_state)
    from g2vec_tpu.train.shard import init_split_params, make_split_fns
    from g2vec_tpu.train.trainer import (_DTYPES, _get_stream_fns,
                                         _get_unpack_fn, _plan_layout,
                                         TrainResult)
    import optax

    if len(groups) != 2:
        raise ValueError(f"need exactly 2 groups, got {len(groups)}")
    if compute_dtype not in _DTYPES or param_dtype not in _DTYPES:
        raise ValueError(
            f"dtypes must be one of {sorted(_DTYPES)}, got "
            f"{compute_dtype!r}/{param_dtype!r}")
    if checkpoint_every < 1:
        raise ValueError(
            f"checkpoint_every must be >= 1, got {checkpoint_every}")

    # ---- sharding axes (docstring; parallel/shard.py) ----
    spec = shard_ctx.spec if shard_ctx is not None else None
    graph_multi = bool(spec and spec.graph_shards and spec.n_ranks > 1)
    embed_multi = bool(spec and spec.embed_split)
    if (graph_multi or embed_multi) and (checkpoint_dir or resume):
        raise ValueError(
            "sharded streaming (--graph-shards/--embed-shards at >1 "
            "process) does not compose with checkpoint/resume yet — the "
            "cursor would have to be a consistent distributed snapshot")
    if spec is not None and spec.n_genes != n_genes:
        raise ValueError(
            f"shard context was built for {spec.n_genes} genes, trainer "
            f"got {n_genes}")
    edge_multi = edge_ctx is not None
    if edge_multi and not graph_multi:
        raise ValueError(
            "edge_ctx (multi-rank --edge-partition) rides the "
            "graph-sharded producer's shard exchange; pass a multi-rank "
            "graph-sharded shard_ctx or None")

    starts = subset_starts(n_genes, walk_starts)
    n_starts = n_genes if starts is None else len(starts)
    plan = plan_shards(n_starts, reps, shard_paths, len_path=len_path)
    n_shards = plan.n_shards
    total_rows = 2 * plan.n_walkers
    stats = StreamStats(n_shards=n_shards, rows_sampled=total_rows,
                        shard_rows=plan.rows_per_shard,
                        ring_depth=prefetch_depth)

    if edge_multi:
        # The rank's PARTIAL per-group CSRs (already built and, in halo
        # mode, halo-merged by the pipeline): the groups' edge lists
        # cover only the owned gene range, so the full-graph walker
        # below must never run on them (_rewalk raises instead).
        csr = [p.csr for p in edge_ctx.pcsrs]
    else:
        csr = []
        for s, d, w in groups:
            _group_edges_csr(np.asarray(s), np.asarray(d), n_genes)
            csr.append(edges_to_csr(np.asarray(s), np.asarray(d),
                                    np.asarray(w), n_genes))

    if walker_backend not in ("native", "device"):
        raise ValueError(
            f"walker_backend must be native|device, got {walker_backend!r}")
    if walker_backend == "device" and (graph_multi or embed_multi
                                       or edge_multi):
        raise ValueError(
            "the device walker does not compose with sharded/edge-"
            "partitioned streaming yet — those producers exchange shards "
            "over host transports keyed to the native pool")
    if device_feed and walker_backend != "device":
        raise ValueError("device_feed requires walker_backend='device'")

    def _walk_group(gi: int, shard_index: int) -> np.ndarray:
        s, d, w = groups[gi]
        if walker_backend == "device":
            # Bit-exact device sampler (ops/device_walker.py): the SAME
            # packed bytes walk_shard would emit, so the ring, spool,
            # dedup, and every downstream consumer are backend-blind.
            return _device_walk_group(gi, shard_index)
        return walk_shard(np.asarray(s), np.asarray(d), np.asarray(w),
                          n_genes, plan, shard_index,
                          seed=(walk_seed << 1) | gi,
                          n_threads=sampler_threads, csr=csr[gi],
                          starts=starts)

    def _device_walk_group(gi: int, shard_index: int,
                           as_device: bool = False):
        """One group's shard rows via the device sampler; retries ONCE on
        a device_walk fault with a clean recompute — the sampler is a
        pure function of (plan, shard, seed), so the recomputed rows are
        byte-identical (the fault drill pins this)."""
        from g2vec_tpu.ops.device_walker import (walk_shard_device,
                                                 walk_shard_device_arrays)

        s, d, w = groups[gi]
        args = (np.asarray(s), np.asarray(d), np.asarray(w), n_genes,
                plan, shard_index)
        kw = dict(seed=(walk_seed << 1) | gi, csr=csr[gi], starts=starts)
        for attempt in (0, 1):
            try:
                if as_device:
                    return walk_shard_device_arrays(*args, **kw)
                return walk_shard_device(*args, **kw)
            except Exception:
                if attempt:
                    raise
                stats.device_recomputes += 1

    def _walk_shard_rows(shard_index: int) -> np.ndarray:
        return np.concatenate([_walk_group(0, shard_index),
                               _walk_group(1, shard_index)], axis=0)

    def _rewalk(shard_index: int) -> np.ndarray:
        """Rewalk-on-corrupt hook for the spool. Edge-partitioned ranks
        cannot rewalk alone — the shard's walks span every rank's CSR
        rows and the collective has long since moved on — so a corrupt
        spooled shard is terminal there instead of self-healing."""
        if edge_multi:
            raise SpoolIntegrityError(
                f"shard {shard_index}: spooled bytes failed verification "
                "and this rank holds only a partial CSR under "
                "--edge-partition; re-walking needs the whole fleet — "
                "restart the run")
        return _walk_shard_rows(shard_index)

    def _shard_labels(shard_index: int) -> np.ndarray:
        n = plan.group_rows(shard_index)
        return np.concatenate([np.zeros(n, np.int32),
                               np.ones(n, np.int32)])

    ring = ShardRing(prefetch_depth)
    if checkpoint_dir:
        # Durable spool: replay epochs AND resumes read it, so it lives
        # with the cursor checkpoint and survives the process. Removed
        # only by whoever owns checkpoint_dir (the serve daemon cleans it
        # with the job's terminal state).
        spool_dir = os.path.join(os.path.abspath(checkpoint_dir), "spool")
        os.makedirs(spool_dir, exist_ok=True)
        spool_is_tmp = False
    else:
        spool_dir = tempfile.mkdtemp(prefix="g2v-stream-")
        spool_is_tmp = True
    spool = ShardSpool(spool_dir)

    # --device-feed spool writes leave the fast path: one writer thread
    # persists each shard's bytes while the SGD step consumes the
    # device-resident copy. _drain_spool joins outstanding writes at
    # every consistency boundary (cursor cuts, replay start, teardown) —
    # durability is deferred, never dropped.
    stats.feed_mode = "device" if device_feed else "ring"
    spool_futs: Dict[int, object] = {}
    spool_pool = (ThreadPoolExecutor(max_workers=1,
                                     thread_name_prefix="g2v-spool")
                  if device_feed else None)

    def _spool_async(shard: Shard) -> None:
        spool_futs[shard.index] = spool_pool.submit(spool.save, shard)

    def _drain_spool() -> None:
        for si in sorted(spool_futs):
            spool_futs.pop(si).result()

    fingerprint = {
        "hidden": hidden, "learning_rate": learning_rate,
        "compute_dtype": compute_dtype, "param_dtype": param_dtype,
        "seed": seed, "walk_seed": walk_seed,
        "val_fraction": val_fraction,
        "decision_threshold": decision_threshold,
        "n_genes": n_genes, "len_path": len_path, "reps": reps,
        "n_shards": n_shards, "rows_per_shard": plan.rows_per_shard,
        "patience": patience, "eval_rows_cap": eval_rows_cap,
        "max_epochs": max_epochs, "walk_starts": walk_starts,
    }

    # ---- resume: restore the newest verified cursor BEFORE the producer
    # starts — it decides where (and whether) sampling restarts ----
    resume_arrays = resume_cursor = None
    if checkpoint_dir and resume:
        loaded = load_stream_state(checkpoint_dir, fingerprint=fingerprint)
        if loaded is not None:
            resume_arrays, resume_cursor = loaded
            spool._sha = {int(k): v for k, v in
                          resume_cursor.get("spool_sha", {}).items()}
            stats.resumed = 1
    start_epoch = int(resume_cursor["epoch"]) if resume_cursor else 0
    start_shard = int(resume_cursor["shard"]) if resume_cursor else 0
    resume_done = (int(resume_cursor.get("done", RUN_IN_PROGRESS))
                   if resume_cursor else RUN_IN_PROGRESS)

    producer_wall = [0.0]

    def _publish_rows(si: int, rows: np.ndarray, owner: int) -> None:
        from g2vec_tpu.parallel import hostcomm

        buf = io.BytesIO()
        np.save(buf, rows, allow_pickle=False)
        hostcomm.exchange_bytes(f"walk/{si}", buf.getvalue(), owner)

    def _recv_exchanged_rows(si: int, owner: int) -> Optional[np.ndarray]:
        """Peer side of the ``walk/{si}`` publish: polls in short
        slices, checking ``ring.cancelled`` between them, so a rank
        whose trainer already stopped returns None instead of waiting
        out the transport deadline on a publish that may never come."""
        from g2vec_tpu.parallel import hostcomm
        from g2vec_tpu.resilience.fleet import PeerTimeoutError

        budget = (shard_ctx.deadline if shard_ctx.deadline
                  else hostcomm.DEFAULT_DEADLINE_S)
        t_end = time.monotonic() + budget
        while True:
            left = t_end - time.monotonic()
            if left <= 0:
                # Re-raise the transport's own naming of the dead owner.
                return np.load(io.BytesIO(hostcomm.exchange_bytes(
                    f"walk/{si}", None, owner, deadline=1e-3)),
                    allow_pickle=False)
            try:
                raw = hostcomm.exchange_bytes(f"walk/{si}", None, owner,
                                              deadline=min(2.0, left))
                return np.load(io.BytesIO(raw), allow_pickle=False)
            except PeerTimeoutError:
                if ring.cancelled:
                    return None

    def _exchange_rows(si: int, owner: int) -> Optional[np.ndarray]:
        """The graph-sharded producer's shard ``si``: the owner samples
        and publishes (explicit-key chunked transport — this runs on the
        PRODUCER thread, so the seq-numbered collectives are off limits;
        parallel/hostcomm.py thread-safety note); the rest receive."""
        if owner == spec.rank:
            rows = _walk_shard_rows(si)
            # The dead-owner seam: sigkill here (before the publish)
            # leaves the peers' chunked get waiting; their deadline
            # expiry names this rank (tests/test_shard.py drill).
            fault_point("shard_exchange", epoch=si)
            _publish_rows(si, rows, owner)
            return rows
        return _recv_exchanged_rows(si, owner)

    def _edge_rows(si: int, owner: int) -> Optional[np.ndarray]:
        """The edge-partitioned producer's shard ``si``: EVERY rank
        joins the collective walk engine per group
        (parallel/shard.run_edge_walk — partial walks on the local CSR
        rows, suspended-state handoff, termination barrier; explicit
        keys, so producer-thread safe). The owner then publishes the
        assembled rows over the same ``walk/{si}`` exchange the
        graph-sharded producer uses; downstream of here the two
        producers are indistinguishable."""
        from g2vec_tpu.parallel.shard import run_edge_walk

        parts = []
        for gi in (0, 1):
            rows_g = run_edge_walk(
                edge_ctx.pcsrs[gi], plan, si,
                seed=(walk_seed << 1) | gi, owner=owner,
                rank=spec.rank, n_ranks=spec.n_ranks, starts=starts,
                n_threads=sampler_threads, deadline=shard_ctx.deadline,
                key_prefix=f"edgewalk/g{gi}",
                cancelled=lambda: ring.cancelled,
                stats=edge_ctx.stats)
            if rows_g is None and spec.rank == owner:
                return None          # consumer gone mid-collective
            parts.append(rows_g)
        if owner == spec.rank:
            rows = np.concatenate(parts, axis=0)
            fault_point("shard_exchange", epoch=si)
            _publish_rows(si, rows, owner)
            return rows
        return _recv_exchanged_rows(si, owner)

    def _produce():
        t0 = time.perf_counter()
        try:
            for si in range(start_shard, n_shards):
                if graph_multi:
                    owner = spec.shard_owner(si, n_shards)
                    rows = (_edge_rows(si, owner) if edge_multi
                            else _exchange_rows(si, owner))
                    if rows is None:
                        return      # consumer gone while waiting
                else:
                    rows = _walk_shard_rows(si)
                shard = Shard(si, rows, _shard_labels(si))
                path = spool.save(shard)
                # The in-flight-shard seam: kind=corrupt tears the SPOOLED
                # bytes (epoch 0 trains on the good in-memory copy; the
                # replay verification catches it), crash/stall/fatal
                # surface at the consumer's next get via ring.fail.
                fault_point("shard_ring", epoch=si, path=path)
                if not ring.put(shard):
                    return          # consumer gone; exit quietly
            ring.finish()
        except BaseException as e:  # noqa: BLE001 — consumer re-raises
            ring.fail(e)
        finally:
            producer_wall[0] = time.perf_counter() - t0

    # The producer (re)samples ONLY the epoch-0 tail: a resume at epoch
    # >= 1 (or at a terminal cursor) replays the durable spool instead.
    # The fused device feed has NO producer thread — epoch 0's shards
    # are sampled on device inside the consumer loop itself (the ring
    # stays empty; shards_emitted == 0 is the pinned assertion).
    need_producer = (resume_done == RUN_IN_PROGRESS and start_epoch == 0
                     and not device_feed)
    remove_closer = None
    producer_thread = None
    if need_producer:
        if overlap is not None:
            remove_closer = overlap.add_closer(ring.cancel)
            overlap.submit("stream_shards", _produce)
        else:
            producer_thread = threading.Thread(target=_produce,
                                               name="g2v-stream-producer",
                                               daemon=True)
            producer_thread.start()

    # ---- device layout: the full-batch derivation, per shard ----
    cdtype = _DTYPES[compute_dtype]
    pdtype = _DTYPES[param_dtype]
    rows_nom = plan.rows_per_shard
    tr_nom = max(1, min(int(rows_nom * (1.0 - val_fraction)), rows_nom - 1))
    if embed_multi:
        # The split step (train/shard.py) unpacks the rank's byte
        # columns inside its own jits — no mesh layout, no pallas, no
        # full-width [G] device padding; the per-rank device arrays are
        # [rows, nb_local] and [g_local_pad, H], never [G, ...].
        layout = None
        row_multiple = 8
        blo, bhi = spec.byte_range()
        nb_local = bhi - blo
        split_fns = make_split_fns(cdtype, decision_threshold)
        update_fn = eval_fn = None       # rebound to the split step below
    else:
        if device_feed:
            # The fused feed keeps packed rows device-resident in the
            # plain XLA unpack layout; the Pallas block-packed layout
            # packs on HOST (pm.pack_blockwise) and would reintroduce
            # the per-shard H2D hop the feed exists to remove.
            use_pallas = False
        ctx = make_mesh_context(None)
        layout = _plan_layout(tr_nom, n_genes, hidden, compute_dtype, ctx,
                              use_pallas)
        row_multiple = layout.row_multiple
        n_genes_pad = layout.n_genes_pad
        unpack_fn = None if layout.use_pallas else _get_unpack_fn(ctx, cdtype)
        update_fn, eval_fn = _get_stream_fns(
            learning_rate, cdtype, decision_threshold,
            packed=layout.use_pallas, interpret=layout.interpret)
    tr_pad = pad_to_multiple(tr_nom, row_multiple)

    def _pack_rows(rows_packed: np.ndarray, n_pad: int) -> np.ndarray:
        """Walker packbits rows -> the device layout, row-padded to n_pad
        (the full-batch _pack_split's per-chunk logic, one shard at a
        time). Embed-sharded: the rank's byte-column slice, nothing
        wider."""
        n = rows_packed.shape[0]
        if embed_multi:
            out = np.zeros((n_pad, nb_local), dtype=np.uint8)
            out[:n] = rows_packed[:, blo:bhi]
            return out
        out = np.zeros((n_pad, n_genes_pad // 8), dtype=np.uint8)
        if not layout.use_pallas and rows_packed.shape[1] == n_genes_pad // 8:
            out[:n] = rows_packed
            return out
        dense = np.unpackbits(rows_packed, axis=1)[:, :n_genes] != 0
        xb = np.zeros((n, n_genes_pad), dtype=bool)
        xb[:, :n_genes] = dense
        out[:n] = (pm.pack_blockwise(xb) if layout.use_pallas
                   else np.packbits(xb, axis=1))
        return out

    def _put_x(packed_np: np.ndarray):
        if embed_multi or layout.use_pallas:
            return jnp.asarray(packed_np)
        return unpack_fn(jnp.asarray(packed_np))

    def _upload(x_np, y_np, n_pad):
        n = x_np.shape[0]
        y = np.zeros((n_pad, 1), np.float32)
        y[:n, 0] = y_np
        w = np.zeros((n_pad, 1), np.float32)
        w[:n] = 1.0
        return (_put_x(_pack_rows(x_np, n_pad)), jnp.asarray(y),
                jnp.asarray(w))

    # ---- params + optimizer (the full-batch init at this layout) ----
    if embed_multi:
        params = init_split_params(jax.random.key(seed), n_genes, hidden,
                                   spec, param_dtype=pdtype)
    else:
        params = init_params(jax.random.key(seed), n_genes, hidden,
                             param_dtype=pdtype, pad_to=n_genes_pad)
    tx = optax.adam(learning_rate, b1=0.9, b2=0.999, eps=1e-8)
    opt_state = tx.init(params)
    snapshot = jax.tree.map(jnp.copy, params)

    if embed_multi:
        # The sharded step: local partial activations, ONE host
        # allreduce, replicated head math, local embedding gradient
        # (train/shard.py module docstring). Rebinding update_fn/eval_fn
        # keeps every downstream line of the epoch loop untouched.
        step_count = [0]

        def _apply_fn(params, opt_state, grads):
            updates, new_state = tx.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), new_state

        _split_apply = jax.jit(_apply_fn, donate_argnums=(0, 1))

        def _reduced_hidden(name, params, x_dev):
            h_part = np.asarray(split_fns.partial_hidden(params.w_ih, x_dev))
            return jnp.asarray(shard_ctx.allreduce(name, h_part))

        def _split_update(params, opt_state, x_dev, y_dev, w_dev):
            # The mid-step seam: a rank killed here leaves the others'
            # allgather waiting; the collective watchdog names it
            # (tests/test_shard.py drill).
            fault_point("embed_allreduce", epoch=step_count[0])
            step_count[0] += 1
            h = _reduced_hidden("h_step", params, x_dev)
            loss, dw_ho, dh = split_fns.head_grads(params.w_ho, h,
                                                   y_dev, w_dev)
            grads = CBOWParams(
                w_ih=split_fns.embed_grad(x_dev, dh).astype(
                    params.w_ih.dtype),
                w_ho=dw_ho.astype(params.w_ho.dtype))
            params, opt_state = _split_apply(params, opt_state, grads)
            return params, opt_state, loss

        def _split_eval(params, x_dev, y_dev, w_dev):
            h = _reduced_hidden("h_eval", params, x_dev)
            return split_fns.head_eval(params.w_ho, h, y_dev, w_dev)

        update_fn, eval_fn = _split_update, _split_eval
    # The checkpoint treedef: (params, opt_state, snapshot) flattened in
    # deterministic order — the train/checkpoint.py convention, with the
    # fresh init as the shape/dtype template.
    _, _state_treedef = jax.tree_util.tree_flatten(
        (params, opt_state, snapshot))
    if resume_arrays is not None:
        n_leaves = sum(1 for k in resume_arrays if k.startswith("leaf_"))
        params, opt_state, snapshot = jax.tree_util.tree_unflatten(
            _state_treedef, [jnp.asarray(resume_arrays[f"leaf_{i}"])
                             for i in range(n_leaves)])

    def _filter_rows(x: np.ndarray, y: np.ndarray) -> np.ndarray:
        """integrate_path_sets at shard granularity: drop rows whose path
        bytes appear in BOTH groups' blocks of this shard, and keep one
        copy per group of within-shard duplicates.

        Shard ``s`` covers the SAME walker-index range of both groups'
        axes, and walker i starts at the same gene in both — so the
        degenerate common paths (dead-end starts whose walks visit the
        identical gene set under both graphs) align inside one shard and
        are dropped HERE, with O(shard) memory, exactly where the
        full-batch common-path drop needed O(total). Cross-shard common
        paths survive as label noise; that residue is what the
        statistical (not bitwise) parity contract absorbs. Returns the
        kept row indices, in order.
        """
        row_bytes = [r.tobytes() for r in x]
        g_set = {b for b, l in zip(row_bytes, y) if l == 0}
        common = g_set & {b for b, l in zip(row_bytes, y) if l == 1}
        seen = (set(), set())
        keep = []
        for i, (b, l) in enumerate(zip(row_bytes, y)):
            if b in common or b in seen[l]:
                continue
            seen[l].add(b)
            keep.append(i)
        return np.asarray(keep, dtype=np.int64)

    # ---- epoch-0 byproducts, accumulated in shard order ----
    good_counts = np.zeros(n_genes, np.int64)
    poor_counts = np.zeros(n_genes, np.int64)
    val_x: List[np.ndarray] = []
    val_y: List[np.ndarray] = []
    probe_x: List[np.ndarray] = []
    probe_y: List[np.ndarray] = []
    eval_buffers = [0, 0]            # collected (val, probe) row counts
    kept_rows = [0]                  # rows surviving the per-shard filter

    # ---- early-stop bookkeeping (restored wholesale on resume) ----
    history: List[dict] = []
    best_val, best_tr = -1.0, -1.0
    best_epoch = 0
    since_best = 0
    stopped_early = False
    stop_epoch = max_epochs - 1
    losses0: List[float] = []        # restored partial-epoch loss prefix
    x_width = (n_genes + 7) // 8
    if resume_arrays is not None:
        good_counts[:] = resume_arrays["good_counts"]
        poor_counts[:] = resume_arrays["poor_counts"]
        val_x.append(resume_arrays["val_x"])
        val_y.append(resume_arrays["val_y"])
        probe_x.append(resume_arrays["probe_x"])
        probe_y.append(resume_arrays["probe_y"])
        sc = resume_arrays["scalars"]
        best_val, best_tr = float(sc[0]), float(sc[1])
        best_epoch, since_best = int(sc[2]), int(sc[3])
        kept_rows[0] = int(sc[4])
        eval_buffers[0], eval_buffers[1] = int(sc[5]), int(sc[6])
        stopped_early, stop_epoch = bool(sc[7]), int(sc[8])
        history = [{"epoch": int(r[0]), "acc_val": float(r[1]),
                    "acc_tr": float(r[2]), "loss": float(r[3]),
                    "secs": float(r[4])}
                   for r in resume_arrays["history"].reshape(-1, 5)]
        losses0 = [float(x) for x in resume_arrays["losses"]]

    def _accumulate(x: np.ndarray, y: np.ndarray, tr_idx, vl_idx) -> None:
        # Row-chunked unpack: the [rows, G] dense transient is capped at
        # ~32 MB regardless of G (at 1M genes a whole 4096-row shard
        # would be 4 GB dense). int64 sums are chunking-order-
        # independent, so the counts are bitwise those of the one-shot
        # unpack at any G.
        rows_chunk = max(1, (32 << 20) // max(1, n_genes))
        for i in range(0, x.shape[0], rows_chunk):
            dense = np.unpackbits(x[i:i + rows_chunk], axis=1)[:, :n_genes]
            yc = y[i:i + rows_chunk]
            good_counts[:] += dense[yc == 0].sum(axis=0, dtype=np.int64)
            poor_counts[:] += dense[yc == 1].sum(axis=0, dtype=np.int64)
        if eval_buffers[0] < eval_rows_cap and len(vl_idx):
            take = vl_idx[:eval_rows_cap - eval_buffers[0]]
            val_x.append(x[take])
            val_y.append(y[take])
            eval_buffers[0] += len(take)
        if eval_buffers[1] < eval_rows_cap and len(tr_idx):
            take = tr_idx[:eval_rows_cap - eval_buffers[1]]
            probe_x.append(x[take])
            probe_y.append(y[take])
            eval_buffers[1] += len(take)

    def _epoch0_iter(start: int = 0) -> Iterator[Shard]:
        for expect in range(start, n_shards):
            fault_point("prefetch", epoch=expect)
            shard = ring.get()
            if shard is None:
                raise RuntimeError(
                    f"shard ring closed after {expect}/{n_shards} shards — "
                    f"producer exited early without failing")
            if shard.index != expect:
                raise RuntimeError(
                    f"shard order violated: got {shard.index}, expected "
                    f"{expect}")
            yield shard

    def _replay_iter(start: int = 0) -> Iterator[Shard]:
        for si in range(start, n_shards):
            fault_point("prefetch", epoch=si)
            yield Shard(si, spool.load(si, _rewalk),
                        _shard_labels(si))

    def _device_feed(shards: Iterator[Shard], epoch0: bool):
        """The double buffer: shard b+1's H2D upload (and on-device
        unpack) is dispatched before shard b is yielded to the SGD step,
        so the upload hides under the step's device time.

        Yields ``(shard_index, accumulate_cb, (x, y, w))``. The epoch-0
        byproduct accumulation is DEFERRED to the yield (the consumer
        runs ``accumulate_cb`` right before the SGD step): the double
        buffer reads shard b+1 before shard b trains, and an eager
        accumulate there would make a checkpoint cut after shard b's
        update carry shard b+1's byproducts — a cursor the resume could
        never reproduce. Deferral keeps the H2D prefetch (the upload is
        still dispatched early) while the host-visible state advances in
        strict shard order.
        """
        pending = None
        for shard in shards:
            keep = _filter_rows(shard.x, shard.y)
            if not len(keep):
                continue             # every row was group-common noise
            fx, fy = shard.x[keep], shard.y[keep]
            tr_idx, vl_idx = _shard_split(fx.shape[0], seed, shard.index,
                                          val_fraction)
            acc_cb = None
            if epoch0:
                def acc_cb(fx=fx, fy=fy, tr=tr_idx, vl=vl_idx, k=len(keep)):
                    kept_rows[0] += k
                    _accumulate(fx, fy, tr, vl)
            nxt = (shard.index, acc_cb,
                   _upload(fx[tr_idx], fy[tr_idx], tr_pad))
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

    def _feed_x_device(packed_dev, row_idx: np.ndarray):
        """Device twin of _upload's x path: gather the kept train rows
        from the DEVICE-RESIDENT packed shard, pad rows and byte-columns
        to the exact layout _pack_rows builds (column pads are zero
        bytes; walker rows never set bits past n_genes, so the padded
        bytes are identical), and unpack on device. No packed training
        bytes cross host->device."""
        n = int(row_idx.shape[0])
        nbytes = int(packed_dev.shape[1])
        sel = jnp.take(packed_dev, jnp.asarray(row_idx, dtype=jnp.int32),
                       axis=0)
        out = jnp.zeros((tr_pad, n_genes_pad // 8), dtype=jnp.uint8)
        out = out.at[:n, :nbytes].set(sel)
        stats.h2d_bytes_saved += tr_pad * (n_genes_pad // 8)
        return unpack_fn(out)

    def _device_epoch0_feed(start: int):
        """Epoch 0 under --device-feed: each shard is sampled ON DEVICE
        (ops/device_walker.py) and its training rows consumed
        device-resident — zero ring puts, zero per-shard H2D for the
        minibatch step. One D2H copy per shard feeds the host-side
        byproducts (common-row filter, dedup, eval buffers — the bytes
        a resume checkpoint needs anyway) and the ASYNC spool write
        (epoch 1..N replay + durability; _drain_spool joins before any
        cursor cut and before replay). Same double-buffer discipline and
        deferred-accumulate contract as _device_feed, and bit-identical
        outputs: same rows, same filter, same split, same layout bytes.
        """
        pending = None
        for si in range(start, n_shards):
            fault_point("prefetch", epoch=si)
            t_walk = time.perf_counter()
            g0, _ = _device_walk_group(0, si, as_device=True)
            g1, _ = _device_walk_group(1, si, as_device=True)
            packed_dev = jnp.concatenate([g0, g1], axis=0)
            rows_np = np.asarray(packed_dev)       # one D2H per shard
            producer_wall[0] += time.perf_counter() - t_walk
            labels = _shard_labels(si)
            _spool_async(Shard(si, rows_np, labels))
            keep = _filter_rows(rows_np, labels)
            if not len(keep):
                continue             # every row was group-common noise
            fx, fy = rows_np[keep], labels[keep]
            tr_idx, vl_idx = _shard_split(fx.shape[0], seed, si,
                                          val_fraction)

            def acc_cb(fx=fx, fy=fy, tr=tr_idx, vl=vl_idx, k=len(keep)):
                kept_rows[0] += k
                _accumulate(fx, fy, tr, vl)

            n = int(tr_idx.shape[0])
            y = np.zeros((tr_pad, 1), np.float32)
            y[:n, 0] = fy[tr_idx]
            w = np.zeros((tr_pad, 1), np.float32)
            w[:n] = 1.0
            nxt = (si, acc_cb,
                   (_feed_x_device(packed_dev, keep[tr_idx]),
                    jnp.asarray(y), jnp.asarray(w)))
            if pending is not None:
                yield pending
            pending = nxt
        if pending is not None:
            yield pending

    # ---- the epoch loop ----
    # Early stop: the SAME metric as full-batch (held-out val accuracy,
    # snapshot-at-the-best returned), evaluated at shard-epoch
    # boundaries — but with PATIENCE instead of the first-strict-dip
    # rule. Per-shard SGD makes the epoch-boundary val accuracy jitter
    # in a way the full-batch trajectory never does (one noisy epoch 1
    # would end the run at random-init accuracy); ``patience``
    # consecutive epochs without a strict improvement over the best is
    # the minibatch-honest reading of "first decrease". patience=1
    # recovers the full-batch rule exactly.
    if patience < 1:
        raise ValueError(f"patience must be >= 1, got {patience}")
    val_dev = probe_dev = None
    t_phase0 = time.perf_counter()
    first_update_ms = None
    ckpt_count = [0]
    ckpt_wall = [0.0]

    def _host_eval_cat():
        """The eval buffers as single host array pairs (empty-safe) — the
        exact bytes a resume needs to rebuild the epoch-boundary eval."""
        if val_x:
            vx, vy = np.concatenate(val_x), np.concatenate(val_y)
        else:
            vx, vy = (np.zeros((0, x_width), np.uint8),
                      np.zeros((0,), np.int32))
        if probe_x:
            px, py = np.concatenate(probe_x), np.concatenate(probe_y)
        else:
            px, py = (np.zeros((0, x_width), np.uint8),
                      np.zeros((0,), np.int32))
        return vx, vy, px, py

    def _save_ckpt(cur_epoch: int, next_shard: int, cur_losses,
                   done: int = RUN_IN_PROGRESS) -> None:
        """Cut the cursor at the current consistent boundary: everything
        the loop owns, keyed to the NEXT shard to train."""
        if not checkpoint_dir:
            return
        if device_feed:
            # A cursor must never reference spool bytes still in flight
            # on the async writer — join them first (cheap: at most the
            # last shard or two are outstanding).
            _drain_spool()
        t0 = time.perf_counter()
        leaves, _ = jax.tree_util.tree_flatten(
            (params, opt_state, snapshot))
        arrays = {f"leaf_{i}": np.asarray(jax.device_get(leaf))
                  for i, leaf in enumerate(leaves)}
        vx, vy, px, py = _host_eval_cat()
        arrays.update(
            good_counts=good_counts, poor_counts=poor_counts,
            val_x=vx, val_y=vy, probe_x=px, probe_y=py,
            history=np.array(
                [[h["epoch"], h["acc_val"], h["acc_tr"], h["loss"],
                  h["secs"]] for h in history],
                np.float64).reshape(len(history), 5),
            losses=np.asarray([float(l) for l in cur_losses], np.float64),
            scalars=np.array(
                [best_val, best_tr, best_epoch, since_best, kept_rows[0],
                 eval_buffers[0], eval_buffers[1], float(stopped_early),
                 float(stop_epoch)], np.float64))
        cursor = {"epoch": int(cur_epoch), "shard": int(next_shard),
                  "done": int(done), "n_shards": int(n_shards),
                  "spool_sha": {str(k): v
                                for k, v in dict(spool._sha).items()}}
        path = save_stream_state(checkpoint_dir, arrays, cursor,
                                 fingerprint=fingerprint)
        ckpt_count[0] += 1
        ckpt_wall[0] += time.perf_counter() - t0
        if lifecycle is not None:
            lifecycle("checkpointed",
                      {"epoch": int(cur_epoch), "shard": int(next_shard),
                       "done": int(done), "path": path})

    def _checked(cur_epoch: int, next_shard: int, cur_losses) -> None:
        """Run the cooperative-interruption hook at a consistent
        boundary. A drain cuts a checkpoint at exactly this cursor before
        propagating (the next run resumes here); cancel/deadline
        propagate bare — they are terminal, there is nothing to keep."""
        if check is None:
            return
        try:
            check()
        except DrainRequested:
            _save_ckpt(cur_epoch, next_shard, cur_losses)
            raise

    def _build_result() -> StreamTrainResult:
        stats.n_paths = kept_rows[0]
        stats.epochs = len(history)
        stats.checkpoints = ckpt_count[0]
        stats.checkpoint_wall_s = round(ckpt_wall[0], 3)
        gene_freq: Dict[str, int] = {}
        for i, g in enumerate(genes):
            fg, fp = int(good_counts[i]), int(poor_counts[i])
            if fg == 0 and fp == 0:
                continue
            gene_freq[g] = 0 if fg > fp else (1 if fg < fp else 2)
        # Embed-sharded: the result carries THIS RANK's real gene rows
        # only ([g_local, H]); stages 5/6 run sharded on it and the
        # writer gathers rank-by-rank (pipeline.py). Unsharded: the full
        # table minus layout padding, as ever.
        w_ih = np.asarray(snapshot.w_ih.astype(jnp.float32)
                          [:(spec.g_local if embed_multi else n_genes)])
        train = TrainResult(
            w_ih=w_ih,
            stop_epoch=(best_epoch if stopped_early else stop_epoch),
            stopped_early=stopped_early,
            acc_val=best_val, acc_tr=best_tr, history=history,
            params=snapshot)
        return StreamTrainResult(train=train, gene_freq=gene_freq,
                                 n_paths=kept_rows[0], stats=stats)

    if resume_done != RUN_IN_PROGRESS:
        # The previous run FINISHED; the process died between its final
        # checkpoint and whatever consumed the result (the serve result
        # record, the output writer). Rebuild the result from state alone
        # — no producer, no training, byte-identical outputs.
        if lifecycle is not None:
            lifecycle("resumed", {"epoch": start_epoch,
                                  "shard": start_shard,
                                  "done": resume_done})
        _record_totals(epochs=0)
        return _build_result()

    if resume_cursor is not None:
        if lifecycle is not None:
            lifecycle("resumed", {"epoch": start_epoch,
                                  "shard": start_shard,
                                  "done": resume_done})
        console(f"[stream] resuming from cursor epoch {start_epoch} "
                f"shard {start_shard}/{n_shards}")
        if start_epoch > 0:
            # Epoch 0 finished, so the eval buffers are final: rebuild
            # the device copies the epoch-boundary eval reads (bitwise
            # the arrays the original epoch-0 pass uploaded).
            val_dev = _upload(val_x[0], val_y[0],
                              pad_to_multiple(eval_buffers[0],
                                              row_multiple))
            probe_dev = _upload(probe_x[0], probe_y[0],
                                pad_to_multiple(eval_buffers[1],
                                                row_multiple))

    try:
        epoch = start_epoch
        since_ckpt = 0
        while epoch < max_epochs and not stopped_early:
            t_epoch = time.perf_counter()
            resumed_here = (resume_cursor is not None
                            and epoch == start_epoch)
            offset = start_shard if resumed_here else 0
            losses = list(losses0) if resumed_here else []
            _checked(epoch, offset, losses)
            if device_feed and epoch == 0:
                feed = _device_epoch0_feed(offset)
            else:
                if device_feed:
                    _drain_spool()   # replay reads the spool; join writes
                feed = _device_feed(
                    _epoch0_iter(offset) if epoch == 0
                    else _replay_iter(offset),
                    epoch0=(epoch == 0))
            for si, acc_cb, (x_dev, y_dev, w_dev) in feed:
                _checked(epoch, si, losses)
                if acc_cb is not None:
                    acc_cb()
                params, opt_state, loss = update_fn(params, opt_state,
                                                    x_dev, y_dev, w_dev)
                if first_update_ms is None:
                    jax.block_until_ready(loss)
                    first_update_ms = (time.perf_counter() - t_phase0) * 1e3
                    stats.time_to_first_update_ms = round(first_update_ms, 2)
                    stats.shards_at_first_update = ring.shards_put
                losses.append(loss)
                since_ckpt += 1
                if checkpoint_dir and since_ckpt >= checkpoint_every \
                        and si + 1 < n_shards:
                    _save_ckpt(epoch, si + 1, losses)
                    since_ckpt = 0
            if epoch == 0:
                if eval_buffers[0] == 0:
                    raise ValueError(
                        "streaming val buffer is empty — shards contributed "
                        "no held-out rows (raise --shard-paths or "
                        "val_fraction)")
                # Collapse the eval buffers to ONE host array pair each:
                # the device copies feed the epoch-boundary eval; the
                # host cats stay behind for the cursor checkpoints (a
                # resume at epoch >= 1 re-uploads these exact bytes).
                val_x[:], val_y[:] = ([np.concatenate(val_x)],
                                      [np.concatenate(val_y)])
                probe_x[:], probe_y[:] = ([np.concatenate(probe_x)],
                                          [np.concatenate(probe_y)])
                val_dev = _upload(val_x[0], val_y[0],
                                  pad_to_multiple(eval_buffers[0],
                                                  row_multiple))
                probe_dev = _upload(probe_x[0], probe_y[0],
                                    pad_to_multiple(eval_buffers[1],
                                                    row_multiple))
            acc_val = float(eval_fn(params, *val_dev))
            acc_tr = float(eval_fn(params, *probe_dev))
            loss_mean = float(np.mean([float(l) for l in losses]))
            secs = time.perf_counter() - t_epoch
            history.append({"epoch": epoch, "acc_val": acc_val,
                            "acc_tr": acc_tr, "loss": loss_mean,
                            "secs": secs})
            if on_epoch is not None:
                on_epoch(epoch, acc_val, acc_tr, secs)
            fault_point("train", epoch=epoch)
            if acc_val > best_val:
                snapshot = jax.tree.map(jnp.copy, params)
                best_val, best_tr = acc_val, acc_tr
                best_epoch = epoch
                since_best = 0
            else:
                since_best += 1
                if since_best >= patience:
                    # Post-best epochs' updates are discarded: the
                    # best-epoch snapshot is the result (the full-batch
                    # dip convention, patience-widened).
                    stopped_early = True
                    stop_epoch = best_epoch
            epoch += 1
            if checkpoint_dir and not stopped_early and epoch < max_epochs:
                # Epoch-boundary cut: the cheapest resume point (no
                # partial-epoch losses, cursor shard 0).
                _save_ckpt(epoch, 0, [])
                since_ckpt = 0
        stats.epochs = len(history)
        # Terminal cut: the done code makes a post-completion relaunch
        # (death between here and the result consumer) rebuild the result
        # from state instead of retraining.
        _save_ckpt(epoch, 0, [],
                   done=(RUN_EARLY_STOPPED if stopped_early
                         else RUN_COMPLETED))
    finally:
        ring.cancel()
        if spool_pool is not None:
            try:
                _drain_spool()
            except BaseException:  # noqa: BLE001 — best-effort flush; a
                pass               # write error already failed the epoch
            spool_pool.shutdown(wait=True)
        if remove_closer is not None:
            remove_closer()
        if producer_thread is not None:
            producer_thread.join(timeout=30)
        elif overlap is not None and overlap.has("stream_shards"):
            try:
                overlap.result("stream_shards")
            except BaseException:  # noqa: BLE001 — best-effort join; the
                pass               # real error already surfaced at get()
        if spool_is_tmp:
            # A durable spool (checkpoint_dir) outlives the process — the
            # replay/resume contract needs it; its owner removes it with
            # the checkpoint directory.
            shutil.rmtree(spool_dir, ignore_errors=True)

    stats.shards_emitted = ring.shards_put
    stats.ring_occupancy_hw = ring.occupancy_hw
    stats.ring_peak_bytes = ring.peak_bytes
    stats.prefetch_wait_ms = round(ring.wait_get_s * 1e3, 2)
    stats.producer_blocked_s = round(ring.wait_put_s, 3)
    stats.sampling_wall_s = round(producer_wall[0], 3)
    stats.rewalks = spool.rewalks
    _record_totals(shards_emitted=stats.shards_emitted,
                   rewalks=stats.rewalks,
                   max_ring_occupancy_hw=stats.ring_occupancy_hw,
                   max_ring_peak_bytes=stats.ring_peak_bytes,
                   prefetch_wait_ms=stats.prefetch_wait_ms,
                   last_time_to_first_update_ms=(
                       stats.time_to_first_update_ms),
                   epochs=stats.epochs,
                   checkpoints=ckpt_count[0],
                   resumes=stats.resumed)

    return _build_result()
