"""Model families. The flagship is the "modified CBOW" bag-of-genes
classifier whose first weight matrix is the gene-embedding table."""
from g2vec_tpu.models.cbow import CBOWParams, forward, init_params, predict_logits  # noqa: F401
