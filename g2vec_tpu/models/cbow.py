"""The "modified CBOW" model — a two-matmul bag-of-genes sigmoid classifier.

Reference architecture (ref: G2Vec.py:231-251): multi-hot gene-set input
``X [batch, n_genes]`` -> hidden ``H = X @ W_ih`` -> scalar logit
``O = H @ W_ho``; no biases, no nonlinearity. The learned ``W_ih
[n_genes, hidden]`` IS the gene-embedding table (ref: G2Vec.py:286).

TPU mapping: both matmuls hit the MXU. The multi-hot X is kept in the compute
dtype (0/1 are exact in bfloat16); accumulation is forced to float32 via
``preferred_element_type`` so bf16 inputs don't cost accuracy in the
reduction. With a ('data','model') mesh, X is sharded [data, model] and
W_ih [model, -] so the gene-axis contraction psums over the model axis —
XLA/GSPMD inserts the collective from the sharding constraints alone.
"""
from __future__ import annotations

from math import sqrt
from typing import NamedTuple

import jax
import jax.numpy as jnp


class CBOWParams(NamedTuple):
    w_ih: jax.Array  # [n_genes, hidden] — the gene-embedding table
    w_ho: jax.Array  # [hidden, 1]


def init_params(key: jax.Array, n_genes: int, hidden: int,
                param_dtype=jnp.float32,
                pad_to: "int | None" = None) -> CBOWParams:
    """Truncated-normal init, std 1/sqrt(hidden) (ref: G2Vec.py:234-235).

    ``jax.random.truncated_normal(-2, 2)`` matches TF1's
    ``tf.truncated_normal`` (resample beyond 2 sigma).

    ``pad_to`` appends ZERO rows to W_ih up to the padded gene count. The
    random draw covers exactly the REAL genes, so the init — and therefore
    the whole seeded trajectory — is invariant to the layout's padding
    choice. (Drawing at the padded shape instead made the init a function
    of the kernel/mesh layout: jax.random counts over the flattened shape,
    so [704, h] and [1024, h] draws disagree at EVERY entry — the
    pallas-vs-XLA and per-mesh-shape drift the parity tests kept
    tripping over.) Pad rows only ever see all-zero X columns, collect
    exactly zero gradient, and Adam holds a zero-init zero-grad row at
    zero — they are dead weight sliced off before results surface.
    """
    k1, k2 = jax.random.split(key)
    std = 1.0 / sqrt(hidden)
    w_ih = jax.random.truncated_normal(k1, -2.0, 2.0, (n_genes, hidden)) * std
    if pad_to is not None and pad_to > n_genes:
        w_ih = jnp.concatenate(
            [w_ih, jnp.zeros((pad_to - n_genes, hidden), w_ih.dtype)], axis=0)
    w_ho = jax.random.truncated_normal(k2, -2.0, 2.0, (hidden, 1)) * std
    return CBOWParams(w_ih=w_ih.astype(param_dtype), w_ho=w_ho.astype(param_dtype))


def output_logits(h: jax.Array, w_ho: jax.Array,
                  compute_dtype=jnp.bfloat16) -> jax.Array:
    """Hidden [batch, hidden] -> logits [batch, 1] f32 (ref: G2Vec.py:240).

    Shared by the dense forward and the trainer's fused packed-X path so the
    output projection has exactly one definition."""
    return jax.lax.dot_general(
        h.astype(compute_dtype), w_ho.astype(compute_dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def forward(params: CBOWParams, x: jax.Array,
            compute_dtype=jnp.bfloat16) -> jax.Array:
    """Logits [batch, 1] in float32 regardless of compute dtype."""
    xc = x.astype(compute_dtype)
    h = jax.lax.dot_general(
        xc, params.w_ih.astype(compute_dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return output_logits(h, params.w_ho, compute_dtype)


def predict_logits(params: CBOWParams, x: jax.Array,
                   compute_dtype=jnp.bfloat16) -> jax.Array:
    """Alias used by serving/entry points."""
    return forward(params, x, compute_dtype)


def masked_bce_loss(logits: jax.Array, y: jax.Array,
                    w: jax.Array) -> jax.Array:
    """Weighted-mean sigmoid BCE (ref: the reference's reduce_mean at
    G2Vec.py:245, generalized to row masks).

    ``w`` is a [batch, 1] 0/1 row mask. Masked rows contribute EXACTLY
    zero (0.0 * finite bce) to both the numerator and the denominator, so
    shard-padding rows — and, under the trainer's fused-eval fold, the
    val-split rows riding the same forward — leave the train loss and its
    gradients bitwise-unchanged: IEEE x + 0.0 == x, and appended zero
    terms never regroup the live terms' reduction order. ONE definition
    shared by the trainer's chunk program and bench.py's standalone
    breakdown pieces, so the measured terms are the shipped math.
    """
    import optax

    bce = optax.sigmoid_binary_cross_entropy(logits, y)
    return jnp.sum(bce * w) / jnp.sum(w)


def accuracy_from_logits(logits: jax.Array, y: jax.Array, w: jax.Array,
                         logit_threshold: float = 0.0) -> jax.Array:
    """Masked classification accuracy at a logit threshold.

    Numerator and denominator are sums of exact 0/1 float terms, so the
    result is reduction-order-independent (exact integers below 2^24) —
    the property the fused-eval fold's bitwise-parity contract leans on
    when the same rows land at different offsets of a bigger batch.
    """
    pred = (logits > logit_threshold).astype(jnp.float32)
    return jnp.sum((pred == y).astype(jnp.float32) * w) / jnp.sum(w)
