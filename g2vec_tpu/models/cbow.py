"""The "modified CBOW" model — a two-matmul bag-of-genes sigmoid classifier.

Reference architecture (ref: G2Vec.py:231-251): multi-hot gene-set input
``X [batch, n_genes]`` -> hidden ``H = X @ W_ih`` -> scalar logit
``O = H @ W_ho``; no biases, no nonlinearity. The learned ``W_ih
[n_genes, hidden]`` IS the gene-embedding table (ref: G2Vec.py:286).

TPU mapping: both matmuls hit the MXU. The multi-hot X is kept in the compute
dtype (0/1 are exact in bfloat16); accumulation is forced to float32 via
``preferred_element_type`` so bf16 inputs don't cost accuracy in the
reduction. With a ('data','model') mesh, X is sharded [data, model] and
W_ih [model, -] so the gene-axis contraction psums over the model axis —
XLA/GSPMD inserts the collective from the sharding constraints alone.
"""
from __future__ import annotations

from math import sqrt
from typing import NamedTuple

import jax
import jax.numpy as jnp


class CBOWParams(NamedTuple):
    w_ih: jax.Array  # [n_genes, hidden] — the gene-embedding table
    w_ho: jax.Array  # [hidden, 1]


def init_params(key: jax.Array, n_genes: int, hidden: int,
                param_dtype=jnp.float32) -> CBOWParams:
    """Truncated-normal init, std 1/sqrt(hidden) (ref: G2Vec.py:234-235).

    ``jax.random.truncated_normal(-2, 2)`` matches TF1's
    ``tf.truncated_normal`` (resample beyond 2 sigma)."""
    k1, k2 = jax.random.split(key)
    std = 1.0 / sqrt(hidden)
    w_ih = jax.random.truncated_normal(k1, -2.0, 2.0, (n_genes, hidden)) * std
    w_ho = jax.random.truncated_normal(k2, -2.0, 2.0, (hidden, 1)) * std
    return CBOWParams(w_ih=w_ih.astype(param_dtype), w_ho=w_ho.astype(param_dtype))


def output_logits(h: jax.Array, w_ho: jax.Array,
                  compute_dtype=jnp.bfloat16) -> jax.Array:
    """Hidden [batch, hidden] -> logits [batch, 1] f32 (ref: G2Vec.py:240).

    Shared by the dense forward and the trainer's fused packed-X path so the
    output projection has exactly one definition."""
    return jax.lax.dot_general(
        h.astype(compute_dtype), w_ho.astype(compute_dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)


def forward(params: CBOWParams, x: jax.Array,
            compute_dtype=jnp.bfloat16) -> jax.Array:
    """Logits [batch, 1] in float32 regardless of compute dtype."""
    xc = x.astype(compute_dtype)
    h = jax.lax.dot_general(
        xc, params.w_ih.astype(compute_dtype),
        (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return output_logits(h, params.w_ho, compute_dtype)


def predict_logits(params: CBOWParams, x: jax.Array,
                   compute_dtype=jnp.bfloat16) -> jax.Array:
    """Alias used by serving/entry points."""
    return forward(params, x, compute_dtype)
