"""jax-purity: jax-free module boundaries + jit host-bounce lint.

Three sub-checks, all pure AST:

1. **jax-free closure.** The declared jax-free modules (the router
   stack and the chaos tool must start fast and run on boxes with no
   accelerator stack) may not reach ``jax``/``jaxlib`` through the
   MODULE-LEVEL import graph. The walk models real import semantics:
   importing ``g2vec_tpu.serve.daemon`` executes ``g2vec_tpu/__init__``
   and ``g2vec_tpu/serve/__init__`` too, so a jax import smuggled into
   a package init is caught even though no declared module names it.
   Function-local (deferred) imports in *transitive* deps are the
   repo's sanctioned lazy idiom and are allowed; a declared module
   itself must not import jax anywhere, deferred or not.
2. **jit host bounces.** Functions handed to ``jax.jit`` / ``vmap`` /
   ``pmap`` / ``lax.while_loop`` / ``lax.scan`` / ``lax.fori_loop``
   (decorator, ``partial(jit, ...)``, or direct call form) under
   ``ops/`` and ``train/`` must not call ``np.asarray``/``np.array``,
   ``.item()``, ``time.*``, or Python RNG (``random.*`` /
   ``np.random.*``) — each is a trace-time constant or a silent
   device→host sync (the PR 5 "np bounce" class).
3. **use-after-donate.** After ``g = jax.jit(f, donate_argnums=(0,))``
   and ``out = g(x)``, a later read of ``x`` in the same function is
   a use of a donated (invalidated) buffer — flagged as a warning
   unless the call rebinds the same name (the in-place update idiom).
"""
from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from g2vec_tpu.analyze.core import (AnalysisContext, Checker, Finding,
                                    SourceFile)

#: Modules that must never reach jax at import time (relpath -> why).
JAX_FREE = {
    "g2vec_tpu/serve/protocol.py":
        "shared by the router process, which never imports jax",
    "g2vec_tpu/serve/router.py":
        "the front door must boot in milliseconds on accelerator-free "
        "hosts",
    "g2vec_tpu/serve/leader.py":
        "the leadership lease is watched by standby routers on "
        "accelerator-free hosts",
    "g2vec_tpu/resilience/lifecycle.py":
        "imported by router and daemon alike; pure state machines",
    "tools/chaos_soak.py":
        "the soak harness supervises daemons, it never owns a device",
}

_JIT_WRAPPERS = {"jit", "vmap", "pmap"}
_LAX_BODY_ARG = {"while_loop": (0, 1), "scan": (0,), "fori_loop": (2,),
                 "cond": (1, 2)}


def _dotted(node: ast.AST) -> Optional[str]:
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


class JaxPurityChecker(Checker):
    id = "jax-purity"
    description = ("jax-free module closure, host bounces inside jitted "
                   "functions, donated-buffer reuse")
    severity = "error"

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        self._check_jax_free(ctx, findings)
        for sf in (ctx.files("g2vec_tpu/ops")
                   + ctx.files("g2vec_tpu/train")):
            self._check_jit_purity(ctx, sf, findings)
        return findings

    # ---- jax-free import closure ------------------------------------------

    def _module_files(self, ctx: AnalysisContext,
                      modname: str) -> List[str]:
        """Repo files executed by importing ``modname``: the module
        itself plus every ancestor package ``__init__``. Empty for
        external modules."""
        parts = modname.split(".")
        out: List[str] = []
        for i in range(1, len(parts) + 1):
            prefix = parts[:i]
            pkg = "/".join(prefix) + "/__init__.py"
            mod = "/".join(prefix) + ".py"
            if ctx.file(pkg) is not None:
                out.append(pkg)
            elif i == len(parts) and ctx.file(mod) is not None:
                out.append(mod)
            elif i < len(parts) and ctx.file(mod) is not None:
                # ``from g2vec_tpu.config import X``: config is a
                # module, X an attribute.
                out.append(mod)
                break
        return out

    def _top_level_imports(self, sf: SourceFile) \
            -> List[Tuple[str, int]]:
        """(module name, line) for every import that executes at module
        import time — module body including class bodies and top-level
        try/if, excluding function bodies (the lazy idiom)."""
        out: List[Tuple[str, int]] = []
        tree = sf.tree
        if tree is None:
            return out

        def visit(stmts):
            for stmt in stmts:
                if isinstance(stmt, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(stmt, ast.Import):
                    for alias in stmt.names:
                        out.append((alias.name, stmt.lineno))
                elif isinstance(stmt, ast.ImportFrom):
                    base = stmt.module or ""
                    if stmt.level:
                        # Relative import: anchor at the file's package.
                        pkg = os.path.dirname(sf.relpath).replace("/",
                                                                  ".")
                        for _ in range(stmt.level - 1):
                            pkg = pkg.rpartition(".")[0]
                        base = f"{pkg}.{base}".rstrip(".") if base \
                            else pkg
                    if base:
                        out.append((base, stmt.lineno))
                        for alias in stmt.names:
                            # ``from pkg import sub`` may bind a
                            # submodule — the walk resolves both.
                            out.append((f"{base}.{alias.name}",
                                        stmt.lineno))
                for field in ("body", "orelse", "finalbody"):
                    sub = getattr(stmt, field, None)
                    if sub:
                        visit(sub)
                for handler in getattr(stmt, "handlers", []):
                    visit(handler.body)

        visit(tree.body)
        return out

    def _check_jax_free(self, ctx: AnalysisContext,
                        findings: List[Finding]) -> None:
        for root, why in sorted(JAX_FREE.items()):
            sf = ctx.file(root)
            if sf is None:
                continue
            # A declared module must not import jax anywhere AT ALL,
            # even deferred (that would just move the cost to runtime).
            tree = sf.tree
            if tree is not None:
                for node in ast.walk(tree):
                    names = []
                    if isinstance(node, ast.Import):
                        names = [(a.name, node.lineno)
                                 for a in node.names]
                    elif isinstance(node, ast.ImportFrom) and \
                            node.module:
                        names = [(node.module, node.lineno)]
                    for name, line in names:
                        top = name.split(".")[0]
                        if top in ("jax", "jaxlib"):
                            findings.append(ctx.finding(
                                self, sf, line,
                                f"{root} is declared jax-free ({why}) "
                                f"but imports {name} directly"))
            # BFS over module-level imports with parent chains.
            parent: Dict[str, Tuple[Optional[str], int]] = {
                root: (None, 0)}
            queue = [root]
            while queue:
                cur = queue.pop(0)
                cur_sf = ctx.file(cur)
                if cur_sf is None:
                    continue
                for modname, line in self._top_level_imports(cur_sf):
                    top = modname.split(".")[0]
                    if top in ("jax", "jaxlib"):
                        chain = [f"{top} (at line {line})"]
                        hop: Optional[str] = cur
                        while hop is not None:
                            chain.append(hop)
                            hop = parent[hop][0]
                        findings.append(ctx.finding(
                            self, sf, parent.get(cur, (None, 1))[1] or 1,
                            f"{root} is declared jax-free ({why}) but "
                            f"reaches jax at import time: "
                            f"{' <- '.join(reversed(chain))}"))
                        continue
                    for dep in self._module_files(ctx, modname):
                        if dep not in parent:
                            parent[dep] = (cur,
                                           line if cur == root
                                           else parent[cur][1])
                            queue.append(dep)

    # ---- jit purity --------------------------------------------------------

    def _check_jit_purity(self, ctx: AnalysisContext, sf: SourceFile,
                          findings: List[Finding]) -> None:
        tree = sf.tree
        if tree is None:
            return
        defs: Dict[str, ast.AST] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        staged: List[Tuple[ast.AST, str]] = []

        def is_jit_ctor(call: ast.Call) -> Optional[str]:
            name = _dotted(call.func)
            if name is None:
                return None
            leaf = name.split(".")[-1]
            if leaf in _JIT_WRAPPERS and \
                    (name == leaf or name.startswith(("jax.", "lax."))):
                return leaf
            if leaf == "partial" and call.args:
                inner = _dotted(call.args[0])
                if inner and inner.split(".")[-1] in _JIT_WRAPPERS:
                    return inner.split(".")[-1]
            return None

        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    tag = (is_jit_ctor(dec)
                           if isinstance(dec, ast.Call)
                           else (_dotted(dec) or "").split(".")[-1])
                    if tag in _JIT_WRAPPERS:
                        staged.append((node, f"@{tag}"))
            elif isinstance(node, ast.Call):
                name = _dotted(node.func)
                tag = is_jit_ctor(node)
                if tag and node.args:
                    target = node.args[0]
                    self._stage(target, defs, staged, tag)
                if name:
                    leaf = name.split(".")[-1]
                    if leaf in _LAX_BODY_ARG and \
                            ("lax" in name or "jax" in name):
                        for pos in _LAX_BODY_ARG[leaf]:
                            if pos < len(node.args):
                                self._stage(node.args[pos], defs,
                                            staged, leaf)
        seen: Set[int] = set()
        for fn, how in staged:
            if id(fn) in seen:
                continue
            seen.add(id(fn))
            self._scan_staged(ctx, sf, fn, how, findings)
        self._check_donate(ctx, sf, tree, findings)

    def _stage(self, target: ast.AST, defs: Dict[str, ast.AST],
               staged: List[Tuple[ast.AST, str]], how: str) -> None:
        if isinstance(target, ast.Lambda):
            staged.append((target, how))
        elif isinstance(target, ast.Name) and target.id in defs:
            staged.append((defs[target.id], how))

    def _scan_staged(self, ctx: AnalysisContext, sf: SourceFile,
                     fn: ast.AST, how: str,
                     findings: List[Finding]) -> None:
        name = getattr(fn, "name", "<lambda>")
        for node in ast.walk(fn):
            if not isinstance(node, ast.Call):
                continue
            dn = _dotted(node.func) or ""
            bad = None
            if dn in ("np.asarray", "numpy.asarray", "np.array",
                      "numpy.array"):
                bad = f"{dn} (host materialization at trace time)"
            elif isinstance(node.func, ast.Attribute) and \
                    node.func.attr == "item" and not node.args:
                bad = ".item() (device->host sync; a traced value " \
                      "has no .item)"
            elif dn.startswith("time."):
                bad = f"{dn} (wall-clock is a trace-time constant " \
                      f"inside a staged function)"
            elif dn.startswith(("random.", "np.random.",
                                "numpy.random.")):
                bad = f"{dn} (Python/numpy RNG is trace-time state; " \
                      f"use jax.random with an explicit key)"
            if bad:
                findings.append(ctx.finding(
                    self, sf, node.lineno,
                    f"{name} is staged via {how} but calls {bad}"))

    # ---- donated buffers ---------------------------------------------------

    def _check_donate(self, ctx: AnalysisContext, sf: SourceFile,
                      tree: ast.Module,
                      findings: List[Finding]) -> None:
        donating: Dict[str, Tuple[int, ...]] = {}
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                call = node.value
                if not any(kw.arg == "donate_argnums"
                           for kw in call.keywords):
                    continue
                try:
                    spec = next(kw.value for kw in call.keywords
                                if kw.arg == "donate_argnums")
                    nums = ast.literal_eval(spec)
                except (ValueError, StopIteration):
                    continue
                nums = (nums,) if isinstance(nums, int) else tuple(nums)
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        donating[t.id] = nums
        if not donating:
            return
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            self._donate_in_fn(ctx, sf, fn, donating, findings)

    def _donate_in_fn(self, ctx: AnalysisContext, sf: SourceFile,
                      fn: ast.AST, donating: Dict[str, Tuple[int, ...]],
                      findings: List[Finding]) -> None:
        #: donated name -> line of the donating call
        dead: Dict[str, int] = {}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                call = node.value
                cn = _dotted(call.func)
                if cn in donating:
                    rebound = {leaf.id for t in node.targets
                               for leaf in ast.walk(t)
                               if isinstance(leaf, ast.Name)}
                    for pos in donating[cn]:
                        if pos < len(call.args) and \
                                isinstance(call.args[pos], ast.Name):
                            arg = call.args[pos].id
                            if arg not in rebound:
                                dead[arg] = node.lineno
                    for t in rebound:
                        dead.pop(t, None)
            elif isinstance(node, ast.Name) and \
                    isinstance(node.ctx, ast.Load) and \
                    node.id in dead and node.lineno > dead[node.id]:
                findings.append(ctx.finding(
                    self, sf, node.lineno,
                    f"{node.id} was donated to a jitted call "
                    f"(donate_argnums) and read afterwards — the "
                    f"buffer is invalidated on the device",
                    severity="warning"))
                dead.pop(node.id)
