"""fault-seams: the ``fault_point`` vocabulary is a closed registry.

``resilience/faults.py`` declares the seam names (``SEAMS``); fault
plans, chaos tests, and the soak harness all speak that vocabulary as
string literals. Nothing ties the strings together at runtime — a typo
in a ``fault_point("hartbeat")`` call site silently never fires, and a
seam whose last call site was refactored away leaves chaos plans
testing nothing. So:

- every ``fault_point(<literal>)`` names a declared seam;
- a non-literal seam argument is flagged (the registry only works if
  the vocabulary is greppable);
- every declared seam has >= 1 call site outside faults.py (a seam with
  no call site is dead vocabulary) and >= 1 word-boundary reference
  under tests/ (an untested seam is an untested failure mode).

The SEAMS tuple is read by AST, not by import, so the checker works on
fixture trees and never executes repo code.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Tuple

from g2vec_tpu.analyze.core import AnalysisContext, Checker, Finding

FAULTS_FILE = "g2vec_tpu/resilience/faults.py"


class FaultSeamChecker(Checker):
    id = "fault-seams"
    description = ("fault_point literals vs the declared SEAMS registry; "
                   "every seam called and test-referenced")
    severity = "error"

    def _declared(self, ctx: AnalysisContext) \
            -> Optional[Tuple[List[str], int]]:
        sf = ctx.file(FAULTS_FILE)
        if sf is None or sf.tree is None:
            return None
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and t.id == "SEAMS":
                        try:
                            seams = list(ast.literal_eval(node.value))
                        except ValueError:
                            return None
                        return seams, node.lineno
        return None

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        decl = self._declared(ctx)
        if decl is None:
            return findings          # fixture tree without a registry
        seams, decl_line = decl
        declared = set(seams)
        call_sites: Dict[str, int] = {}
        for sf in ctx.files():
            if sf.relpath == FAULTS_FILE or \
                    sf.relpath.startswith("tests/"):
                continue
            tree = sf.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                fn = node.func
                name = (fn.id if isinstance(fn, ast.Name)
                        else fn.attr if isinstance(fn, ast.Attribute)
                        else None)
                if name != "fault_point" or not node.args:
                    continue
                arg = node.args[0]
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str):
                    seam = arg.value
                    call_sites[seam] = call_sites.get(seam, 0) + 1
                    if seam not in declared:
                        findings.append(ctx.finding(
                            self, sf, node.lineno,
                            f"fault_point({seam!r}) names an "
                            f"undeclared seam — add it to SEAMS in "
                            f"{FAULTS_FILE} or fix the typo"))
                else:
                    findings.append(ctx.finding(
                        self, sf, node.lineno,
                        f"fault_point seam argument is not a string "
                        f"literal — the registry is only checkable "
                        f"when the vocabulary is greppable"))
        tests_text = "\n".join(sf.text for sf in ctx.files("tests"))
        faults_sf = ctx.file(FAULTS_FILE)
        for seam in seams:
            if not call_sites.get(seam):
                findings.append(ctx.finding(
                    self, faults_sf, decl_line,
                    f"seam {seam!r} is declared in SEAMS but has no "
                    f"fault_point call site — dead vocabulary"))
            if tests_text and not re.search(
                    r"\b%s\b" % re.escape(seam), tests_text):
                findings.append(ctx.finding(
                    self, faults_sf, decl_line,
                    f"seam {seam!r} is declared in SEAMS but no test "
                    f"references it — an untested failure mode"))
        return findings
