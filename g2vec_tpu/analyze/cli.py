"""``python -m g2vec_tpu analyze`` — the static-check front end.

Exit-code contract (relied on by watch_loop.sh and the smoke tests):

- ``0`` — clean: no active findings, no stale baseline entries;
- ``1`` — findings (or a stale baseline: shrink-only means a fixed
  finding must also drop its suppression);
- ``2`` — usage error (unknown flag or ``--checker`` id).

The suite is pure AST, so this subcommand never imports jax and runs
in well under a second on the whole repo — cheap enough for every
watch-loop arm and pre-push hook.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import List, Optional

from g2vec_tpu.analyze.core import (all_checkers, run_analysis,
                                    save_baseline)


def _default_root() -> str:
    """The repo root: the directory holding the g2vec_tpu package."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.dirname(os.path.dirname(here))


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="g2vec analyze",
        description="Run the g2vec static-analysis suite "
                    "(lock discipline, jax purity, fault seams, "
                    "metrics schemas, config/doc drift).")
    p.add_argument("--root", default=None,
                   help="repo root to scan (default: the checkout "
                        "containing this package)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable report on stdout")
    p.add_argument("--baseline", default=None,
                   help="suppression file (default: "
                        "<root>/ANALYZE_BASELINE.json)")
    p.add_argument("--checker", action="append", default=None,
                   metavar="ID",
                   help="run only this checker (repeatable); "
                        "see --list-checkers")
    p.add_argument("--list-checkers", action="store_true",
                   help="print checker ids and exit")
    p.add_argument("--write-baseline", action="store_true",
                   help="write the current active findings as the new "
                        "baseline (deliberate growth — CI refuses it)")
    return p


def analyze_main(argv: Optional[List[str]] = None) -> int:
    try:
        args = build_parser().parse_args(argv)
    except SystemExit as e:
        # argparse exits 2 on usage errors and 0 on --help; preserve.
        return int(e.code or 0)
    if args.list_checkers:
        for c in all_checkers():
            print(f"{c.id:18s} {c.description}")
        return 0
    root = os.path.abspath(args.root or _default_root())
    baseline = args.baseline or os.path.join(root,
                                             "ANALYZE_BASELINE.json")
    t0 = time.perf_counter()
    try:
        report = run_analysis(root, checker_ids=args.checker,
                              baseline_path=baseline)
    except KeyError as e:
        print(f"g2vec analyze: {e.args[0]}", file=sys.stderr)
        return 2
    dt = time.perf_counter() - t0
    if args.write_baseline:
        save_baseline(baseline, report.findings)
        print(f"[analyze] wrote {len(report.findings)} suppression(s) "
              f"to {baseline}")
        return 0
    if args.json:
        out = report.to_dict()
        out["elapsed_s"] = round(dt, 3)
        json.dump(out, sys.stdout)
        print()
    else:
        for f in report.findings:
            print(f"{f.location()}: [{f.checker}] {f.severity}: "
                  f"{f.message}   ({f.context})")
        for fp in sorted(report.stale_baseline):
            print(f"{baseline}: stale suppression {fp} — the finding "
                  f"is gone, remove the entry (shrink-only)")
        counts = (f"{len(report.findings)} finding(s), "
                  f"{len(report.waived)} waived, "
                  f"{len(report.baselined)} baselined, "
                  f"{len(report.stale_baseline)} stale")
        status = "clean" if report.clean else "FAIL"
        print(f"[analyze] {status}: {counts} "
              f"({', '.join(report.checkers_run)}; {dt:.2f}s)")
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(analyze_main())
