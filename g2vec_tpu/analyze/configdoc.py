"""config-doc-drift: the flag surface, job-key map, and payload
vocabulary stay mutually consistent.

Three registries drift independently today:

- **CLI flags vs README.** ``config.build_parser`` is the source of
  truth for the flag surface; the README's flag tables are what users
  read. Every ``--flag`` the parser accepts must appear in README.md.
- **SERVE_JOB_KEYS vs G2VecConfig.** The serve job schema whitelists
  which config fields a submitted job may set. A key that is not a
  real dataclass field is accepted-then-ignored — the worst kind of
  API lie.
- **Serve payload keys vs the protocol vocabularies.** daemon.py/
  router.py read request-envelope keys by string; the jax-free
  protocol module owns the vocabularies. Each envelope has a
  conventional variable name, and every key read through that name is
  linted against its tuple: ``payload`` → ``SUBMIT_KEYS``, ``qreq``
  (query requests) → ``QUERY_KEYS``, ``rreq`` (result requests) →
  ``RESULT_KEYS``. A key read in the daemon but absent from its
  whitelist is either a typo or an undocumented protocol extension.

Everything is AST + text: flags from ``add_argument`` literals, fields
from the dataclass's annotated assignments, payload keys from
``<name>["k"]`` / ``<name>.get("k")`` subscripts on the conventional
envelope names above.
"""
from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from g2vec_tpu.analyze.core import (AnalysisContext, Checker, Finding,
                                    SourceFile)

CONFIG_FILE = "g2vec_tpu/config.py"
PROTOCOL_FILE = "g2vec_tpu/serve/protocol.py"
README = "README.md"
_PAYLOAD_FILES = ("g2vec_tpu/serve/daemon.py",
                  "g2vec_tpu/serve/router.py")
#: Conventional envelope variable name -> the protocol tuple its key
#: reads are linted against.
_ENVELOPES = {"payload": "SUBMIT_KEYS",
              "qreq": "QUERY_KEYS",
              "fqreq": "FQUERY_KEYS",
              "rreq": "RESULT_KEYS",
              "ureq": "UPDATE_KEYS"}


def _tuple_of_str(tree: ast.Module, name: str) -> Optional[Set[str]]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == name:
                    try:
                        return set(ast.literal_eval(node.value))
                    except ValueError:
                        return None
    return None


class ConfigDocChecker(Checker):
    id = "config-doc-drift"
    description = ("CLI flags vs README, SERVE_JOB_KEYS vs config "
                   "fields, serve payload keys vs protocol.SUBMIT_KEYS")
    severity = "error"

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        cfg = ctx.file(CONFIG_FILE)
        if cfg is None or cfg.tree is None:
            return findings          # fixture tree without a config
        self._check_flags(ctx, cfg, findings)
        self._check_job_keys(ctx, cfg, findings)
        self._check_payload_keys(ctx, findings)
        return findings

    def _check_flags(self, ctx: AnalysisContext, cfg: SourceFile,
                     findings: List[Finding]) -> None:
        readme_path = ctx.file(README)
        if readme_path is None:
            return
        readme = readme_path.text
        for node in ast.walk(cfg.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "add_argument"):
                continue
            for arg in node.args:
                if isinstance(arg, ast.Constant) and \
                        isinstance(arg.value, str) and \
                        arg.value.startswith("--"):
                    if arg.value not in readme:
                        findings.append(ctx.finding(
                            self, cfg, node.lineno,
                            f"CLI flag {arg.value} is accepted by "
                            f"config.build_parser but never mentioned "
                            f"in {README}"))

    def _check_job_keys(self, ctx: AnalysisContext, cfg: SourceFile,
                        findings: List[Finding]) -> None:
        keys = _tuple_of_str(cfg.tree, "SERVE_JOB_KEYS")
        if keys is None:
            return
        fields: Set[str] = set()
        for node in ast.walk(cfg.tree):
            if isinstance(node, ast.ClassDef) and \
                    node.name == "G2VecConfig":
                for stmt in node.body:
                    if isinstance(stmt, ast.AnnAssign) and \
                            isinstance(stmt.target, ast.Name):
                        fields.add(stmt.target.id)
                    elif isinstance(stmt, ast.Assign):
                        for t in stmt.targets:
                            if isinstance(t, ast.Name):
                                fields.add(t.id)
        if not fields:
            return
        decl_line = next(
            (n.lineno for n in ast.walk(cfg.tree)
             if isinstance(n, ast.Assign)
             and any(isinstance(t, ast.Name)
                     and t.id == "SERVE_JOB_KEYS"
                     for t in n.targets)), 1)
        for key in sorted(keys - fields):
            findings.append(ctx.finding(
                self, cfg, decl_line,
                f"SERVE_JOB_KEYS entry {key!r} is not a G2VecConfig "
                f"field — jobs setting it are accepted-then-ignored"))

    def _check_payload_keys(self, ctx: AnalysisContext,
                            findings: List[Finding]) -> None:
        proto = ctx.file(PROTOCOL_FILE)
        if proto is None or proto.tree is None:
            return
        whitelists = {}
        for var, tuple_name in _ENVELOPES.items():
            wl = _tuple_of_str(proto.tree, tuple_name)
            if wl is not None:
                whitelists[var] = (tuple_name, wl)
        if not whitelists:
            return
        for rel in _PAYLOAD_FILES:
            sf = ctx.file(rel)
            if sf is None or sf.tree is None:
                continue
            for node in ast.walk(sf.tree):
                var = key = line = None
                if isinstance(node, ast.Subscript) and \
                        isinstance(node.value, ast.Name) and \
                        node.value.id in whitelists and \
                        isinstance(node.slice, ast.Constant) and \
                        isinstance(node.slice.value, str):
                    var, key, line = (node.value.id, node.slice.value,
                                      node.lineno)
                elif isinstance(node, ast.Call) and \
                        isinstance(node.func, ast.Attribute) and \
                        node.func.attr == "get" and \
                        isinstance(node.func.value, ast.Name) and \
                        node.func.value.id in whitelists and \
                        node.args and \
                        isinstance(node.args[0], ast.Constant) and \
                        isinstance(node.args[0].value, str):
                    var, key, line = (node.func.value.id,
                                      node.args[0].value, node.lineno)
                if key is not None:
                    tuple_name, wl = whitelists[var]
                    if key not in wl:
                        findings.append(ctx.finding(
                            self, sf, line,
                            f"{var} key {key!r} is read here but not "
                            f"whitelisted in protocol.{tuple_name} — "
                            f"typo or undocumented protocol extension"))
