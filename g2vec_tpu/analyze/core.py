"""Analyzer plumbing: files, findings, waivers, baseline, runner.

Design rules every checker obeys:

- **Pure AST + text.** Checkers never import the module under analysis
  (importing daemon.py would spin up jax paths and make the linter as
  slow as the code it guards). Everything is ``ast.parse`` plus line
  regexes for the comment grammar ``ast`` drops.
- **Line-stable fingerprints.** A baseline entry must survive an
  unrelated edit shifting the file, so a finding's identity is
  (checker, file, enclosing ``Class.function`` context, message) — the
  line number is display-only and excluded from the hash.
- **Shrink-only baseline.** Baseline entries that no longer match any
  current finding are reported as *stale* and fail the run: the file
  may only shrink. Growing it requires a deliberate commit that CI (the
  repo-wide test in tests/test_analyze.py) refuses.
- **Inline waivers beat baseline entries.** A deliberate exception
  belongs next to the code as ``# analyze: allow[<id>] <reason>`` (the
  reason is mandatory — a bare allow does not suppress anything); the
  baseline is only for pre-existing findings awaiting a fix.
"""
from __future__ import annotations

import ast
import dataclasses
import hashlib
import json
import os
import re
from typing import Dict, Iterable, List, Optional, Set, Tuple

#: ``# analyze: allow[lock-discipline] boot-time, single-threaded``
#: The reason group is mandatory: a waiver that does not say why is not
#: a waiver, and the original finding fires (loudly) instead.
_WAIVER_RE = re.compile(
    r"#\s*analyze:\s*allow\[([A-Za-z0-9_-]+)\]\s+(\S.*)")

_SKIP_DIRS = {".git", "__pycache__", ".pytest_cache", "build", "dist",
              ".eggs", "node_modules"}


@dataclasses.dataclass
class Finding:
    """One checker verdict, anchored to ``path:line`` for humans and to
    a line-free fingerprint for the baseline."""

    checker: str
    severity: str                 # "error" | "warning"
    path: str                     # repo-relative, forward slashes
    line: int
    message: str
    context: str = "<module>"     # enclosing Class.function, line-stable

    def fingerprint(self) -> str:
        raw = f"{self.checker}|{self.path}|{self.context}|{self.message}"
        return hashlib.sha1(raw.encode()).hexdigest()[:16]

    def location(self) -> str:
        return f"{self.path}:{self.line}"

    def to_dict(self) -> dict:
        return {"checker": self.checker, "severity": self.severity,
                "path": self.path, "line": self.line,
                "context": self.context, "message": self.message,
                "fingerprint": self.fingerprint()}


class SourceFile:
    """One parsed file: text, lines, AST (lazy), waivers, and a
    line → enclosing-scope map for stable finding contexts."""

    def __init__(self, abspath: str, relpath: str):
        self.abspath = abspath
        self.relpath = relpath
        with open(abspath, encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self._tree: Optional[ast.Module] = None
        self._parse_error: Optional[str] = None
        self._scopes: Optional[List[Tuple[int, int, str]]] = None
        #: line number -> checker ids waived on that line
        self.waivers: Dict[int, Set[str]] = {}
        for i, line in enumerate(self.lines, 1):
            m = _WAIVER_RE.search(line)
            if m:
                self.waivers.setdefault(i, set()).add(m.group(1))

    @property
    def tree(self) -> Optional[ast.Module]:
        if self._tree is None and self._parse_error is None:
            try:
                self._tree = ast.parse(self.text, filename=self.relpath)
            except SyntaxError as e:
                self._parse_error = f"syntax error: {e}"
        return self._tree

    def scope_at(self, line: int) -> str:
        """``Class.function`` (or ``<module>``) enclosing ``line`` —
        the innermost def/class whose span covers it."""
        if self._scopes is None:
            self._scopes = []
            tree = self.tree
            if tree is not None:
                def visit(node, prefix):
                    for child in ast.iter_child_nodes(node):
                        if isinstance(child, (ast.FunctionDef,
                                              ast.AsyncFunctionDef,
                                              ast.ClassDef)):
                            name = (f"{prefix}.{child.name}" if prefix
                                    else child.name)
                            self._scopes.append(
                                (child.lineno,
                                 child.end_lineno or child.lineno, name))
                            visit(child, name)
                visit(tree, "")
        best = "<module>"
        best_span = None
        for lo, hi, name in self._scopes:
            if lo <= line <= hi and (best_span is None
                                     or hi - lo < best_span):
                best, best_span = name, hi - lo
        return best

    def is_waived(self, line: int, checker: str) -> bool:
        """A waiver suppresses findings on its own line or anywhere in
        the contiguous comment block directly above the statement (a
        waiver's reason often needs a second comment line)."""
        if checker in self.waivers.get(line, ()):
            return True
        cand = line - 1
        while 1 <= cand <= len(self.lines) and \
                self.lines[cand - 1].lstrip().startswith("#"):
            if checker in self.waivers.get(cand, ()):
                return True
            cand -= 1
        return False


class AnalysisContext:
    """The scanned file set plus shared lookups, built once per run."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self._files: Dict[str, SourceFile] = {}
        self._all: Optional[List[SourceFile]] = None

    def _rel(self, abspath: str) -> str:
        return os.path.relpath(abspath, self.root).replace(os.sep, "/")

    def file(self, relpath: str) -> Optional[SourceFile]:
        """One file by repo-relative path; None if absent (checkers
        skip targets a fixture tree does not provide)."""
        relpath = relpath.replace(os.sep, "/")
        if relpath not in self._files:
            abspath = os.path.join(self.root, relpath)
            if not os.path.isfile(abspath):
                return None
            self._files[relpath] = SourceFile(abspath, relpath)
        return self._files.get(relpath)

    def files(self, under: Optional[str] = None) -> List[SourceFile]:
        """Every ``.py`` file under the root (or one subtree)."""
        if self._all is None:
            found = []
            for dirpath, dirnames, filenames in os.walk(self.root):
                dirnames[:] = sorted(d for d in dirnames
                                     if d not in _SKIP_DIRS)
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = self._rel(os.path.join(dirpath, fn))
                        sf = self.file(rel)
                        if sf is not None:
                            found.append(sf)
            self._all = found
        if under is None:
            return list(self._all)
        under = under.rstrip("/") + "/"
        return [sf for sf in self._all if sf.relpath.startswith(under)]

    def finding(self, checker: "Checker", sf: SourceFile, line: int,
                message: str, severity: Optional[str] = None) -> Finding:
        return Finding(checker=checker.id,
                       severity=severity or checker.severity,
                       path=sf.relpath, line=line, message=message,
                       context=sf.scope_at(line))


class Checker:
    """Plugin base: subclasses set ``id``/``description``/``severity``
    and implement :meth:`check` returning raw findings (the runner
    applies waivers and the baseline)."""

    id = "abstract"
    description = ""
    severity = "error"

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        raise NotImplementedError


def all_checkers() -> List[Checker]:
    # Imported here, not at module top: core must stay importable from
    # a checker module without a cycle.
    from g2vec_tpu.analyze.configdoc import ConfigDocChecker
    from g2vec_tpu.analyze.epochs import EpochStampChecker
    from g2vec_tpu.analyze.events import MetricsSchemaChecker
    from g2vec_tpu.analyze.locks import LockDisciplineChecker
    from g2vec_tpu.analyze.purity import JaxPurityChecker
    from g2vec_tpu.analyze.seams import FaultSeamChecker
    return [LockDisciplineChecker(), JaxPurityChecker(),
            FaultSeamChecker(), MetricsSchemaChecker(),
            ConfigDocChecker(), EpochStampChecker()]


def load_baseline(path: str) -> Dict[str, str]:
    """fingerprint -> human note. Missing file = empty baseline."""
    if not os.path.isfile(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    supp = data.get("suppressions", {})
    if not isinstance(supp, dict):
        raise ValueError(
            f"{path}: 'suppressions' must be an object mapping "
            f"fingerprint -> note")
    return dict(supp)


def save_baseline(path: str, findings: Iterable[Finding]) -> None:
    supp = {f.fingerprint(): f"{f.checker} {f.path} "
                             f"[{f.context}] {f.message}"
            for f in findings}
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"version": 1,
                   "note": "shrink-only: entries may be removed when "
                           "fixed, never added (fix or use an inline "
                           "'# analyze: allow[id] reason' waiver)",
                   "suppressions": dict(sorted(supp.items()))},
                  f, indent=2, sort_keys=False)
        f.write("\n")


@dataclasses.dataclass
class AnalysisReport:
    """Runner output: what fires, what was deliberately quiet, and
    which baseline entries went stale (shrink-only enforcement)."""

    findings: List[Finding]               # active (fail the run)
    waived: List[Finding]                 # inline-waiver suppressed
    baselined: List[Finding]              # baseline suppressed
    stale_baseline: List[str]             # fingerprints with no match
    checkers_run: List[str]

    @property
    def clean(self) -> bool:
        return not self.findings and not self.stale_baseline

    def to_dict(self) -> dict:
        return {"clean": self.clean,
                "checkers": self.checkers_run,
                "counts": {"active": len(self.findings),
                           "waived": len(self.waived),
                           "baselined": len(self.baselined),
                           "stale_baseline": len(self.stale_baseline)},
                "findings": [f.to_dict() for f in self.findings],
                "waived": [f.to_dict() for f in self.waived],
                "baselined": [f.to_dict() for f in self.baselined],
                "stale_baseline": sorted(self.stale_baseline)}


def run_analysis(root: str,
                 checker_ids: Optional[List[str]] = None,
                 baseline_path: Optional[str] = None) -> AnalysisReport:
    """Run the suite (or a subset) over ``root``. Raises KeyError for an
    unknown checker id — the CLI maps that to the usage exit code."""
    ctx = AnalysisContext(root)
    checkers = all_checkers()
    known = {c.id for c in checkers}
    if checker_ids:
        unknown = sorted(set(checker_ids) - known)
        if unknown:
            raise KeyError(
                f"unknown checker(s) {unknown}; known: {sorted(known)}")
        checkers = [c for c in checkers if c.id in set(checker_ids)]
    baseline = load_baseline(baseline_path) if baseline_path else {}

    active: List[Finding] = []
    waived: List[Finding] = []
    baselined: List[Finding] = []
    seen_fps: Set[str] = set()
    for checker in checkers:
        for f in checker.check(ctx):
            sf = ctx.file(f.path)
            if sf is not None and sf.is_waived(f.line, f.checker):
                waived.append(f)
                continue
            fp = f.fingerprint()
            seen_fps.add(fp)
            if fp in baseline:
                baselined.append(f)
            else:
                active.append(f)
    stale = [fp for fp in baseline if fp not in seen_fps]
    active.sort(key=lambda f: (f.path, f.line, f.checker))
    return AnalysisReport(findings=active, waived=waived,
                          baselined=baselined, stale_baseline=stale,
                          checkers_run=[c.id for c in checkers])
