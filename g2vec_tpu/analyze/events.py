"""metrics-schema: every MetricsWriter emission matches its declared
event schema.

The metrics JSONL is an API: the chaos-soak accountant sums
``job_done`` events, dashboards pivot on ``failover.latency_s``, and
tests assert field presence. Yet emission is stringly typed —
``metrics.emit("job_done", walltime=...)`` — so renaming a field at one
of a kind's five emit sites silently forks the stream's shape.

``utils/metrics_schema.py`` declares, per event kind, the fields every
emission must carry (``required``) and the fields any emission may
carry (``optional``). This checker lints every ``.emit("<literal>",
...)`` site:

- unknown event kind (not declared at all);
- unknown field (neither required nor optional for that kind);
- missing required field — skipped when the call splats ``**fields``
  (the checker cannot see inside a splat; unknown-field checking still
  applies to the literal kwargs).

Sites whose kind is not a string literal are skipped: the two generic
relay shims (e.g. re-emitting a child's event) are schema-checked at
the original emit site instead. Fields injected by the BoundMetrics
facade (``job``, ``lane``, ...) are declared optional, never required.
The schema file is read by AST — the checker never imports repo code.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from g2vec_tpu.analyze.core import AnalysisContext, Checker, Finding

SCHEMA_FILE = "g2vec_tpu/utils/metrics_schema.py"
#: Dirs scanned for emit sites (tests emit ad-hoc kinds on purpose).
_SCAN = ("g2vec_tpu", "tools")


class MetricsSchemaChecker(Checker):
    id = "metrics-schema"
    description = ("MetricsWriter emissions vs the declared per-kind "
                   "event schemas (utils/metrics_schema.py)")
    severity = "error"

    def _schemas(self, ctx: AnalysisContext) \
            -> Optional[Dict[str, Dict[str, Set[str]]]]:
        sf = ctx.file(SCHEMA_FILE)
        if sf is None or sf.tree is None:
            return None
        for node in ast.walk(sf.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Name) and \
                            t.id == "EVENT_SCHEMAS":
                        try:
                            raw = ast.literal_eval(node.value)
                        except ValueError:
                            return None
                        return {
                            kind: {"required": set(s.get("required",
                                                         ())),
                                   "optional": set(s.get("optional",
                                                         ()))}
                            for kind, s in raw.items()}
        return None

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        schemas = self._schemas(ctx)
        if schemas is None:
            return findings          # fixture tree without schemas
        for top in _SCAN:
            for sf in ctx.files(top):
                if sf.relpath == SCHEMA_FILE:
                    continue
                tree = sf.tree
                if tree is None:
                    continue
                for node in ast.walk(tree):
                    if not isinstance(node, ast.Call):
                        continue
                    fn = node.func
                    if not (isinstance(fn, ast.Attribute)
                            and fn.attr == "emit"):
                        continue
                    if not node.args:
                        continue
                    kind_node = node.args[0]
                    if not (isinstance(kind_node, ast.Constant)
                            and isinstance(kind_node.value, str)):
                        continue     # generic relay shim
                    kind = kind_node.value
                    schema = schemas.get(kind)
                    if schema is None:
                        findings.append(ctx.finding(
                            self, sf, node.lineno,
                            f"emit({kind!r}) is not a declared event "
                            f"kind — add it to EVENT_SCHEMAS in "
                            f"{SCHEMA_FILE}"))
                        continue
                    has_splat = any(kw.arg is None
                                    for kw in node.keywords)
                    present = {kw.arg for kw in node.keywords
                               if kw.arg is not None}
                    allowed = schema["required"] | schema["optional"]
                    for field in sorted(present - allowed):
                        findings.append(ctx.finding(
                            self, sf, node.lineno,
                            f"emit({kind!r}) passes undeclared field "
                            f"{field!r} — declare it in the "
                            f"{kind!r} schema or drop it"))
                    if not has_splat:
                        for field in sorted(schema["required"]
                                            - present):
                            findings.append(ctx.finding(
                                self, sf, node.lineno,
                                f"emit({kind!r}) is missing required "
                                f"field {field!r}"))
        return findings
