"""Fencing-epoch discipline: every mutating router→daemon command
literal carries ``router_epoch``.

The partition-tolerance story (serve/leader.py) only holds if a zombie
ex-leader cannot emit even ONE mutating command without its epoch
stamped on it — daemons reject stale epochs, but an epoch-LESS command
is accepted for single-router compatibility, so a forgotten stamp at a
new call site silently reopens the split-brain hole the lease closed.
That is a grep-able invariant, so this checker greps it (structurally):

- Every ``dict`` literal and every ``dict(...)`` call in
  serve/router.py whose ``op`` is one of the daemon's MUTATING ops
  (``submit`` / ``cancel`` / ``drain`` / ``shutdown``) must also carry
  a ``router_epoch`` key.
- The stamp must be the router's live view — ``self.router_epoch`` (or
  a local bound from it); a hard-coded integer other than 0 is flagged
  too, since a constant epoch can never be superseded.

Read-plane ops (status / ping / result / query) are exempt by design:
reads stay open during partitions — that IS degraded mode. The check
is literal-site-only on purpose (same philosophy as the metrics-schema
checker): a payload assembled dynamically goes through
``Router._request``, which refuses to invent an epoch, so the literal
sites are exactly where the invariant lives.
"""
from __future__ import annotations

import ast
from typing import List, Optional

from g2vec_tpu.analyze.core import (AnalysisContext, Checker, Finding,
                                    SourceFile)

#: Ops the daemon's connection handler epoch-gates (daemon.py keeps the
#: matching tuple in ``_handle_conn``); reads are deliberately absent.
MUTATING_OPS = ("submit", "cancel", "drain", "shutdown")

_ROUTER_FILE = "g2vec_tpu/serve/router.py"


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _literal_payload_keys(node: ast.AST):
    """(op, keys) for a dict literal or ``dict(...)`` call building a
    request payload; (None, None) for anything else. ``dict(base,
    op="submit", ...)`` counts the kwargs only — the positional base is
    an already-stamped (or client-sanitized) payload and the kwargs are
    what THIS site adds."""
    if isinstance(node, ast.Dict):
        keys = [_const_str(k) for k in node.keys]
        if None in keys:        # **splat or computed key: not a literal
            return None, None
        op = None
        for k, v in zip(keys, node.values):
            if k == "op":
                op = _const_str(v)
        return op, set(keys)
    if isinstance(node, ast.Call) and isinstance(node.func, ast.Name) \
            and node.func.id == "dict":
        op = None
        keys = set()
        for kw in node.keywords:
            if kw.arg is None:
                return None, None
            keys.add(kw.arg)
            if kw.arg == "op":
                op = _const_str(kw.value)
        return op, keys
    return None, None


def _epoch_value(node: ast.AST) -> Optional[ast.AST]:
    """The expression bound to ``router_epoch`` in a payload literal."""
    if isinstance(node, ast.Dict):
        for k, v in zip(node.keys, node.values):
            if _const_str(k) == "router_epoch":
                return v
    elif isinstance(node, ast.Call):
        for kw in node.keywords:
            if kw.arg == "router_epoch":
                return kw.value
    return None


class EpochStampChecker(Checker):
    id = "epoch-stamp"
    description = ("every mutating router->daemon payload literal "
                   "carries router_epoch")
    severity = "error"

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        out: List[Finding] = []
        sf = ctx.file(_ROUTER_FILE)
        if sf is None or sf.tree is None:
            return out
        self._scan(ctx, sf, out)
        return out

    def _scan(self, ctx: AnalysisContext, sf: SourceFile,
              out: List[Finding]) -> None:
        for node in ast.walk(sf.tree):
            op, keys = _literal_payload_keys(node)
            if op is None or op not in MUTATING_OPS:
                continue
            if "router_epoch" not in keys:
                out.append(ctx.finding(
                    self, sf, node.lineno,
                    f"mutating payload literal (op={op!r}) without a "
                    f"router_epoch stamp — a zombie ex-leader could "
                    f"emit it unfenced; stamp self.router_epoch (0 "
                    f"strips to byte-identical HA-off wire form)"))
                continue
            val = _epoch_value(node)
            if isinstance(val, ast.Constant) and val.value != 0:
                out.append(ctx.finding(
                    self, sf, node.lineno,
                    f"op={op!r} stamps a constant router_epoch "
                    f"{val.value!r} — a fixed epoch can never be "
                    f"superseded; use self.router_epoch"))
