"""g2vec check — the project-invariant static-analysis suite.

The repo states a dozen load-bearing invariants only in prose: the
exactly-once argument leans on ``_idem_lock`` discipline, the router is
"jax-free" by contract, the fault-seam vocabulary is a registry humans
kept in sync by grep. PR 11's review proved prose rots — an unlocked
check-then-insert shipped in ``admit()`` and had to be caught by eye.
This package turns those invariants into AST checkers that run in
tier-1 (``python -m g2vec_tpu analyze``):

- ``lock-discipline`` (locks.py): ``# guarded-by:`` annotations on
  attributes of threaded classes; mutations outside the named lock,
  check-then-act across a lock release, lock-order cycles.
- ``jax-purity`` (purity.py): declared jax-free modules never reach
  jax/jaxlib through the module-level import graph; no host bounces
  (np.asarray / .item() / time.* / Python RNG) inside functions handed
  to jit/vmap/while_loop; donated-buffer use-after-donate.
- ``fault-seams`` (seams.py): every ``fault_point`` literal is a
  declared seam, every declared seam has a call site and a test.
- ``metrics-schema`` (events.py): every ``MetricsWriter`` emission
  matches the declared event schema (utils/metrics_schema.py).
- ``config-doc-drift`` (configdoc.py): CLI flags vs the README table,
  SERVE_JOB_KEYS vs real config fields, serve payload keys vs the
  protocol whitelist.

Checkers are pure AST + text — they never import the code under
analysis, so the suite runs in milliseconds on CPU with no jax init.
Known findings live in the committed ANALYZE_BASELINE.json (shrink-only:
new entries fail CI); deliberate exceptions carry an inline
``# analyze: allow[<checker-id>] <reason>`` waiver.
"""
from g2vec_tpu.analyze.core import (AnalysisContext, Checker, Finding,
                                    load_baseline, run_analysis)

__all__ = ["AnalysisContext", "Checker", "Finding", "load_baseline",
           "run_analysis"]
