"""lock-discipline: ``# guarded-by:`` race lint + lock-order cycles.

Annotation grammar (ARCHITECTURE.md §16):

- ``self._idem: Dict[str, str] = {}   # guarded-by: _idem_lock`` — the
  attribute may only be MUTATED while ``with self._idem_lock:`` is
  held. The comment may also sit on its own line directly above the
  assignment. ``_rep_locks`` (a dict of locks) counts as held when any
  ``with self._rep_locks[...]:`` is open.
- ``# guarded-by: Router._rep_locks`` — a dotted lock name declares an
  EXTERNAL serializer (another class's lock). Recorded as documentation
  only: the checker cannot see the foreign holder, so these attributes
  are exempt from enforcement (the annotation still pins the contract
  in a greppable form).
- ``# analyze: holds[_lock]`` on (or directly above) a ``def`` declares
  a caller-holds contract: the method body is analyzed as if the lock
  were already held, and every same-class call site that does NOT hold
  it is flagged.

What fires:

- mutation of a guarded attribute outside its lock (assignment,
  augmented assignment, ``del``, subscript store, or a mutating method
  call such as ``.append``/``.pop``/``[k] =``) — ``__init__`` is exempt
  (construction happens-before any thread exists);
- check-then-act: a guarded attribute read under its lock in one
  ``with`` block and mutated under a RE-ACQUIRED lock later in the same
  function (the PR 11 ``_idem`` bug class — lookup and reservation must
  be one critical section);
- a ``holds[...]`` method called without the promised lock;
- a cycle in the per-class lock-acquisition graph (nested ``with``
  scopes plus one level of ``self._method()`` propagation).

Conditions wrapping locks are understood: after
``self._not_empty = threading.Condition(self._lock)``, holding
``_not_empty`` IS holding ``_lock``.

Known limits (documented, deliberate): mutations through a local alias
(``tier = self._tiers[p]; tier.append(...)``) are invisible, as are
acquisitions through helpers more than one call deep. The checker is a
tripwire for the bug classes this repo has actually shipped, not a
proof system.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from g2vec_tpu.analyze.core import (AnalysisContext, Checker, Finding,
                                    SourceFile)

_GUARD_RE = re.compile(r"#+:?\s*guarded-by:\s*"
                       r"([A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*)")
_HOLDS_RE = re.compile(r"#\s*analyze:\s*holds\[([A-Za-z_][A-Za-z0-9_]*)\]")
_ATTR_DEF_RE = re.compile(r"\bself\.([A-Za-z_][A-Za-z0-9_]*)\s*"
                          r"(?::[^=]+)?=(?!=)")

#: Method calls that mutate their receiver in place.
_MUTATORS = {"append", "appendleft", "extend", "insert", "add", "update",
             "setdefault", "pop", "popitem", "popleft", "remove",
             "discard", "clear", "sort", "reverse", "move_to_end"}


def _self_attr(node: ast.AST) -> Optional[str]:
    """``self.X`` / ``self.X[...]`` / ``self.X[...][...]`` -> ``X``."""
    while isinstance(node, ast.Subscript):
        node = node.value
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _ClassInfo:
    def __init__(self, node: ast.ClassDef, sf: SourceFile):
        self.node = node
        self.sf = sf
        self.name = node.name
        #: attr -> lock name (local, enforceable)
        self.guards: Dict[str, str] = {}
        #: attr -> dotted external lock (documentation only)
        self.external: Dict[str, str] = {}
        #: condition/lock aliasing: held(_not_empty) => held(_lock)
        self.aliases: Dict[str, str] = {}
        #: method name -> locks its body acquires anywhere
        self.acquires: Dict[str, Set[str]] = {}
        #: method name -> caller-holds contract locks
        self.holds: Dict[str, Set[str]] = {}
        #: lock-order edges (outer, inner) -> first witness line
        self.edges: Dict[Tuple[str, str], int] = {}
        #: deferred same-class calls: (caller, callee, held, line)
        self.calls: List[Tuple[str, str, frozenset, int]] = []

    def canon(self, lock: str) -> str:
        seen = set()
        while lock in self.aliases and lock not in seen:
            seen.add(lock)
            lock = self.aliases[lock]
        return lock


class LockDisciplineChecker(Checker):
    id = "lock-discipline"
    description = ("guarded-by annotations: mutations outside the lock, "
                   "check-then-act across a release, holds[] contracts, "
                   "lock-order cycles")
    severity = "error"

    def check(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        for sf in ctx.files():
            if "guarded-by:" not in sf.text and \
                    "analyze: holds[" not in sf.text:
                continue
            tree = sf.tree
            if tree is None:
                continue
            for node in ast.walk(tree):
                if isinstance(node, ast.ClassDef):
                    info = self._collect(node, sf)
                    if info.guards or info.holds:
                        self._check_class(ctx, info, findings)
        return findings

    # ---- annotation + structure collection --------------------------------

    def _collect(self, node: ast.ClassDef, sf: SourceFile) -> _ClassInfo:
        info = _ClassInfo(node, sf)
        lo, hi = node.lineno, node.end_lineno or node.lineno
        for i in range(lo, hi + 1):
            line = sf.lines[i - 1]
            m = _GUARD_RE.search(line)
            if m:
                lock = m.group(1)
                attr = self._annotated_attr(sf, i, hi)
                if attr is not None:
                    if "." in lock:
                        info.external[attr] = lock
                    else:
                        info.guards[attr] = lock
        # Condition-wraps-lock aliasing, from any method body.
        for sub in ast.walk(node):
            if isinstance(sub, ast.Assign) and \
                    isinstance(sub.value, ast.Call):
                fn = sub.value.func
                if isinstance(fn, ast.Attribute) and \
                        fn.attr == "Condition" and sub.value.args:
                    wrapped = _self_attr(sub.value.args[0])
                    for t in sub.targets:
                        cond = _self_attr(t)
                        if cond and wrapped:
                            info.aliases[cond] = wrapped
        # holds[...] contracts: on the def line or in the contiguous
        # comment block above it (above decorators too).
        for meth in self._methods(node):
            first = min([meth.lineno]
                        + [d.lineno for d in meth.decorator_list])
            cand = [meth.lineno]
            i = first - 1
            while i >= 1 and sf.lines[i - 1].lstrip().startswith("#"):
                cand.append(i)
                i -= 1
            for i in cand:
                for m in _HOLDS_RE.finditer(sf.lines[i - 1]):
                    info.holds.setdefault(meth.name,
                                          set()).add(m.group(1))
        return info

    def _annotated_attr(self, sf: SourceFile, line: int,
                        class_end: int) -> Optional[str]:
        """The ``self.X`` an annotation at ``line`` talks about: on the
        same line, or the next assignment below a standalone comment."""
        m = _ATTR_DEF_RE.search(sf.lines[line - 1])
        if m:
            return m.group(1)
        if sf.lines[line - 1].lstrip().startswith("#"):
            for j in range(line + 1, min(line + 4, class_end + 1)):
                text = sf.lines[j - 1]
                if text.lstrip().startswith("#"):
                    continue
                m = _ATTR_DEF_RE.search(text)
                return m.group(1) if m else None
        return None

    def _methods(self, node: ast.ClassDef) -> List[ast.FunctionDef]:
        out = []
        for sub in ast.walk(node):
            if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef)):
                out.append(sub)
        return out

    # ---- per-class analysis -----------------------------------------------

    def _check_class(self, ctx: AnalysisContext, info: _ClassInfo,
                     findings: List[Finding]) -> None:
        sf = info.sf
        # Pass 1: per-method lock sets + direct findings.
        for meth in self._methods(info.node):
            acquired: Set[str] = set()
            events: List[Tuple[str, str, int, int]] = []
            held0 = frozenset(info.canon(l)
                              for l in info.holds.get(meth.name, ()))
            self._walk(info, meth, list(meth.body), held0, acquired,
                       events, findings, ctx)
            info.acquires[meth.name] = acquired
            if meth.name != "__init__":
                self._check_then_act(ctx, info, meth, events, findings)
        # Pass 2: one-level interprocedural lock edges + holds[] audit.
        for caller, callee, held, line in info.calls:
            for inner in info.acquires.get(callee, ()):
                for outer in held:
                    if outer != inner:
                        info.edges.setdefault((outer, inner), line)
            missing = sorted(info.holds.get(callee, set()) - set(held))
            if missing and caller != "__init__":
                findings.append(ctx.finding(
                    self, sf, line,
                    f"{info.name}.{callee} requires holds"
                    f"[{', '.join(missing)}] but {info.name}.{caller} "
                    f"calls it without holding the lock"))
        self._check_cycles(ctx, info, findings)

    def _walk(self, info: _ClassInfo, meth: ast.FunctionDef,
              stmts: List[ast.stmt], held: frozenset,
              acquired: Set[str], events: List[Tuple[str, str, int, int]],
              findings: List[Finding], ctx: AnalysisContext,
              with_id: int = 0) -> None:
        """Statement walk tracking the held-lock set. ``events`` records
        (attr, kind, with_id, line) touches on guarded attrs for the
        check-then-act pass; ``with_id`` is the id() of the innermost
        guarding With node (0 = no lock held)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # A nested def runs later, on whoever calls it — its
                # body starts with no locks held.
                inner_acq: Set[str] = set()
                inner_ev: List[Tuple[str, str, int, int]] = []
                self._walk(info, stmt, list(stmt.body), frozenset(),
                           inner_acq, inner_ev, findings, ctx)
                acquired |= inner_acq
                self._check_then_act(ctx, info, stmt, inner_ev, findings)
                continue
            if isinstance(stmt, ast.With):
                locks = []
                for item in stmt.items:
                    lock = _self_attr(item.context_expr)
                    if lock is not None and self._is_lock(info, lock):
                        lock = info.canon(lock)
                        locks.append(lock)
                        acquired.add(lock)
                        for outer in held:
                            if outer != lock:
                                info.edges.setdefault((outer, lock),
                                                      stmt.lineno)
                new_held = held | frozenset(locks)
                self._scan_exprs(info, meth, stmt, held, events,
                                 findings, ctx, with_id,
                                 items_only=True)
                self._walk(info, meth, list(stmt.body), new_held,
                           acquired, events, findings, ctx,
                           id(stmt) if locks else with_id)
                continue
            # Compound statements: recurse into bodies with same held set.
            self._scan_exprs(info, meth, stmt, held, events, findings,
                             ctx, with_id)
            for field in ("body", "orelse", "finalbody"):
                sub = getattr(stmt, field, None)
                if sub:
                    self._walk(info, meth, list(sub), held, acquired,
                               events, findings, ctx, with_id)
            for handler in getattr(stmt, "handlers", []):
                self._walk(info, meth, list(handler.body), held,
                           acquired, events, findings, ctx, with_id)

    def _is_lock(self, info: _ClassInfo, attr: str) -> bool:
        if attr in info.aliases or attr in set(info.guards.values()):
            return True
        return bool(re.search(r"lock|_cv$|cond|not_empty|_mu$", attr))

    def _scan_exprs(self, info: _ClassInfo, meth: ast.FunctionDef,
                    stmt: ast.stmt, held: frozenset,
                    events: List[Tuple[str, str, int, int]],
                    findings: List[Finding], ctx: AnalysisContext,
                    with_id: int, items_only: bool = False) -> None:
        """Findings + events for one statement's own expressions (child
        bodies are walked separately so the held set stays accurate)."""
        nodes: List[ast.AST] = []
        if items_only:
            return
        if isinstance(stmt, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (stmt.targets if isinstance(stmt, ast.Assign)
                       else [stmt.target])
            for t in targets:
                attr = _self_attr(t)
                if attr:
                    self._touch(info, meth, attr, "mutate", held,
                                with_id, stmt.lineno, events, findings,
                                ctx)
            if stmt.value is not None:
                nodes.append(stmt.value)
            if isinstance(stmt, ast.AugAssign):
                # ``self.x += 1`` also reads self.x.
                attr = _self_attr(stmt.target)
                if attr:
                    self._touch(info, meth, attr, "read", held, with_id,
                                stmt.lineno, events, findings, ctx)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                attr = _self_attr(t)
                if attr:
                    self._touch(info, meth, attr, "mutate", held,
                                with_id, stmt.lineno, events, findings,
                                ctx)
        elif isinstance(stmt, (ast.If, ast.While)):
            nodes.append(stmt.test)
        elif isinstance(stmt, ast.For):
            nodes.extend([stmt.iter, stmt.target])
        elif isinstance(stmt, ast.Return) and stmt.value is not None:
            nodes.append(stmt.value)
        elif isinstance(stmt, ast.Expr):
            nodes.append(stmt.value)
        elif isinstance(stmt, (ast.Assert, ast.Raise)):
            nodes.extend([n for n in (getattr(stmt, "test", None),
                                      getattr(stmt, "exc", None)) if n])
        # A mutator call's receiver (``self._running`` in
        # ``self._running.update(...)``) is not a check-making READ —
        # counting it would turn every two-critical-section function
        # into a check-then-act false positive. Collect receivers first.
        receiver_ids = set()
        for node in nodes:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call) and \
                        isinstance(sub.func, ast.Attribute) and \
                        sub.func.attr in _MUTATORS and \
                        _self_attr(sub.func.value) is not None:
                    receiver_ids.update(id(n) for n
                                        in ast.walk(sub.func.value))
        for node in nodes:
            for sub in ast.walk(node):
                if isinstance(sub, ast.Call):
                    fn = sub.func
                    if isinstance(fn, ast.Attribute):
                        base = _self_attr(fn.value)
                        if base is not None and fn.attr in _MUTATORS:
                            self._touch(info, meth, base, "mutate",
                                        held, with_id, sub.lineno,
                                        events, findings, ctx)
                        elif base is None and \
                                isinstance(fn.value, ast.Name) and \
                                fn.value.id == "self":
                            info.calls.append(
                                (meth.name, fn.attr, held, sub.lineno))
                elif isinstance(sub, ast.Attribute) and \
                        isinstance(sub.ctx, ast.Load) and \
                        id(sub) not in receiver_ids:
                    attr = _self_attr(sub)
                    if attr:
                        self._touch(info, meth, attr, "read", held,
                                    with_id, sub.lineno, events,
                                    findings, ctx)

    def _touch(self, info: _ClassInfo, meth: ast.FunctionDef, attr: str,
               kind: str, held: frozenset, with_id: int, line: int,
               events: List[Tuple[str, str, int, int]],
               findings: List[Finding], ctx: AnalysisContext) -> None:
        lock = info.guards.get(attr)
        if lock is None:
            return
        lock = info.canon(lock)
        if kind == "mutate" and meth.name != "__init__" \
                and lock not in held:
            findings.append(ctx.finding(
                self, info.sf, line,
                f"{info.name}.{attr} is guarded-by {lock} but "
                f"{meth.name} mutates it without holding the lock"))
        if lock in held:
            events.append((attr, kind, with_id, line))

    def _check_then_act(self, ctx: AnalysisContext, info: _ClassInfo,
                        meth: ast.FunctionDef,
                        events: List[Tuple[str, str, int, int]],
                        findings: List[Finding]) -> None:
        """A read of a guarded attr in one critical section and a
        mutation of it in a LATER, separately-acquired one: the decision
        made under the first lock is stale by the second (TOCTOU)."""
        span: Dict[int, Tuple[int, int]] = {}
        for _, _, wid, line in events:
            lo, hi = span.get(wid, (line, line))
            span[wid] = (min(lo, line), max(hi, line))
        mutates = [(a, w, l) for a, k, w, l in events
                   if k == "mutate" and w]
        # A critical section that reads AND mutates the attr committed
        # its decision atomically (classic check-AND-act, e.g. "if x in
        # s: return; s.add(x)") — later sections mutating the same attr
        # (cleanup in finally, etc.) are not TOCTOU against it.
        committed = {(a, w) for a, w, _ in mutates}
        reads = [(a, w, l) for a, k, w, l in events
                 if k == "read" and w and (a, w) not in committed]
        flagged = set()
        for attr, w_r, _ in reads:
            for attr_m, w_m, line_m in mutates:
                if attr_m != attr or w_m == w_r:
                    continue
                if span[w_m][0] > span[w_r][1] and \
                        (attr, line_m) not in flagged:
                    flagged.add((attr, line_m))
                    findings.append(ctx.finding(
                        self, info.sf, line_m,
                        f"check-then-act on {info.name}.{attr}: read "
                        f"under {info.canon(info.guards[attr])} in one "
                        f"critical section, mutated in a later one — "
                        f"the decision is stale once the lock is "
                        f"dropped (merge into one with-block)",
                        severity="error"))

    def _check_cycles(self, ctx: AnalysisContext, info: _ClassInfo,
                      findings: List[Finding]) -> None:
        graph: Dict[str, Set[str]] = {}
        for (a, b) in info.edges:
            graph.setdefault(a, set()).add(b)
        state: Dict[str, int] = {}
        stack: List[str] = []

        def dfs(node: str) -> Optional[List[str]]:
            state[node] = 1
            stack.append(node)
            for nxt in sorted(graph.get(node, ())):
                if state.get(nxt, 0) == 1:
                    return stack[stack.index(nxt):] + [nxt]
                if state.get(nxt, 0) == 0:
                    cyc = dfs(nxt)
                    if cyc:
                        return cyc
            stack.pop()
            state[node] = 2
            return None

        for node in sorted(graph):
            if state.get(node, 0) == 0:
                cyc = dfs(node)
                if cyc:
                    line = min(info.edges.get((a, b), 1)
                               for a, b in zip(cyc, cyc[1:]))
                    findings.append(ctx.finding(
                        self, info.sf, line,
                        f"lock-order cycle in {info.name}: "
                        f"{' -> '.join(cyc)} — two threads taking these "
                        f"in opposite orders deadlock"))
                    return
