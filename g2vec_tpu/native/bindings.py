"""ctypes bindings for the native TSV parser, with on-demand compilation.

``read_expression(path)`` returns (samples, genes, expr[s, g] float32) or
None when the native library cannot be built/loaded — callers
(g2vec_tpu.io.readers.load_expression) fall back to the Python parser.

Build contract shared with the walker bindings (_build.py): compiled once
per checkout (``g++ -O3 -shared -fPIC``) and cached as ``_tsv_reader.so``
beside the sources — or in ``$XDG_CACHE_HOME/g2vec_tpu/`` when the package
directory is read-only; a stale .so (older than the .cpp) is rebuilt.
"""
from __future__ import annotations

import ctypes
import os
from typing import Tuple

import numpy as np

from g2vec_tpu.native._build import build_and_load

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "tsv_reader.cpp")
_SO = os.path.join(_HERE, "_tsv_reader.so")


def _configure(lib: ctypes.CDLL) -> None:
    lib.g2v_expr_read.restype = ctypes.c_void_p
    lib.g2v_expr_read.argtypes = [ctypes.c_char_p, ctypes.c_char_p,
                                  ctypes.c_int]
    lib.g2v_expr_nsamples.restype = ctypes.c_int
    lib.g2v_expr_nsamples.argtypes = [ctypes.c_void_p]
    lib.g2v_expr_ngenes.restype = ctypes.c_int
    lib.g2v_expr_ngenes.argtypes = [ctypes.c_void_p]
    lib.g2v_expr_sample.restype = ctypes.c_char_p
    lib.g2v_expr_sample.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.g2v_expr_gene.restype = ctypes.c_char_p
    lib.g2v_expr_gene.argtypes = [ctypes.c_void_p, ctypes.c_int]
    lib.g2v_expr_copy.restype = None
    lib.g2v_expr_copy.argtypes = [ctypes.c_void_p,
                                  ctypes.POINTER(ctypes.c_float)]
    lib.g2v_expr_free.restype = None
    lib.g2v_expr_free.argtypes = [ctypes.c_void_p]


def _load() -> ctypes.CDLL:
    # Fault seam: an injected crash here (InjectedFault IS a RuntimeError)
    # exercises the caller's fallback-to-Python-parser path, the same
    # degradation a segfault-poisoned .so would force.
    from g2vec_tpu.resilience.faults import fault_point

    fault_point("native_load")
    return build_and_load(_SRC, _SO, [], _configure)


def read_expression(path: str) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Parse an expression TSV natively.

    Returns (samples [S] str, genes [G] str, expr [S, G] float32). Raises
    ValueError on malformed input (matching the Python reader's behavior)
    and RuntimeError when the native library is unavailable (build/load
    failure) — callers fall back to the Python parser on the latter only.
    """
    lib = _load()
    err = ctypes.create_string_buffer(512)
    handle = lib.g2v_expr_read(path.encode(), err, len(err))
    if not handle:
        raise ValueError(err.value.decode() or f"{path}: native parse failed")
    try:
        n_s = lib.g2v_expr_nsamples(handle)
        n_g = lib.g2v_expr_ngenes(handle)
        samples = np.array([lib.g2v_expr_sample(handle, i).decode()
                            for i in range(n_s)])
        genes = np.array([lib.g2v_expr_gene(handle, j).decode()
                          for j in range(n_g)])
        expr = np.empty((n_s, n_g), dtype=np.float32)
        lib.g2v_expr_copy(handle, expr.ctypes.data_as(
            ctypes.POINTER(ctypes.c_float)))
        return samples, genes, expr
    finally:
        lib.g2v_expr_free(handle)
