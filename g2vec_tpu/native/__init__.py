"""Native (C++) runtime components, loaded via ctypes.

The reference has no native code of its own (SURVEY.md §2 "Native
components" — its speed came from NumPy/TF wheels). This package provides
the framework's native layer where host-side work is the bottleneck: a fast
expression-TSV parser (single pass, writes the transposed samples x genes
matrix directly). The build is one ``g++ -O3 -shared`` invocation, run
on demand and cached next to the sources; everything degrades gracefully to
the pure-Python readers when a toolchain is unavailable.
"""
