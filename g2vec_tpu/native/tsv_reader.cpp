// Fast expression-TSV parser (native side of g2vec_tpu.io.readers).
//
// File contract (same as the Python reader, ref: G2Vec.py:478-503): first
// row is "PATIENT\t<sample ids...>", each body row "gene\tfloat...", rows
// may end in \r\n or trailing whitespace, header column count defines the
// sample count. The matrix is gene-major in the file; this parser writes
// straight into a samples x genes float32 buffer (the transpose the Python
// reader does as a second pass, ref: G2Vec.py:498).
//
// C API (ctypes-friendly, no C++ types across the boundary):
//   g2v_expr_read(path, err, errlen) -> opaque handle or NULL (err filled)
//   g2v_expr_nsamples / g2v_expr_ngenes
//   g2v_expr_sample / g2v_expr_gene   (borrowed pointers, valid until free)
//   g2v_expr_copy(handle, out)        (out: samples*genes float32)
//   g2v_expr_free
#include <cerrno>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <memory>
#include <string>
#include <vector>

namespace {

struct Expr {
  std::vector<std::string> samples;
  std::vector<std::string> genes;
  std::vector<float> matrix;  // samples x genes (transposed from file)
};

void fail(char* err, int errlen, const std::string& msg) {
  if (err && errlen > 0) {
    std::snprintf(err, static_cast<size_t>(errlen), "%s", msg.c_str());
  }
}

// Split one line on tabs after stripping trailing whitespace.
void split_fields(const char* begin, const char* end,
                  std::vector<std::pair<const char*, const char*>>* out) {
  while (end > begin &&
         (end[-1] == ' ' || end[-1] == '\t' || end[-1] == '\r')) {
    --end;
  }
  out->clear();
  const char* field = begin;
  for (const char* p = begin; p <= end; ++p) {
    if (p == end || *p == '\t') {
      out->push_back({field, p});
      field = p + 1;
    }
  }
}

}  // namespace

extern "C" {

void* g2v_expr_read(const char* path, char* err, int errlen) try {
  FILE* f = std::fopen(path, "rb");
  if (!f) {
    fail(err, errlen, std::string(path) + ": " + std::strerror(errno));
    return nullptr;
  }
  std::fseek(f, 0, SEEK_END);
  long size = std::ftell(f);
  std::fseek(f, 0, SEEK_SET);
  if (size < 0) {  // unseekable (FIFO, ...) — refuse instead of overflowing
    std::fclose(f);
    fail(err, errlen, std::string(path) + ": not a regular seekable file");
    return nullptr;
  }
  std::string buf(static_cast<size_t>(size), '\0');
  if (size > 0 && std::fread(&buf[0], 1, static_cast<size_t>(size), f) !=
                      static_cast<size_t>(size)) {
    std::fclose(f);
    fail(err, errlen, std::string(path) + ": short read");
    return nullptr;
  }
  std::fclose(f);

  auto expr = std::make_unique<Expr>();
  std::vector<std::pair<const char*, const char*>> fields;
  const char* p = buf.data();
  const char* bufend = buf.data() + buf.size();
  long lineno = 0;
  // First pass: collect gene rows as (name, value-span) so we can size the
  // matrix once; value parsing happens in the second pass, writing
  // transposed.
  std::vector<std::pair<const char*, const char*>> gene_rows;
  while (p < bufend) {
    const char* nl = static_cast<const char*>(
        std::memchr(p, '\n', static_cast<size_t>(bufend - p)));
    const char* line_end = nl ? nl : bufend;
    ++lineno;
    if (lineno == 1) {
      split_fields(p, line_end, &fields);
      if (fields.size() < 2) {
        fail(err, errlen, std::string(path) +
                              ": expression header needs at least one sample");
        return nullptr;
      }
      for (size_t i = 1; i < fields.size(); ++i) {
        expr->samples.emplace_back(fields[i].first,
                                   fields[i].second - fields[i].first);
      }
    } else {
      // Blank-line test AFTER stripping trailing whitespace, so a CRLF
      // file's trailing "\r\n" line is skipped exactly like the Python
      // reader's rstrip() path.
      const char* stripped_end = line_end;
      while (stripped_end > p &&
             (stripped_end[-1] == ' ' || stripped_end[-1] == '\t' ||
              stripped_end[-1] == '\r')) {
        --stripped_end;
      }
      if (stripped_end > p) {
        gene_rows.push_back({p, line_end});
      }
    }
    p = nl ? nl + 1 : bufend;
  }
  size_t n_samples = expr->samples.size();
  size_t n_genes = gene_rows.size();
  if (n_genes == 0) {
    // Same wording contract as the Python reader: actionable, names the
    // file shape the caller must fix.
    fail(err, errlen, std::string(path) +
                          ": expression file needs a header and at least "
                          "one gene row");
    return nullptr;
  }
  expr->genes.reserve(n_genes);
  expr->matrix.resize(n_samples * n_genes);

  for (size_t j = 0; j < n_genes; ++j) {
    split_fields(gene_rows[j].first, gene_rows[j].second, &fields);
    if (fields.size() != n_samples + 1) {
      // Name the offending gene (Python-reader parity): a truncated row
      // in a million-line TSV is unfindable by row count alone.
      std::string gene(fields.empty() ? "" : fields[0].first,
                       fields.empty()
                           ? 0
                           : static_cast<size_t>(fields[0].second -
                                                 fields[0].first));
      fail(err, errlen,
           std::string(path) + ": gene row " + std::to_string(j + 2) +
               " ('" + gene + "') has " +
               std::to_string(fields.empty() ? 0 : fields.size() - 1) +
               " values, expected " + std::to_string(n_samples) +
               " (one per sample in the header)");
      return nullptr;
    }
    expr->genes.emplace_back(fields[0].first,
                             fields[0].second - fields[0].first);
    for (size_t i = 1; i <= n_samples; ++i) {
      // Parsing in place is safe: std::string guarantees buf is
      // NUL-terminated, and strtof stops at the field's '\t'/'\n'/'\r'
      // delimiter (none of which can appear inside a float).
      char* parse_end = nullptr;
      float v = std::strtof(fields[i].first, &parse_end);
      if (parse_end != fields[i].second) {  // empty, garbage, or trailing junk
        fail(err, errlen,
             std::string(path) + ": non-numeric value in gene row " +
                 std::to_string(j + 2));
        return nullptr;
      }
      expr->matrix[(i - 1) * n_genes + j] = v;  // transposed write
    }
  }
  return expr.release();
} catch (const std::exception& e) {
  // Never let a C++ exception cross the C ABI into ctypes (it aborts the
  // whole Python process). bad_alloc on oversized files lands here too.
  fail(err, errlen, std::string(path) + ": " + e.what());
  return nullptr;
} catch (...) {
  fail(err, errlen, std::string(path) + ": unknown native parser error");
  return nullptr;
}

int g2v_expr_nsamples(void* h) {
  return static_cast<int>(static_cast<Expr*>(h)->samples.size());
}

int g2v_expr_ngenes(void* h) {
  return static_cast<int>(static_cast<Expr*>(h)->genes.size());
}

const char* g2v_expr_sample(void* h, int i) {
  return static_cast<Expr*>(h)->samples[static_cast<size_t>(i)].c_str();
}

const char* g2v_expr_gene(void* h, int j) {
  return static_cast<Expr*>(h)->genes[static_cast<size_t>(j)].c_str();
}

void g2v_expr_copy(void* h, float* out) {
  Expr* e = static_cast<Expr*>(h);
  std::memcpy(out, e->matrix.data(), e->matrix.size() * sizeof(float));
}

void g2v_expr_free(void* h) { delete static_cast<Expr*>(h); }

}  // extern "C"
