"""Shared on-demand build/load scaffolding for the native components.

One contract for every .cpp in this package (tsv_reader, walker): compile
once per checkout with ``g++ -O3 -shared -fPIC`` next to the source,
rebuild when the source is newer than the .so, remember a build/load
failure so it raises exactly once per process (as RuntimeError — callers
treat that one type as "native unavailable" and fall back), and serialize
everything behind a per-target lock.

The .so is written to a temp name and os.replace()d in, so two processes
racing on a cold checkout can never dlopen a half-written library.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Callable, Dict, List, Optional


class _Target:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.lib: Optional[ctypes.CDLL] = None
        self.error: Optional[str] = None


_targets: Dict[str, _Target] = {}
_registry_lock = threading.Lock()


def build_and_load(src: str, so: str, extra_flags: List[str],
                   configure: Callable[[ctypes.CDLL], None]) -> ctypes.CDLL:
    """Load (building if stale/missing) ``so`` from ``src``.

    ``configure`` sets restype/argtypes on first load. Raises RuntimeError
    (memoized) when the toolchain is missing or the build/load fails.
    """
    with _registry_lock:
        target = _targets.setdefault(so, _Target())
    with target.lock:
        if target.lib is not None:
            return target.lib
        if target.error is not None:
            raise RuntimeError(target.error)
        try:
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                tmp = f"{so}.{os.getpid()}.tmp"
                cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
                       *extra_flags, "-o", tmp, src]
                try:
                    proc = subprocess.run(cmd, capture_output=True,
                                          text=True, timeout=120)
                    if proc.returncode != 0:
                        raise RuntimeError(
                            f"native build failed: {' '.join(cmd)}\n"
                            f"{proc.stderr}")
                    os.replace(tmp, so)
                finally:
                    # A failed/timed-out compile must not leave its partial
                    # output orphaned in the package directory.
                    if os.path.exists(tmp):
                        os.unlink(tmp)
            lib = ctypes.CDLL(so)
            configure(lib)
        except Exception as e:  # remember, so we don't rebuild per call
            target.error = str(e)
            # Normalize to RuntimeError so callers have ONE "unavailable"
            # exception type regardless of how the build died (missing
            # g++, compiler timeout, dlopen failure, ...).
            raise RuntimeError(target.error) from e
        target.lib = lib
        return lib
