"""Shared on-demand build/load scaffolding for the native components.

One contract for every .cpp in this package (tsv_reader, walker): compile
once per checkout with ``g++ -O3 -shared -fPIC`` next to the source,
rebuild when the source is newer than the .so, remember a build/load
failure so it raises exactly once per process (as RuntimeError — callers
treat that one type as "native unavailable" and fall back), and serialize
everything behind a per-target lock.

The .so is written to a temp name and os.replace()d in, so two processes
racing on a cold checkout can never dlopen a half-written library.

When the package directory is not writable (non-editable install into a
read-only site-packages), the build falls back to a per-user cache
(``$XDG_CACHE_HOME``/``~/.cache`` ``/g2vec_tpu/<source-hash>.so``) so the
native components stay available — the sources ship in the wheel
specifically for this on-demand build.
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import platform
import subprocess
import sys
import threading
from typing import Callable, Dict, List, Optional


def _cache_path(src: str, extra_flags: List[str]) -> str:
    """Per-user cache location for ``src``'s .so, keyed by source content
    AND build flags (the hash in the name doubles as the staleness check
    across versions — a flags-only release change must miss the cache)."""
    h = hashlib.sha256()
    with open(src, "rb") as f:
        h.update(f.read())
    h.update(b"\0".join(f.encode() for f in extra_flags))
    # Platform identity: a $HOME shared across heterogeneous hosts must not
    # serve host A's ELF to host B.
    h.update(f"{sys.platform}\0{platform.machine()}\0"
             f"{'-'.join(platform.libc_ver())}".encode())
    digest = h.hexdigest()[:16]
    root = os.environ.get("XDG_CACHE_HOME") or os.path.join(
        os.path.expanduser("~"), ".cache")
    cache_dir = os.path.join(root, "g2vec_tpu")
    os.makedirs(cache_dir, exist_ok=True)
    base = os.path.splitext(os.path.basename(src))[0]
    return os.path.join(cache_dir, f"{base}-{digest}.so")


def _compile(src: str, so: str, extra_flags: List[str]) -> None:
    """g++-compile ``src`` to ``so`` atomically (tmp + os.replace)."""
    tmp = f"{so}.{os.getpid()}.tmp"
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17",
           *extra_flags, "-o", tmp, src]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=120)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build failed: {' '.join(cmd)}\n{proc.stderr}")
        os.replace(tmp, so)
    finally:
        # A failed/timed-out compile must not leave its partial output
        # orphaned next to the target.
        if os.path.exists(tmp):
            os.unlink(tmp)


def _probe_writable(dirname: str) -> None:
    """Raise OSError unless ``dirname`` accepts an actual file create.

    Deliberately not os.access(): root-squash NFS (and plain root
    processes) report W_OK and then fail the real write — which would
    otherwise surface later as a g++ "cannot open output file"
    RuntimeError, indistinguishable from a broken source.
    """
    probe = os.path.join(dirname or ".", f".wprobe.{os.getpid()}")
    with open(probe, "w"):
        pass
    os.unlink(probe)


class _Target:
    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.lib: Optional[ctypes.CDLL] = None
        self.error: Optional[str] = None


_targets: Dict[str, _Target] = {}
_registry_lock = threading.Lock()


def build_and_load(src: str, so: str, extra_flags: List[str],
                   configure: Callable[[ctypes.CDLL], None]) -> ctypes.CDLL:
    """Load (building if stale/missing) ``so`` from ``src``.

    ``configure`` sets restype/argtypes on first load. Raises RuntimeError
    (memoized) when the toolchain is missing or the build/load fails.
    """
    with _registry_lock:
        target = _targets.setdefault(so, _Target())
    with target.lock:
        if target.lib is not None:
            return target.lib
        if target.error is not None:
            raise RuntimeError(target.error)
        try:
            cache_so = None
            if (not os.path.exists(so)
                    or os.path.getmtime(so) < os.path.getmtime(src)):
                try:
                    _probe_writable(os.path.dirname(so))
                    _compile(src, so, extra_flags)
                except OSError:
                    # Read-only install (the write probe failed): build
                    # into the per-user cache instead, memoized under the
                    # ORIGINAL so key above so later calls still
                    # short-circuit. The cache name is keyed by
                    # (source, flags, platform) content, so an existing
                    # file is current. A genuinely failed g++ invocation
                    # (RuntimeError, incl. compiler timeout) on a WRITABLE
                    # dir is NOT a writability problem — it propagates
                    # directly rather than re-running the failed compile
                    # against the cache and misattributing the error to
                    # the cache path (ADVICE r4).
                    cache_so = _cache_path(src, extra_flags)
                    if not os.path.exists(cache_so):
                        _compile(src, cache_so, extra_flags)
                    so = cache_so
            try:
                lib = ctypes.CDLL(so)
            except OSError:
                if cache_so is None:
                    raise
                # A pre-existing cache .so that will not dlopen (e.g. left
                # by an older key scheme, or corrupted): rebuild it once
                # rather than memoizing the failure forever.
                os.unlink(cache_so)
                _compile(src, cache_so, extra_flags)
                lib = ctypes.CDLL(cache_so)
            configure(lib)
        except Exception as e:  # remember, so we don't rebuild per call
            target.error = str(e)
            # Normalize to RuntimeError so callers have ONE "unavailable"
            # exception type regardless of how the build died (missing
            # g++, compiler timeout, dlopen failure, ...).
            raise RuntimeError(target.error) from e
        target.lib = lib
        return lib
