"""ctypes bindings for the native CPU walk sampler (walker.cpp).

Same build contract as the TSV reader (shared scaffolding in _build.py):
compiled once per checkout to ``_walker.so`` beside the sources (or into
``$XDG_CACHE_HOME/g2vec_tpu/`` when the package directory is read-only —
non-editable installs), rebuilt when the .cpp is newer, and a build/load
failure raises RuntimeError exactly once — callers (ops/host_walker.py)
surface it as "native walker unavailable".
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

from g2vec_tpu.native._build import build_and_load

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "walker.cpp")
_SO = os.path.join(_HERE, "_walker.so")


def _configure(lib: ctypes.CDLL) -> None:
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    common = [
        i32p,                                          # indptr [G+1]
        i32p,                                          # indices [E]
        f32p,                                          # w [E]
        ctypes.c_int32,                                # n_genes
        i32p,                                          # starts [W]
        u64p,                                          # ids [W]
        ctypes.c_int64,                                # n_walkers
        ctypes.c_int32,                                # len_path
        ctypes.c_uint64,                               # seed
        ctypes.c_int32,                                # n_threads
    ]
    lib.g2v_walk.restype = None
    lib.g2v_walk.argtypes = common + [i32p]            # out [W, len_path]
    lib.g2v_walk_packed.restype = None
    lib.g2v_walk_packed.argtypes = common + [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int64,                                # nbytes
    ]


def load() -> ctypes.CDLL:
    """Build/load the library (RuntimeError when unavailable). Public so
    benchmarks can warm the one-time compile outside their timed region."""
    # Fault seam: an injected crash (a RuntimeError) makes the "auto"
    # backend resolution degrade to the device walker — the path a host
    # with a broken toolchain takes.
    from g2vec_tpu.resilience.faults import fault_point

    fault_point("native_walker_load")
    return build_and_load(_SRC, _SO, ["-pthread"], _configure)


def _validated(indptr, indices, weights, n_genes, starts, stream_ids,
               len_path):
    """Canonicalize dtypes and bound-check everything the C++ dereferences.

    This module IS the language boundary, so the range checks live here
    (out-of-range ids would be heap corruption, not an exception; a
    non-positive len_path would leave np.empty output buffers unwritten).
    """
    if len_path < 1:
        raise ValueError(f"len_path must be >= 1, got {len_path}")
    indptr = np.ascontiguousarray(indptr, dtype=np.int32)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    weights = np.ascontiguousarray(weights, dtype=np.float32)
    starts = np.ascontiguousarray(starts, dtype=np.int32)
    stream_ids = np.ascontiguousarray(stream_ids, dtype=np.uint64)
    n_walkers = starts.shape[0]
    if stream_ids.shape[0] != n_walkers:
        raise ValueError(
            f"stream_ids has {stream_ids.shape[0]} entries for "
            f"{n_walkers} walkers")
    if indptr.shape[0] != n_genes + 1:
        raise ValueError(
            f"indptr has {indptr.shape[0]} entries for {n_genes} genes "
            f"(want n_genes+1)")
    if weights.shape[0] != indices.shape[0]:
        raise ValueError(
            f"weights has {weights.shape[0]} entries for "
            f"{indices.shape[0]} edges")
    for name, arr in (("starts", starts), ("indices", indices)):
        if arr.size and (arr.min() < 0 or arr.max() >= n_genes):
            raise ValueError(
                f"{name} contains node ids outside [0, {n_genes})")
    if indptr[0] != 0 or indptr[-1] != indices.shape[0] \
            or np.any(np.diff(indptr) < 0):
        raise ValueError("indptr is not a valid CSR row-pointer array")
    return indptr, indices, weights, starts, stream_ids, n_walkers


def walk_paths(indptr: np.ndarray, indices: np.ndarray, weights: np.ndarray,
               n_genes: int, starts: np.ndarray, stream_ids: np.ndarray,
               len_path: int, seed: int, n_threads: int = 0) -> np.ndarray:
    """Run the native sampler; returns [n_walkers, len_path] int32 paths.

    Node ids with -1 padding past each walk's end. Raises RuntimeError when
    the native library is unavailable (no toolchain / build failure).
    """
    lib = load()
    indptr, indices, weights, starts, stream_ids, n_walkers = _validated(
        indptr, indices, weights, n_genes, starts, stream_ids, len_path)
    out = np.empty((n_walkers, len_path), dtype=np.int32)
    lib.g2v_walk(indptr, indices, weights, np.int32(n_genes), starts,
                 stream_ids, np.int64(n_walkers), np.int32(len_path),
                 np.uint64(seed & 0xFFFFFFFFFFFFFFFF), np.int32(n_threads),
                 out)
    return out


def walk_paths_packed(indptr: np.ndarray, indices: np.ndarray,
                      weights: np.ndarray, n_genes: int, starts: np.ndarray,
                      stream_ids: np.ndarray, len_path: int, seed: int,
                      n_threads: int = 0,
                      out: "np.ndarray | None" = None) -> np.ndarray:
    """Same walks as :func:`walk_paths`, emitted as the path-set encoding:
    [n_walkers, ceil(n_genes/8)] uint8 np.packbits-layout multi-hot rows
    (MSB of byte 0 = gene 0). The packing happens inside the sampler's
    walk loop, so no [W, n_genes] dense matrix ever exists on either side
    of the boundary.

    ``out`` lets the caller hand in the destination buffer — the Python
    thread pool (ops/host_walker.py) writes each walker range into a
    disjoint row slice of ONE array, so the sharded result needs no
    concatenate pass and is byte-for-byte the single-call layout. Must be
    C-contiguous uint8 of exactly [n_walkers, ceil(n_genes/8)] (a row
    slice of a C-contiguous matrix qualifies).
    """
    lib = load()
    indptr, indices, weights, starts, stream_ids, n_walkers = _validated(
        indptr, indices, weights, n_genes, starts, stream_ids, len_path)
    nbytes = (n_genes + 7) // 8
    if out is None:
        out = np.empty((n_walkers, nbytes), dtype=np.uint8)
    elif (out.dtype != np.uint8 or out.shape != (n_walkers, nbytes)
            or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous uint8 [{n_walkers}, {nbytes}], got "
            f"{out.dtype} {out.shape} (contiguous={out.flags.c_contiguous})")
    lib.g2v_walk_packed(
        indptr, indices, weights, np.int32(n_genes), starts, stream_ids,
        np.int64(n_walkers), np.int32(len_path),
        np.uint64(seed & 0xFFFFFFFFFFFFFFFF), np.int32(n_threads),
        out, np.int64(nbytes))
    return out
