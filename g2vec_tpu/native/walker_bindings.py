"""ctypes bindings for the native CPU walk sampler (walker.cpp).

Same build contract as the TSV reader (shared scaffolding in _build.py):
compiled once per checkout to ``_walker.so`` beside the sources (or into
``$XDG_CACHE_HOME/g2vec_tpu/`` when the package directory is read-only —
non-editable installs), rebuilt when the .cpp is newer, and a build/load
failure raises RuntimeError exactly once — callers (ops/host_walker.py)
surface it as "native walker unavailable".
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

from g2vec_tpu.native._build import build_and_load

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "walker.cpp")
_SO = os.path.join(_HERE, "_walker.so")


def _configure(lib: ctypes.CDLL) -> None:
    i32p = np.ctypeslib.ndpointer(np.int32, flags="C_CONTIGUOUS")
    f32p = np.ctypeslib.ndpointer(np.float32, flags="C_CONTIGUOUS")
    u64p = np.ctypeslib.ndpointer(np.uint64, flags="C_CONTIGUOUS")
    common = [
        i32p,                                          # indptr [G+1]
        i32p,                                          # indices [E]
        f32p,                                          # w [E]
        ctypes.c_int32,                                # n_genes
        i32p,                                          # starts [W]
        u64p,                                          # ids [W]
        ctypes.c_int64,                                # n_walkers
        ctypes.c_int32,                                # len_path
        ctypes.c_uint64,                               # seed
        ctypes.c_int32,                                # n_threads
    ]
    lib.g2v_walk.restype = None
    lib.g2v_walk.argtypes = common + [i32p]            # out [W, len_path]
    lib.g2v_walk_packed.restype = None
    lib.g2v_walk_packed.argtypes = common + [
        np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS"),
        ctypes.c_int64,                                # nbytes
    ]
    u8p = np.ctypeslib.ndpointer(np.uint8, flags="C_CONTIGUOUS")
    u8pw = np.ctypeslib.ndpointer(
        np.uint8, flags=("C_CONTIGUOUS", "WRITEABLE"))
    i32pw = np.ctypeslib.ndpointer(
        np.int32, flags=("C_CONTIGUOUS", "WRITEABLE"))
    u64pw = np.ctypeslib.ndpointer(
        np.uint64, flags=("C_CONTIGUOUS", "WRITEABLE"))
    lib.g2v_init_walk_state.restype = None
    lib.g2v_init_walk_state.argtypes = [
        ctypes.c_uint64,                               # seed
        u64p,                                          # stream_ids [W]
        ctypes.c_int64,                                # n
        u64pw,                                         # out state [W]
    ]
    lib.g2v_walk_partial.restype = None
    lib.g2v_walk_partial.argtypes = [
        i32p,                                          # indptr [G+1]
        i32p,                                          # indices [E]
        f32p,                                          # w [E]
        ctypes.c_int32,                                # n_genes
        u8p,                                           # avail [G]
        i32pw,                                         # cur [W] (in-out)
        u64pw,                                         # rng [W] (in-out)
        i32pw,                                         # pos [W] (in-out)
        i32pw,                                         # paths [W, L] (in-out)
        ctypes.c_int64,                                # n_walkers
        ctypes.c_int32,                                # len_path
        ctypes.c_int32,                                # n_threads
        u8pw,                                          # status [W] (out)
    ]
    lib.g2v_pack_paths.restype = None
    lib.g2v_pack_paths.argtypes = [
        i32p,                                          # paths [R, L]
        ctypes.c_int64,                                # n_rows
        ctypes.c_int32,                                # len_path
        u8pw,                                          # out [R, nbytes]
        ctypes.c_int64,                                # nbytes
    ]


def load() -> ctypes.CDLL:
    """Build/load the library (RuntimeError when unavailable). Public so
    benchmarks can warm the one-time compile outside their timed region."""
    # Fault seam: an injected crash (a RuntimeError) makes the "auto"
    # backend resolution degrade to the device walker — the path a host
    # with a broken toolchain takes.
    from g2vec_tpu.resilience.faults import fault_point

    fault_point("native_walker_load")
    return build_and_load(_SRC, _SO, ["-pthread"], _configure)


def _validated(indptr, indices, weights, n_genes, starts, stream_ids,
               len_path):
    """Canonicalize dtypes and bound-check everything the C++ dereferences.

    This module IS the language boundary, so the range checks live here
    (out-of-range ids would be heap corruption, not an exception; a
    non-positive len_path would leave np.empty output buffers unwritten).
    """
    if len_path < 1:
        raise ValueError(f"len_path must be >= 1, got {len_path}")
    indptr = np.ascontiguousarray(indptr, dtype=np.int32)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    weights = np.ascontiguousarray(weights, dtype=np.float32)
    starts = np.ascontiguousarray(starts, dtype=np.int32)
    stream_ids = np.ascontiguousarray(stream_ids, dtype=np.uint64)
    n_walkers = starts.shape[0]
    if stream_ids.shape[0] != n_walkers:
        raise ValueError(
            f"stream_ids has {stream_ids.shape[0]} entries for "
            f"{n_walkers} walkers")
    if indptr.shape[0] != n_genes + 1:
        raise ValueError(
            f"indptr has {indptr.shape[0]} entries for {n_genes} genes "
            f"(want n_genes+1)")
    if weights.shape[0] != indices.shape[0]:
        raise ValueError(
            f"weights has {weights.shape[0]} entries for "
            f"{indices.shape[0]} edges")
    for name, arr in (("starts", starts), ("indices", indices)):
        if arr.size and (arr.min() < 0 or arr.max() >= n_genes):
            raise ValueError(
                f"{name} contains node ids outside [0, {n_genes})")
    if indptr[0] != 0 or indptr[-1] != indices.shape[0] \
            or np.any(np.diff(indptr) < 0):
        raise ValueError("indptr is not a valid CSR row-pointer array")
    return indptr, indices, weights, starts, stream_ids, n_walkers


def walk_paths(indptr: np.ndarray, indices: np.ndarray, weights: np.ndarray,
               n_genes: int, starts: np.ndarray, stream_ids: np.ndarray,
               len_path: int, seed: int, n_threads: int = 0) -> np.ndarray:
    """Run the native sampler; returns [n_walkers, len_path] int32 paths.

    Node ids with -1 padding past each walk's end. Raises RuntimeError when
    the native library is unavailable (no toolchain / build failure).
    """
    lib = load()
    indptr, indices, weights, starts, stream_ids, n_walkers = _validated(
        indptr, indices, weights, n_genes, starts, stream_ids, len_path)
    out = np.empty((n_walkers, len_path), dtype=np.int32)
    lib.g2v_walk(indptr, indices, weights, np.int32(n_genes), starts,
                 stream_ids, np.int64(n_walkers), np.int32(len_path),
                 np.uint64(seed & 0xFFFFFFFFFFFFFFFF), np.int32(n_threads),
                 out)
    return out


def walk_paths_packed(indptr: np.ndarray, indices: np.ndarray,
                      weights: np.ndarray, n_genes: int, starts: np.ndarray,
                      stream_ids: np.ndarray, len_path: int, seed: int,
                      n_threads: int = 0,
                      out: "np.ndarray | None" = None) -> np.ndarray:
    """Same walks as :func:`walk_paths`, emitted as the path-set encoding:
    [n_walkers, ceil(n_genes/8)] uint8 np.packbits-layout multi-hot rows
    (MSB of byte 0 = gene 0). The packing happens inside the sampler's
    walk loop, so no [W, n_genes] dense matrix ever exists on either side
    of the boundary.

    ``out`` lets the caller hand in the destination buffer — the Python
    thread pool (ops/host_walker.py) writes each walker range into a
    disjoint row slice of ONE array, so the sharded result needs no
    concatenate pass and is byte-for-byte the single-call layout. Must be
    C-contiguous uint8 of exactly [n_walkers, ceil(n_genes/8)] (a row
    slice of a C-contiguous matrix qualifies).
    """
    lib = load()
    indptr, indices, weights, starts, stream_ids, n_walkers = _validated(
        indptr, indices, weights, n_genes, starts, stream_ids, len_path)
    nbytes = (n_genes + 7) // 8
    if out is None:
        out = np.empty((n_walkers, nbytes), dtype=np.uint8)
    elif (out.dtype != np.uint8 or out.shape != (n_walkers, nbytes)
            or not out.flags.c_contiguous):
        raise ValueError(
            f"out must be C-contiguous uint8 [{n_walkers}, {nbytes}], got "
            f"{out.dtype} {out.shape} (contiguous={out.flags.c_contiguous})")
    lib.g2v_walk_packed(
        indptr, indices, weights, np.int32(n_genes), starts, stream_ids,
        np.int64(n_walkers), np.int32(len_path),
        np.uint64(seed & 0xFFFFFFFFFFFFFFFF), np.int32(n_threads),
        out, np.int64(nbytes))
    return out


def init_walk_state(seed: int, stream_ids: np.ndarray) -> np.ndarray:
    """Raw splitmix64 state per walker, exactly as g2v_walk_packed seeds
    it internally (xor-fold of the stream id plus one decorrelation
    advance). A walk resumed from this state via :func:`walk_partial`
    draws the identical uniform sequence the one-shot sampler would."""
    lib = load()
    stream_ids = np.ascontiguousarray(stream_ids, dtype=np.uint64)
    out = np.empty(stream_ids.shape[0], dtype=np.uint64)
    lib.g2v_init_walk_state(np.uint64(seed & 0xFFFFFFFFFFFFFFFF),
                            stream_ids, np.int64(stream_ids.shape[0]), out)
    return out


def walk_partial(indptr: np.ndarray, indices: np.ndarray,
                 weights: np.ndarray, n_genes: int, avail: np.ndarray,
                 cur: np.ndarray, rng: np.ndarray, pos: np.ndarray,
                 paths: np.ndarray, len_path: int,
                 n_threads: int = 0) -> np.ndarray:
    """Advance explicit-state walks over an availability-masked CSR.

    ``cur``/``rng``/``pos``/``paths`` are updated IN PLACE; returns a
    [n_walkers] uint8 status array — 0 when the walk finished (full
    length or dead end), 1 when it suspended because ``avail[cur]`` is 0
    (the rank owning ``cur``'s row must resume it). Rows with
    ``avail[g] == 0`` may be empty in the CSR; they are never scanned.
    """
    if len_path < 1:
        raise ValueError(f"len_path must be >= 1, got {len_path}")
    lib = load()
    indptr = np.ascontiguousarray(indptr, dtype=np.int32)
    indices = np.ascontiguousarray(indices, dtype=np.int32)
    weights = np.ascontiguousarray(weights, dtype=np.float32)
    avail = np.ascontiguousarray(avail, dtype=np.uint8)
    n_walkers = cur.shape[0]
    if indptr.shape[0] != n_genes + 1:
        raise ValueError(
            f"indptr has {indptr.shape[0]} entries for {n_genes} genes "
            f"(want n_genes+1)")
    if weights.shape[0] != indices.shape[0]:
        raise ValueError(
            f"weights has {weights.shape[0]} entries for "
            f"{indices.shape[0]} edges")
    if avail.shape[0] != n_genes:
        raise ValueError(
            f"avail has {avail.shape[0]} entries for {n_genes} genes")
    if indices.size and (indices.min() < 0 or indices.max() >= n_genes):
        raise ValueError(f"indices contains node ids outside [0, {n_genes})")
    if indptr[0] != 0 or indptr[-1] != indices.shape[0] \
            or np.any(np.diff(indptr) < 0):
        raise ValueError("indptr is not a valid CSR row-pointer array")
    for name, arr, dt in (("cur", cur, np.int32), ("rng", rng, np.uint64),
                          ("pos", pos, np.int32)):
        if arr.dtype != dt or arr.shape != (n_walkers,) \
                or not arr.flags.c_contiguous or not arr.flags.writeable:
            raise ValueError(
                f"{name} must be writable C-contiguous {np.dtype(dt)} "
                f"[{n_walkers}], got {arr.dtype} {arr.shape}")
    if paths.dtype != np.int32 or paths.shape != (n_walkers, len_path) \
            or not paths.flags.c_contiguous or not paths.flags.writeable:
        raise ValueError(
            f"paths must be writable C-contiguous int32 "
            f"[{n_walkers}, {len_path}], got {paths.dtype} {paths.shape}")
    if n_walkers and (cur.min() < 0 or cur.max() >= n_genes):
        raise ValueError(f"cur contains node ids outside [0, {n_genes})")
    if n_walkers and (pos.min() < 1 or pos.max() > len_path):
        raise ValueError(f"pos outside [1, {len_path}]")
    status = np.empty(n_walkers, dtype=np.uint8)
    lib.g2v_walk_partial(
        indptr, indices, weights, np.int32(n_genes), avail, cur, rng, pos,
        paths, np.int64(n_walkers), np.int32(len_path), np.int32(n_threads),
        status)
    return status


def pack_paths(paths: np.ndarray, n_genes: int,
               out: "np.ndarray | None" = None) -> np.ndarray:
    """Pack [R, len_path] int32 paths (-1 padded) into the packbits
    multi-hot encoding g2v_walk_packed emits — byte-identical rows for
    the same node sets. ``out`` may be a row slice of a larger matrix
    (the shard owner scatters remotely-completed walks into the shard's
    buffer at their walker-index rows)."""
    lib = load()
    paths = np.ascontiguousarray(paths, dtype=np.int32)
    if paths.ndim != 2:
        raise ValueError(f"paths must be [R, len_path], got {paths.shape}")
    n_rows, len_path = paths.shape
    live = paths[paths >= 0]
    if live.size and live.max() >= n_genes:
        raise ValueError(f"paths contains node ids outside [0, {n_genes})")
    nbytes = (n_genes + 7) // 8
    if out is None:
        out = np.empty((n_rows, nbytes), dtype=np.uint8)
    elif (out.dtype != np.uint8 or out.shape != (n_rows, nbytes)
            or not out.flags.c_contiguous or not out.flags.writeable):
        raise ValueError(
            f"out must be writable C-contiguous uint8 [{n_rows}, {nbytes}], "
            f"got {out.dtype} {out.shape}")
    lib.g2v_pack_paths(paths, np.int64(n_rows), np.int32(len_path), out,
                       np.int64(nbytes))
    return out
