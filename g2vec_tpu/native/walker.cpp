// Native CPU random-walk sampler — the host-side twin of ops/walker.py.
//
// Reference semantics (generate_randomPath, ref: G2Vec.py:328-346):
// weighted no-revisit walks of at most len_path nodes, Categorical over the
// current node's positive out-edge weights restricted to unvisited targets,
// early stop at dead ends. The reference pays an O(n_genes) dense-row
// deepcopy per step; this walks CSR rows with an O(out_degree) two-pass
// scan (mass total, then inverse-CDF pick) and an O(1)-membership visited
// byte mask that is wiped by path replay after each walk, so cost per step
// is O(D + L) instead of O(G).
//
// Threading: walkers split into contiguous ranges over n_threads OS
// threads. Every walker draws from its own splitmix64 stream keyed by
// (seed, stream_id) — results are bit-identical for any thread count.
//
// Exposed flat-C so ctypes can load it (no pybind11 in this image).

#include <algorithm>
#include <cstdint>
#include <thread>
#include <vector>

namespace {

inline uint64_t splitmix64(uint64_t& s) {
    uint64_t z = (s += 0x9e3779b97f4a7c15ULL);
    z = (z ^ (z >> 30)) * 0xbf58476d1ce4e5b9ULL;
    z = (z ^ (z >> 27)) * 0x94d049bb133111ebULL;
    return z ^ (z >> 31);
}

// 53-bit mantissa uniform in [0, 1).
inline double uniform01(uint64_t& s) {
    return static_cast<double>(splitmix64(s) >> 11) * 0x1.0p-53;
}

// PACKED=false writes [len_path] int32 node lists (-1 pads) to out_paths;
// PACKED=true writes [nbytes] np.packbits-layout multi-hot rows (MSB of
// byte 0 = gene 0) to out_packed — the path-set encoding, built here so
// the Python side never expands a [W, G] bool matrix just to re-pack it.
template <bool PACKED>
void walk_range(const int32_t* indptr, const int32_t* indices,
                const float* weights, int32_t n_genes, const int32_t* starts,
                const uint64_t* stream_ids, int32_t len_path, uint64_t seed,
                int32_t* out_paths, uint8_t* out_packed, int64_t nbytes,
                int64_t max_degree, int64_t lo, int64_t hi) {
    std::vector<uint8_t> visited(static_cast<size_t>(n_genes), 0);
    std::vector<int32_t> scratch(PACKED ? static_cast<size_t>(len_path) : 0);
    // Eligible-neighbor compaction: the mass pass records each unvisited
    // positive-weight neighbor's running cumulative sum, and the second
    // scan becomes a binary search over that buffer. Same cumulative
    // values in the same order, same "first cum > target" rule as the
    // old scan, so streams/goldens are unchanged; measured ~12% faster
    // at bundled scale (the second scan averaged ~d/2 extra reads).
    // Sized by MAX ROW DEGREE, which can exceed n_genes — duplicate
    // edges are legal (multiset semantics, ops/host_walker.edges_to_csr)
    // and each duplicate occupies its own slot, exactly as it added its
    // own mass in the old scan.
    std::vector<double> cumbuf(static_cast<size_t>(max_degree));
    std::vector<int32_t> idxbuf(static_cast<size_t>(max_degree));
    for (int64_t w = lo; w < hi; ++w) {
        int32_t* path;
        if (PACKED) {
            path = scratch.data();
            std::fill(path, path + len_path, -1);
        } else {
            path = out_paths + w * len_path;
            std::fill(path, path + len_path, -1);
        }
        uint64_t st = seed ^ (stream_ids[w] * 0x9e3779b97f4a7c15ULL);
        splitmix64(st);  // decorrelate nearby stream ids
        int32_t cur = starts[w];
        path[0] = cur;
        visited[cur] = 1;
        int32_t plen = 1;
        for (int32_t step = 1; step < len_path; ++step) {
            const int32_t b = indptr[cur], e = indptr[cur + 1];
            int32_t m = 0;
            double total = 0.0;
            for (int32_t k = b; k < e; ++k) {
                const int32_t t = indices[k];
                if (!visited[t] && weights[k] > 0.0f) {
                    total += weights[k];
                    cumbuf[m] = total;
                    idxbuf[m] = t;
                    ++m;
                }
            }
            if (m == 0 || total <= 0.0) break;  // dead end (G2Vec.py:343-344)
            const double target = uniform01(st) * total;
            // Smallest j with target < cumbuf[j]; target == total after
            // rounding falls through to the last eligible (the old
            // second-scan fallback).
            int32_t lo_j = 0, hi_j = m;
            while (lo_j < hi_j) {
                const int32_t mid = lo_j + ((hi_j - lo_j) >> 1);
                if (target < cumbuf[mid]) hi_j = mid;
                else lo_j = mid + 1;
            }
            const int32_t nxt = idxbuf[lo_j < m ? lo_j : m - 1];
            path[plen++] = nxt;
            visited[nxt] = 1;
            cur = nxt;
        }
        if (PACKED) {
            uint8_t* row = out_packed + w * nbytes;
            std::fill(row, row + nbytes, 0);
            for (int32_t i = 0; i < plen; ++i) {
                const int32_t n = path[i];
                row[n >> 3] |= static_cast<uint8_t>(0x80u >> (n & 7));
            }
        }
        for (int32_t i = 0; i < plen; ++i) visited[path[i]] = 0;
    }
}

template <bool PACKED>
void walk_threaded(const int32_t* indptr, const int32_t* indices,
                   const float* weights, int32_t n_genes,
                   const int32_t* starts, const uint64_t* stream_ids,
                   int64_t n_walkers, int32_t len_path, uint64_t seed,
                   int32_t n_threads, int32_t* out_paths, uint8_t* out_packed,
                   int64_t nbytes) {
    if (len_path <= 0 || n_walkers <= 0) return;
    int64_t max_degree = 1;
    for (int32_t g = 0; g < n_genes; ++g)
        max_degree = std::max<int64_t>(max_degree, indptr[g + 1] - indptr[g]);
    if (n_threads <= 0) {
        unsigned hw = std::thread::hardware_concurrency();
        n_threads = hw ? static_cast<int32_t>(hw) : 1;
    }
    n_threads = static_cast<int32_t>(
        std::min<int64_t>(n_threads, n_walkers));
    if (n_threads == 1) {
        walk_range<PACKED>(indptr, indices, weights, n_genes, starts,
                           stream_ids, len_path, seed, out_paths, out_packed,
                           nbytes, max_degree, 0, n_walkers);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    const int64_t chunk = (n_walkers + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(lo + chunk, n_walkers);
        if (lo >= hi) break;
        pool.emplace_back(walk_range<PACKED>, indptr, indices, weights,
                          n_genes, starts, stream_ids, len_path, seed,
                          out_paths, out_packed, nbytes, max_degree, lo, hi);
    }
    for (auto& th : pool) th.join();
}

// Resumable walks over an availability-masked CSR (edge-partitioned
// mode). Each walker carries explicit state — current gene, raw
// splitmix64 state, and the path prefix taken so far — so a walk can
// suspend at a partition boundary (the row for `cur` is not
// materialized on this rank: avail[cur] == 0) and resume bit-identically
// on the rank that owns it. The step body below is a literal copy of
// walk_range's: same eligible scan, same cumulative order, same single
// uniform01 draw per step, so a walk's draw sequence is independent of
// where (or in how many pieces) it executes.
void walk_partial_range(const int32_t* indptr, const int32_t* indices,
                        const float* weights, int32_t n_genes,
                        const uint8_t* avail, int32_t* cur, uint64_t* rng,
                        int32_t* pos, int32_t* paths, int32_t len_path,
                        uint8_t* status, int64_t max_degree, int64_t lo,
                        int64_t hi) {
    std::vector<uint8_t> visited(static_cast<size_t>(n_genes), 0);
    std::vector<double> cumbuf(static_cast<size_t>(max_degree));
    std::vector<int32_t> idxbuf(static_cast<size_t>(max_degree));
    for (int64_t w = lo; w < hi; ++w) {
        int32_t* path = paths + w * len_path;
        int32_t plen = pos[w];
        for (int32_t i = 0; i < plen; ++i) visited[path[i]] = 1;
        int32_t c = cur[w];
        uint64_t st = rng[w];
        uint8_t suspended = 0;
        while (plen < len_path) {
            if (!avail[c]) {  // partition boundary: owner of c resumes
                suspended = 1;
                break;
            }
            const int32_t b = indptr[c], e = indptr[c + 1];
            int32_t m = 0;
            double total = 0.0;
            for (int32_t k = b; k < e; ++k) {
                const int32_t t = indices[k];
                if (!visited[t] && weights[k] > 0.0f) {
                    total += weights[k];
                    cumbuf[m] = total;
                    idxbuf[m] = t;
                    ++m;
                }
            }
            if (m == 0 || total <= 0.0) break;  // dead end
            const double target = uniform01(st) * total;
            int32_t lo_j = 0, hi_j = m;
            while (lo_j < hi_j) {
                const int32_t mid = lo_j + ((hi_j - lo_j) >> 1);
                if (target < cumbuf[mid]) hi_j = mid;
                else lo_j = mid + 1;
            }
            const int32_t nxt = idxbuf[lo_j < m ? lo_j : m - 1];
            path[plen++] = nxt;
            visited[nxt] = 1;
            c = nxt;
        }
        cur[w] = c;
        rng[w] = st;
        pos[w] = plen;
        status[w] = suspended;
        for (int32_t i = 0; i < plen; ++i) visited[path[i]] = 0;
    }
}

void walk_partial_threaded(const int32_t* indptr, const int32_t* indices,
                           const float* weights, int32_t n_genes,
                           const uint8_t* avail, int32_t* cur, uint64_t* rng,
                           int32_t* pos, int32_t* paths, int64_t n_walkers,
                           int32_t len_path, int32_t n_threads,
                           uint8_t* status) {
    if (len_path <= 0 || n_walkers <= 0) return;
    int64_t max_degree = 1;
    for (int32_t g = 0; g < n_genes; ++g)
        max_degree = std::max<int64_t>(max_degree, indptr[g + 1] - indptr[g]);
    if (n_threads <= 0) {
        unsigned hw = std::thread::hardware_concurrency();
        n_threads = hw ? static_cast<int32_t>(hw) : 1;
    }
    n_threads = static_cast<int32_t>(
        std::min<int64_t>(n_threads, n_walkers));
    if (n_threads == 1) {
        walk_partial_range(indptr, indices, weights, n_genes, avail, cur,
                           rng, pos, paths, len_path, status, max_degree, 0,
                           n_walkers);
        return;
    }
    std::vector<std::thread> pool;
    pool.reserve(n_threads);
    const int64_t chunk = (n_walkers + n_threads - 1) / n_threads;
    for (int32_t t = 0; t < n_threads; ++t) {
        const int64_t lo = t * chunk;
        const int64_t hi = std::min<int64_t>(lo + chunk, n_walkers);
        if (lo >= hi) break;
        pool.emplace_back(walk_partial_range, indptr, indices, weights,
                          n_genes, avail, cur, rng, pos, paths, len_path,
                          status, max_degree, lo, hi);
    }
    for (auto& th : pool) th.join();
}

}  // namespace

extern "C" {

// out must hold n_walkers * len_path int32; filled with node ids, -1 pads.
void g2v_walk(const int32_t* indptr, const int32_t* indices,
              const float* weights, int32_t n_genes, const int32_t* starts,
              const uint64_t* stream_ids, int64_t n_walkers,
              int32_t len_path, uint64_t seed, int32_t n_threads,
              int32_t* out) {
    walk_threaded<false>(indptr, indices, weights, n_genes, starts,
                         stream_ids, n_walkers, len_path, seed, n_threads,
                         out, nullptr, 0);
}

// out must hold n_walkers * nbytes uint8 (nbytes = ceil(n_genes/8));
// filled with np.packbits-layout multi-hot rows. Identical walks to
// g2v_walk for the same inputs — only the output encoding differs.
void g2v_walk_packed(const int32_t* indptr, const int32_t* indices,
                     const float* weights, int32_t n_genes,
                     const int32_t* starts, const uint64_t* stream_ids,
                     int64_t n_walkers, int32_t len_path, uint64_t seed,
                     int32_t n_threads, uint8_t* out, int64_t nbytes) {
    walk_threaded<true>(indptr, indices, weights, n_genes, starts,
                        stream_ids, n_walkers, len_path, seed, n_threads,
                        nullptr, out, nbytes);
}

// The per-walker PRNG init walk_range performs inline: raw state =
// seed ^ (stream_id * GOLDEN), then one discarded splitmix64 output to
// decorrelate nearby stream ids. Exposed so the edge-partitioned path
// can seed explicit walk states that continue the EXACT stream
// g2v_walk_packed would have drawn from.
void g2v_init_walk_state(uint64_t seed, const uint64_t* stream_ids,
                         int64_t n, uint64_t* out) {
    for (int64_t i = 0; i < n; ++i) {
        uint64_t st = seed ^ (stream_ids[i] * 0x9e3779b97f4a7c15ULL);
        splitmix64(st);
        out[i] = st;
    }
}

// Resume/run walks with explicit per-walker state over an
// availability-masked CSR. cur/rng/pos/paths are IN-OUT; status[w] is
// 1 when walker w suspended on an unavailable row (cur[w] names the
// gene whose owner must resume it), 0 when it finished (full length or
// dead end). Rows with avail[g] == 0 may have empty indptr spans — they
// are never scanned.
void g2v_walk_partial(const int32_t* indptr, const int32_t* indices,
                      const float* weights, int32_t n_genes,
                      const uint8_t* avail, int32_t* cur, uint64_t* rng,
                      int32_t* pos, int32_t* paths, int64_t n_walkers,
                      int32_t len_path, int32_t n_threads, uint8_t* status) {
    walk_partial_threaded(indptr, indices, weights, n_genes, avail, cur,
                          rng, pos, paths, n_walkers, len_path, n_threads,
                          status);
}

// Pack finished [len_path] int32 paths (-1 padded) into
// np.packbits-layout multi-hot rows, the same encoding g2v_walk_packed
// emits — used by the shard owner to assemble remotely-completed walks
// without ever expanding a [W, G] bool matrix.
void g2v_pack_paths(const int32_t* paths, int64_t n_rows, int32_t len_path,
                    uint8_t* out, int64_t nbytes) {
    for (int64_t r = 0; r < n_rows; ++r) {
        const int32_t* path = paths + r * len_path;
        uint8_t* row = out + r * nbytes;
        std::fill(row, row + nbytes, 0);
        for (int32_t i = 0; i < len_path; ++i) {
            const int32_t n = path[i];
            if (n < 0) break;
            row[n >> 3] |= static_cast<uint8_t>(0x80u >> (n & 7));
        }
    }
}

}  // extern "C"
