"""The seven-stage pipeline orchestrator.

Drives L0-L6 in the reference's order (ref: main, G2Vec.py:11-120) and
reproduces its console transcript (the only golden spec the reference
publishes, README.md:21-49): stage banners ``>>> N. ...``, the indented
preprocessing stats, the epoch log cadence, and the saved-file listing —
while running stages 3-5 on device (adjacency, walks, trainer, k-means all
jit-compiled JAX).

Differences from the reference, all deliberate (SURVEY.md §7):
- seeded end to end (the reference is unseeded);
- ``--epoch`` is honored (the reference hardcodes 500, G2Vec.py:262);
- structured JSONL metrics / profiler traces / checkpoints behind flags;
- stage 3 walks all sources in lockstep on device instead of one Python
  walker at a time (ops/walker.py docstring has the mapping);
- overlapped execution (parallel/overlap.py): the two groups' native
  walks sample concurrently on the host pool, and the trainer-chunk and
  k-means compiles warm in the background while stage 3 walks — the
  transcript and every output stay byte-identical, only the wall clock
  moves; ``--no-overlap`` restores strictly sequential stages;
- persistent caches (g2vec_tpu/cache.py): ``--cache-dir`` wires the XLA
  compilation cache AND a sha256-verified walk-artifact tier, so a
  repeat run at the same inputs/config skips stage 3's walks entirely.
"""
from __future__ import annotations

import dataclasses
import json
import os
from typing import Callable, Dict, List, Optional

import numpy as np

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.resilience.faults import fault_point, install_plan


@dataclasses.dataclass
class PipelineResult:
    genes: np.ndarray            # [G] str — global sorted-intersection order
    embeddings: np.ndarray       # [G, hidden] float32
    lgroup_idx: np.ndarray       # [G] int32 in {0 good, 1 poor, 2 other}
    biomarkers: List[str]
    output_files: List[str]
    n_samples: int = 0
    n_genes: int = 0
    n_edges: int = 0
    n_paths: int = 0
    n_path_genes: int = 0
    train_history: List[dict] = dataclasses.field(default_factory=list)
    acc_val: float = 0.0
    stage_seconds: Dict[str, float] = dataclasses.field(default_factory=dict)
    walker_backend: str = ""     # the RESOLVED stage-3 sampler ("device" |
                                 # "native") — what actually ran, not the
                                 # config value (which may be "auto")
    sampler_threads: int = 0     # resolved host-pool width (0 when the
                                 # device walker ran)
    overlap_saved_s: Dict[str, float] = dataclasses.field(
        default_factory=dict)    # per-background-task run time the
                                 # foreground never waited for
    walk_cache_hits: List[str] = dataclasses.field(default_factory=list)
                                 # groups whose stage-3 walks were served
                                 # from the artifact cache
    stream_stats: Dict = dataclasses.field(default_factory=dict)
                                 # --train-mode streaming attribution
                                 # (train/stream.py StreamStats.as_dict();
                                 # empty for full-batch runs)
    edge_stats: Dict = dataclasses.field(default_factory=dict)
                                 # --edge-partition attribution for THIS
                                 # rank (csr_bytes/halo bytes; only the
                                 # coordinator has a metrics stream, so
                                 # per-rank numbers ride the result);
                                 # empty when edge partitioning is off
    biomarker_scores: Optional[np.ndarray] = None
                                 # [2, G] float32 prognostic score stack
                                 # (good row 0 / poor row 1) — the query
                                 # plane's topk_biomarkers vector, kept
                                 # so the serve daemon can publish the
                                 # inventory bundle without recomputing
                                 # stage 6
    km_centers: Optional[np.ndarray] = None
                                 # [k, hidden] float32 stage-5 k-means
                                 # centers (winning restart) — seeds the
                                 # bundle's IVF coarse quantizer
                                 # (ops/ann.build_ivf) when hidden
                                 # matches; None for sharded runs


def _background_warm(fn, console):
    """Wrap a compile-warm thunk for the overlap scheduler: a warm is an
    optimization, so ANY failure degrades to a console note and False —
    the foreground stage then simply pays the compile itself, exactly the
    pre-overlap behavior."""
    def task():
        try:
            return fn()
        except Exception as e:  # noqa: BLE001 — warm failure must not kill
            console(f"    [overlap] background compile warm skipped "
                    f"({type(e).__name__}: {str(e)[:120]})")
            return False
    return task


class _EpochReporter:
    """Reproduces the reference's epoch log cadence (ref: G2Vec.py:269-278).

    A line is printed whenever ``step % display_step == 0``, showing the wall
    time accumulated since the previous printed line; on early stop the
    ``Epoch(stop)`` line reports the PREVIOUS epoch's accuracies.
    """

    def __init__(self, console: Callable[[str], None], display_step: int):
        self.console = console
        self.display_step = display_step
        self.block_secs = 0.0

    def on_epoch(self, step: int, acc_val: float, acc_tr: float, secs: float) -> None:
        self.block_secs += secs
        if step % self.display_step == 0:
            self.console("    - Epoch: %03d\tACC[val]=%.4f\tACC[tr]=%.4f (%.3f sec)"
                         % (step, acc_val, acc_tr, self.block_secs))
            self.block_secs = 0.0

    def on_stop(self, stop_epoch: int, acc_val: float, acc_tr: float) -> None:
        self.console("    - Epoch(stop): %03d\tACC[val]=%.4f\tACC[tr]=%.4f (%.3f sec)"
                     % (stop_epoch, acc_val, acc_tr, self.block_secs))


def run(cfg: G2VecConfig, console: Callable[[str], None] = print,
        check: Optional[Callable[[], None]] = None,
        lifecycle: Optional[Callable[[str, dict], None]] = None,
        ) -> PipelineResult:
    """Execute the full pipeline; returns all artifacts plus run stats.

    ``check`` is the cooperative-interruption hook threaded into the
    trainers' epoch/shard loops (resilience/lifecycle.py — the serve
    daemon raises cancel/deadline/drain through it); ``lifecycle(state,
    info)`` observes the durable-job transitions ("checkpointed",
    "resumed") the streaming trainer emits.
    """
    # Deferred imports: jax must not be pulled in before the CLI has had the
    # chance to set platform env vars (see __main__.py).
    import jax

    from g2vec_tpu.analysis import biomarker_scores_device, top_biomarkers
    from g2vec_tpu.io.readers import load_clinical, load_expression, load_network
    from g2vec_tpu.io.writers import write_biomarkers, write_lgroups, write_vectors
    from g2vec_tpu.ops.graph import thresholded_edges
    from g2vec_tpu.ops.walker import count_gene_freq, integrate_path_sets
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.preprocess import (edges_to_indices, find_common_genes,
                                      fold_cohort, make_gene2idx,
                                      match_labels, permute_labels,
                                      restrict_data, restrict_network,
                                      subsample_patients)
    from g2vec_tpu.train.trainer import train_cbow
    from g2vec_tpu.utils.metrics import MetricsWriter
    from g2vec_tpu.utils.timing import StageTimer

    cfg.validate()
    if cfg.fault_plan:
        # Config-driven fault injection (tests/chaos drills); the env-var
        # form needs no install. Re-installing on a supervised retry keeps
        # already-fired once-only entries fired.
        install_plan(cfg.fault_plan)
    if cfg.distributed:
        # Idempotent when __main__ already joined; after a runtime
        # teardown (distributed.shutdown) an in-process supervisor restart
        # re-initializes here.
        from g2vec_tpu.parallel.distributed import initialize

        initialize(cfg.coordinator, cfg.process_id, cfg.num_processes)
    from g2vec_tpu.resilience import fleet

    fleet.configure(liveness_dir=cfg.fleet_liveness_dir,
                    heartbeat_interval=cfg.fleet_heartbeat_interval,
                    watchdog_deadline=cfg.fleet_watchdog_deadline,
                    straggler_factor=cfg.fleet_straggler_factor)
    if cfg.debug_nans:
        jax.config.update("jax_debug_nans", True)
    from g2vec_tpu.cache import (autotune_cache_path, configure_xla_cache,
                                 resolve_cache_tiers)

    xla_cache_dir, walk_cache = resolve_cache_tiers(
        cfg.cache_dir, cfg.compilation_cache, cfg.walk_cache)
    autotune_path = autotune_cache_path(cfg.cache_dir)
    if cfg.distributed:
        # The artifact tier is per-host files; in a multi-process run the
        # ranks would race identical writes and the sharded native walk
        # would cache only this rank's shard under a full-set key. Keep
        # multi-process runs uncached until the tier learns rank scoping.
        walk_cache = None
    # Persistent XLA cache: a warm repeat run skips the compiles that
    # dominate a cold pipeline's wall (the TPU acceptance run spends
    # most of its train/lgroups/biomarkers stage time compiling).
    configure_xla_cache(xla_cache_dir)
    if cfg.distributed:
        # Worker processes compute shards but neither narrate nor write:
        # transcript, metrics stream, profiler trace, and the three outputs
        # all belong to the coordinator (checkpoint writes are gated inside
        # save_state, which every process must still enter — it gathers
        # cross-process shards collectively).
        from g2vec_tpu.parallel.distributed import is_coordinator

        if jax.process_count() > 1 and not cfg.mesh_shape \
                and not (cfg.graph_shards or cfg.embed_shards):
            raise ValueError(
                f"--distributed with {jax.process_count()} processes needs "
                "--mesh (e.g. --mesh 8x1) or --graph-shards/--embed-shards; "
                "without either every process would redundantly train the "
                "full model on one local device")
        if not is_coordinator():
            console = lambda s: None  # noqa: E731
            cfg = dataclasses.replace(cfg, metrics_jsonl=None,
                                      profile_dir=None)

    timer = StageTimer()
    edge_attrib: Dict = {}       # this rank's --edge-partition attribution
    # A resumed run APPENDS: its records continue the interrupted attempt's
    # stream (and the supervisor's retry/resume events in between survive).
    metrics = MetricsWriter(cfg.metrics_jsonl, append=cfg.resume)
    if cfg.distributed:
        # Structured init-outcome records (e.g. single_process_fallback —
        # the misconfigured-fleet hazard whose only other symptom is one
        # stderr line) land in the stream ahead of the run's own records.
        from g2vec_tpu.parallel.distributed import drain_pending_events

        for ev in drain_pending_events():
            metrics.emit(ev.pop("event"), **ev)
    # Liveness beacon + per-stage fleet barriers (no-ops unless --fleet-*
    # flags enable them; see resilience/fleet.py).
    fleet.start_heartbeat(metrics)

    def _stage_edge(name: str) -> None:
        # Post-stage fleet barrier + straggler check: a rank that died
        # mid-stage surfaces here as PeerTimeoutError naming it, at the
        # stage edge, instead of wedging an arbitrary later collective.
        if cfg.distributed:
            fleet.stage_barrier(name, timer.as_dict().get(name, 0.0),
                                metrics, console)

    if cfg.profile_dir:
        jax.profiler.start_trace(cfg.profile_dir)

    # Created at stage 3 (it needs the resolved backend); closed in the
    # outer finally so a failing FOREGROUND stage still drains the
    # background tasks instead of leaking threads mid-walk.
    overlap = None
    try:
        console(">>> 0. Arguments")
        console(str(cfg))
        metrics.emit("config", **{f.name: str(getattr(cfg, f.name))
                                  for f in dataclasses.fields(cfg)})

        console(">>> 1. Load data")
        fault_point("load")
        fleet.note_phase("load")
        with timer.stage("load"):
            data = load_expression(cfg.expression_file, use_native=cfg.use_native_io)
            clinical = load_clinical(cfg.clinical_file)
            if cfg.edge_partition != "off":
                # Edge-partitioned (--edge-partition): scan endpoint
                # NAMES only (O(G) strings — the sorted-common invariant
                # needs the set); the edges themselves stream later
                # through the src-range-filtered reader, so the full
                # edge list never materializes on any rank
                # (io/readers.FORBID_FULL_NETWORK_ENV pins this).
                from g2vec_tpu.io.readers import scan_network_genes

                network = None
                net_genes = scan_network_genes(cfg.network_file)
            else:
                network = load_network(cfg.network_file)
        _stage_edge("load")

        console(">>> 2. Preprocess data")
        fault_point("preprocess")
        fleet.note_phase("preprocess")
        with timer.stage("preprocess"):
            data.label = match_labels(clinical, data.sample)
            if cfg.subsample_mode == "bootstrap":
                n_before = data.expr.shape[0]
                data = subsample_patients(data,
                                          cfg.patient_subsample or 1.0,
                                          cfg.subsample_seed,
                                          with_replacement=True)
                console("    patient bootstrap: drew %d/%d samples with "
                        "replacement (fraction=%.3f, seed=%d)"
                        % (data.expr.shape[0], n_before,
                           cfg.patient_subsample or 1.0,
                           cfg.subsample_seed))
            elif cfg.subsample_mode == "fold":
                n_before = data.expr.shape[0]
                data = fold_cohort(data, cfg.cv_folds, cfg.cv_fold,
                                   cfg.subsample_seed)
                console("    patient folds: training on %d/%d samples "
                        "(held-out fold %d/%d, seed=%d)"
                        % (data.expr.shape[0], n_before, cfg.cv_fold,
                           cfg.cv_folds, cfg.subsample_seed))
            elif cfg.patient_subsample:
                n_before = data.expr.shape[0]
                data = subsample_patients(data, cfg.patient_subsample,
                                          cfg.subsample_seed)
                console("    patient subsample: kept %d/%d samples "
                        "(fraction=%.3f, seed=%d)"
                        % (data.expr.shape[0], n_before,
                           cfg.patient_subsample, cfg.subsample_seed))
            if cfg.edge_partition != "off":
                # Streamed restrict_network + edges_to_indices with a
                # src-index range filter: this rank reads only the edges
                # of its OWNED gene range [ep_lo, ep_hi) — identical to
                # the in-memory path's arrays restricted to that range
                # (io/readers.load_network_range order contract).
                from g2vec_tpu.io.readers import load_network_range
                from g2vec_tpu.parallel.shard import edge_range

                common = find_common_genes(net_genes, data.gene)
                data = restrict_data(data, common)
                gene2idx = make_gene2idx(data.gene)
                ep_rank = jax.process_index() if cfg.distributed else 0
                ep_ranks = jax.process_count() if cfg.distributed else 1
                ep_lo, ep_hi = edge_range(ep_rank, ep_ranks, len(common))
                src, dst = load_network_range(cfg.network_file, gene2idx,
                                              ep_lo, ep_hi)
            else:
                common = find_common_genes(network.genes, data.gene)
                network = restrict_network(network, common)
                data = restrict_data(data, common)
                gene2idx = make_gene2idx(data.gene)
                src, dst = edges_to_indices(network, gene2idx)
        _stage_edge("preprocess")
        n_samples, n_genes = data.expr.shape
        n_edges = len(src)
        console("    n_samples: %d" % n_samples)
        console("    n_genes  : %d\t(common genes in both EXPRESSION and NETWORK)" % n_genes)
        console("    n_edges  : %d\t(%s)" % (
            n_edges,
            "edges of this rank's owned gene range"
            if cfg.edge_partition != "off" else "edges with the common genes"))
        metrics.emit("preprocess", n_samples=n_samples, n_genes=n_genes, n_edges=n_edges)

        console(">>> 3. Generate random paths from each group")
        console("    *** most time consuming step ***")
        key = jax.random.key(cfg.seed)
        if cfg.distributed and cfg.mesh_shape:
            from g2vec_tpu.parallel.distributed import (cpu_fleet,
                                                        make_global_mesh)

            if cpu_fleet():
                # The CPU backend cannot compile cross-process XLA, so a
                # CPU fleet runs its device stages REPLICATED on a
                # process-local mesh (deterministic: every rank lands on
                # identical state) and divides only the host-side walk
                # work across ranks (sharded_native_path_set). The local
                # mesh is the global plan folded onto this rank's devices.
                local = fleet.plan_mesh(len(jax.local_devices()),
                                        prefer_model=cfg.mesh_shape[1])
                console(f"    [fleet] cpu backend: replicated local mesh "
                        f"{local[0]}x{local[1]} per rank "
                        f"(global plan {cfg.mesh_shape})")
                mesh_ctx = make_mesh_context(local,
                                             devices=jax.local_devices())
            else:
                mesh_ctx = make_global_mesh(cfg.mesh_shape)
        else:
            mesh_ctx = make_mesh_context(cfg.mesh_shape)
        # "auto" = host-walks-chip-trains: the walk step is CPU-shaped
        # (pointer-chase, no matmul), the trainer is MXU-shaped — measured
        # basis and resolution rules in ops/backend.py.
        from g2vec_tpu.cache import NATIVE_FAMILY, walk_cache_key
        from g2vec_tpu.ops.backend import resolve_walker_backend
        from g2vec_tpu.ops.host_walker import resolve_sampler_threads
        from g2vec_tpu.parallel.overlap import OverlapScheduler

        walker_backend = resolve_walker_backend(cfg)
        sampler_threads = (resolve_sampler_threads(cfg.sampler_threads)
                           if walker_backend == "native" else 0)
        # Overlap is single-process only: the collectives in a distributed
        # stage 3 must stay on the main thread in program order on every
        # rank, or ranks deadlock on mismatched gather sequences.
        use_overlap = cfg.overlap and not cfg.distributed
        overlap = OverlapScheduler(max_workers=4)
        if walker_backend == "native":
            console(f"    [sampler] native C++ CSR sampler, "
                    f"{sampler_threads} host thread(s)"
                    + (", groups overlapped" if use_overlap else ""))
        if use_overlap:
            # The device sits idle while the host walks: warm stage 5's
            # k-means program now so its multi-second compile hides under
            # stage 3 instead of extending stage 5 (wall only — results
            # are a pure jit-cache hit on identical shapes/statics).
            from g2vec_tpu.analysis import warm_lgroups_compile

            overlap.submit("warm_lgroups", _background_warm(
                lambda: warm_lgroups_compile(
                    n_genes, cfg.sizeHiddenlayer, k=cfg.n_lgroups,
                    iters=cfg.kmeans_iters), console))
        walk_cache_hits: List[str] = []
        shard_ctx = None
        if cfg.train_mode == "streaming":
            # ---- streaming minibatch trainer: stages 3-4 merged ----
            # (train/stream.py): the sampler pool emits walk shards into
            # a bounded host ring while the jitted minibatch-SGD step
            # consumes them — training starts the moment shard 0 lands,
            # and host path memory peaks at O(shard x ring depth) instead
            # of O(total paths). Statistical contract vs full-batch
            # (val-ACC parity band + biomarker overlap, ARCHITECTURE.md
            # §12); bitwise-deterministic WITHIN the mode at any thread
            # count / ring depth.
            # Both production samplers stream: the native C++ pool and
            # the bit-exact device walker emit byte-identical shard rows
            # over the same walker-index ranges (ops/device_walker.py
            # parity contract), so the trainer's shard sequence — and
            # its outputs — are the same bytes either way.
            if walker_backend not in ("native", "device"):
                raise ValueError(
                    "--train-mode streaming needs a shard-emitting "
                    "sampler (native or device); this host resolved "
                    f"walker_backend={walker_backend!r}")
            from g2vec_tpu.parallel.shard import make_shard_context
            from g2vec_tpu.train.stream import (EVAL_ROWS_CAP,
                                                train_cbow_streaming)

            # Million-node scale-out (ROADMAP item 2): the shard context
            # binds this process's rank to the partitioning arithmetic;
            # None when both --graph-shards/--embed-shards are off, and a
            # single-rank context routes every consumer through the plain
            # unsharded programs (byte-identity).
            shard_ctx = make_shard_context(
                cfg.graph_shards, cfg.embed_shards, n_genes,
                deadline=(cfg.fleet_watchdog_deadline or None))
            if shard_ctx is not None:
                console(f"    [shard] rank {shard_ctx.spec.rank}/"
                        f"{shard_ctx.spec.n_ranks}: graph_shards="
                        f"{cfg.graph_shards} embed_shards="
                        f"{cfg.embed_shards} gene range "
                        f"[{shard_ctx.spec.lo}, {shard_ctx.spec.hi})")

            fault_point("paths")
            fleet.note_phase("paths")
            with timer.stage("paths"):
                group_edges = []
                for i in range(2):
                    expr_group = data.expr[data.label == i]
                    s_k, d_k, w_k = thresholded_edges(
                        expr_group, src, dst, threshold=cfg.pcc_threshold)
                    group_edges.append((np.asarray(s_k), np.asarray(d_k),
                                        np.asarray(w_k)))
            edge_ctx = None
            if cfg.edge_partition != "off":
                # Owned-range CSRs from the range-filtered thresholded
                # edges; halo mode then replicates the 1-hop boundary
                # rows in a main-thread collective per group. At one
                # rank the range is the whole graph and the trainer
                # routes through the plain unsharded code paths (PR 10
                # byte-identity convention), so edge_ctx stays None.
                from g2vec_tpu.parallel.shard import (EdgeContext,
                                                      EdgeWalkStats,
                                                      build_halo_csr,
                                                      build_partitioned_csr)

                if ep_ranks > 1 and (shard_ctx is None
                                     or not shard_ctx.spec.graph_shards):
                    raise ValueError(
                        "multi-rank --edge-partition needs --graph-shards "
                        "(the shard exchange distributes finished rows)")
                pcsrs = []
                for gi, (s_k, d_k, w_k) in enumerate(group_edges):
                    p = build_partitioned_csr(s_k, d_k, w_k, n_genes,
                                              ep_lo, ep_hi)
                    if cfg.edge_partition == "halo" and ep_ranks > 1:
                        p = build_halo_csr(
                            p, rank=ep_rank, n_ranks=ep_ranks, group=gi,
                            deadline=(cfg.fleet_watchdog_deadline or None))
                    pcsrs.append(p)
                csr_bytes = sum(p.csr_bytes for p in pcsrs)
                owned_edges = sum(p.owned_edges for p in pcsrs)
                halo_edges = sum(p.halo_edges for p in pcsrs)
                console(f"    [edge] {cfg.edge_partition}: rank {ep_rank}/"
                        f"{ep_ranks} owns genes [{ep_lo}, {ep_hi}) — "
                        f"{owned_edges} owned edges, {csr_bytes} CSR bytes"
                        + (f", {halo_edges} halo edges"
                           if cfg.edge_partition == "halo" else ""))
                metrics.emit("edge_partition", mode=cfg.edge_partition,
                             rank=ep_rank, n_ranks=ep_ranks,
                             gene_lo=ep_lo, gene_hi=ep_hi,
                             owned_edges=owned_edges, csr_bytes=csr_bytes)
                edge_attrib = {
                    "mode": cfg.edge_partition, "rank": ep_rank,
                    "n_ranks": ep_ranks, "gene_lo": ep_lo, "gene_hi": ep_hi,
                    "owned_edges": owned_edges, "csr_bytes": csr_bytes,
                    "halo_edges": halo_edges,
                    "halo_bytes": sum(p.halo_bytes for p in pcsrs)}
                if cfg.edge_partition == "halo":
                    metrics.emit(
                        "halo", halo_edges=halo_edges,
                        halo_bytes=sum(p.halo_bytes for p in pcsrs),
                        halo_genes=sum(len(p.halo_genes) for p in pcsrs),
                        overhead_ratio=(
                            sum(p.halo_bytes for p in pcsrs)
                            / max(1, 8 * owned_edges)))
                if ep_ranks > 1:
                    edge_ctx = EdgeContext(mode=cfg.edge_partition,
                                           pcsrs=pcsrs,
                                           stats=EdgeWalkStats())
            _stage_edge("paths")
            console("    [stream] walk shards stream from the sampler "
                    "pool; stage 4 overlaps stage 3")
            console(">>> 4. Compute distributed representations using "
                    "modified CBOW")
            console("     Start training the modified CBOW with early "
                    "stopping")
            reporter = _EpochReporter(console, cfg.display_step)

            def on_epoch(step, acc_val, acc_tr, secs):
                reporter.on_epoch(step, acc_val, acc_tr, secs)
                metrics.emit("epoch", step=step, acc_val=acc_val,
                             acc_tr=acc_tr, secs=secs)

            fault_point("train")
            fleet.note_phase("train")
            with timer.stage("train"):
                sres = train_cbow_streaming(
                    groups=group_edges, n_genes=n_genes, genes=data.gene,
                    hidden=cfg.sizeHiddenlayer,
                    learning_rate=cfg.learningRate, max_epochs=cfg.epoch,
                    val_fraction=cfg.val_fraction,
                    decision_threshold=cfg.decision_threshold,
                    compute_dtype=cfg.compute_dtype,
                    param_dtype=cfg.param_dtype,
                    seed=(cfg.seed if cfg.train_seed is None
                          else cfg.train_seed),
                    walk_seed=cfg.seed, len_path=cfg.lenPath,
                    reps=cfg.numRepetition, shard_paths=cfg.shard_paths,
                    prefetch_depth=cfg.prefetch_depth,
                    patience=cfg.stream_patience,
                    sampler_threads=cfg.sampler_threads,
                    overlap=overlap,
                    checkpoint_dir=cfg.checkpoint_dir, resume=cfg.resume,
                    checkpoint_every=cfg.checkpoint_every,
                    check=check, lifecycle=lifecycle,
                    on_epoch=on_epoch, console=console,
                    shard_ctx=shard_ctx, walk_starts=cfg.walk_starts,
                    edge_ctx=edge_ctx,
                    eval_rows_cap=(cfg.stream_eval_rows or EVAL_ROWS_CAP),
                    walker_backend=walker_backend,
                    device_feed=cfg.device_feed)
            if edge_ctx is not None:
                st = edge_ctx.stats
                metrics.emit("handoff", mode=edge_ctx.mode,
                             shards=st.shards, rounds=st.rounds,
                             states_sent=st.states_sent,
                             batches=st.batches,
                             peak_in_flight=st.peak_in_flight)
                edge_attrib.update(
                    shards=st.shards, rounds=st.rounds,
                    states_sent=st.states_sent, batches=st.batches,
                    peak_in_flight=st.peak_in_flight)
            _stage_edge("train")
            result = sres.train
            gene_freq = sres.gene_freq
            n_paths = sres.n_paths
            console("    n_paths : %d\t(streamed, %d shard(s))"
                    % (n_paths, sres.stats.n_shards))
            console("    n_genes : %d\t(genes in good or poor random "
                    "paths)" % len(gene_freq))
            console("    [stream] first update %.0f ms in; sampling wall "
                    "%.2f s; ring high-water %d/%d shard(s)"
                    % (sres.stats.time_to_first_update_ms,
                       sres.stats.sampling_wall_s,
                       sres.stats.ring_occupancy_hw, cfg.prefetch_depth))
            metrics.emit("paths", n_paths=n_paths,
                         n_path_genes=len(gene_freq),
                         walker_backend=walker_backend,
                         sampler_threads=sampler_threads,
                         walk_cache_hits=walk_cache_hits)
            metrics.emit("stream", **sres.stats.as_dict())
            if walker_backend == "device":
                wall = sres.stats.sampling_wall_s
                metrics.emit(
                    "device_walk",
                    paths_per_s=(n_paths / wall if wall > 0 else 0.0),
                    h2d_bytes_saved=sres.stats.h2d_bytes_saved,
                    feed_mode=sres.stats.feed_mode)
            timer.annotate("paths",
                           sampling_wall_s=sres.stats.sampling_wall_s,
                           walker_backend=walker_backend,
                           sampler_threads=sampler_threads)
            timer.annotate("train", train_mode="streaming",
                           **sres.stats.as_dict())
            if result.stopped_early:
                reporter.on_stop(result.stop_epoch, result.acc_val,
                                 result.acc_tr)
            console("    Optimization Finish")
            metrics.emit("train_done", stop_epoch=result.stop_epoch,
                         acc_val=result.acc_val, acc_tr=result.acc_tr,
                         stopped_early=result.stopped_early)
        else:
            fault_point("paths")
            fleet.note_phase("paths")
            with timer.stage("paths"):
                path_sets: List = [None, None]
                joins = []
                for i, group in enumerate(["g", "p"]):
                    expr_group = data.expr[data.label == i]
                    # Sparse transitions: per-step walk cost O(W*D) instead of
                    # O(W*G), and no dense G^2 matrix in HBM (ops/graph.py).
                    s_k, d_k, w_k = thresholded_edges(expr_group, src, dst,
                                                      threshold=cfg.pcc_threshold)
                    ckey = None
                    if walk_cache is not None:
                        # Content-addressed: the exact thresholded edges + the
                        # walk params + the sampler's PRNG-family tag. Any
                        # input or config drift misses; a verified hit skips
                        # this group's walks entirely (g2vec_tpu/cache.py).
                        ckey = walk_cache_key(
                            np.asarray(s_k), np.asarray(d_k), np.asarray(w_k),
                            n_genes, len_path=cfg.lenPath,
                            reps=cfg.numRepetition, seed=(cfg.seed << 1) | i,
                            # One family for BOTH backends: the device
                            # sampler's rows are byte-identical to the
                            # native sampler's, so a device run HITS a
                            # host-populated entry and vice versa
                            # (cache.py NATIVE_FAMILY contract).
                            family=NATIVE_FAMILY)
                        cached = walk_cache.load(ckey)
                        if cached is not None:
                            path_sets[i] = cached
                            walk_cache_hits.append(group)
                            console(f"    [cache] group {group!r}: verified "
                                    f"walk artifact hit ({len(cached)} unique "
                                    f"paths) — walks skipped")
                            metrics.emit("walk_cache", group=group,
                                         outcome="hit", n_rows=len(cached))
                            continue
                        metrics.emit("walk_cache", group=group, outcome="miss")
                    if walker_backend == "native":
                        # Threaded C++ CSR sampler (ops/host_walker.py): the
                        # default host path (ops/backend.py has the measured
                        # rationale). Same packed-row contract; its own
                        # deterministic PRNG family (module docstring). In a
                        # multi-process run each host walks its shard of the
                        # walker axis and the packed rows are allgathered —
                        # bit-identical to the single-host set.
                        if cfg.distributed:
                            # Collective; falls back to the plain single-host
                            # call itself when process_count == 1.
                            from g2vec_tpu.parallel.distributed import \
                                sharded_native_path_set

                            path_sets[i] = sharded_native_path_set(
                                np.asarray(s_k), np.asarray(d_k),
                                np.asarray(w_k), n_genes,
                                len_path=cfg.lenPath, reps=cfg.numRepetition,
                                seed=(cfg.seed << 1) | i,
                                n_threads=cfg.sampler_threads)
                            continue
                        from g2vec_tpu.ops.host_walker import \
                            generate_path_set_native

                        def _walk(s=np.asarray(s_k), d=np.asarray(d_k),
                                  w=np.asarray(w_k), i=i, group=group,
                                  ckey=ckey):
                            ps = generate_path_set_native(
                                s, d, w, n_genes, len_path=cfg.lenPath,
                                reps=cfg.numRepetition,
                                seed=(cfg.seed << 1) | i,
                                n_threads=cfg.sampler_threads)
                            if walk_cache is not None and ckey:
                                walk_cache.store(ckey, ps, n_genes,
                                                 meta={"group": group})
                            return ps

                        if use_overlap:
                            # Both groups' walks share the sampler pool; the
                            # second group's ranges interleave with the
                            # first's instead of waiting for its full join.
                            overlap.submit(f"walks_{group}", _walk)
                            joins.append((i, f"walks_{group}"))
                        else:
                            path_sets[i] = _walk()
                        continue
                    # Device backend: the bit-exact CSR device sampler
                    # (ops/device_walker.py) — the SAME splitmix64 walk
                    # as the native branch above, byte for byte, so the
                    # walk-cache key and every downstream golden are
                    # backend-invariant. (The legacy dense/jax.random
                    # walker survives only behind a deprecation shim in
                    # ops/walker.py.)
                    from g2vec_tpu.ops.device_walker import \
                        generate_path_set_device

                    path_sets[i] = generate_path_set_device(
                        np.asarray(s_k), np.asarray(d_k), np.asarray(w_k),
                        n_genes, len_path=cfg.lenPath,
                        reps=cfg.numRepetition, seed=(cfg.seed << 1) | i)
                    if walk_cache is not None and ckey:
                        walk_cache.store(ckey, path_sets[i], n_genes,
                                         meta={"group": group})
                for i, name in joins:
                    # Re-raises a walk task's exception here, inside the
                    # stage — same failure surface as the sequential order.
                    path_sets[i] = overlap.result(name)
                # Paths stay bit-packed from the walker all the way into the
                # trainer — the dense uint8 [n_paths, n_genes] matrix never
                # materializes on the host (8x smaller at any scale).
                paths, labels = integrate_path_sets(path_sets[0], path_sets[1],
                                                    n_genes, packed=True)
                if use_overlap and paths.shape[0] >= 2:
                    # n_paths is known the moment integrate returns: warm the
                    # trainer's chunk program in the background while the
                    # foreground counts gene frequencies and train_cbow
                    # bit-packs the split — train_cbow joins this via its
                    # pre-compile hook, right where it wants the executable.
                    from g2vec_tpu.train.trainer import warm_train_compile

                    n_paths_known = int(paths.shape[0])
                    # The warm must predict the REAL chunk program — the
                    # fused/superstep/donate trainer modes and the autotuner's
                    # tile installs are all part of its cache key, so they ride
                    # along here (a warm that swept the autotune shapes also
                    # spares the foreground the measurement sweep).
                    overlap.submit("warm_trainer", _background_warm(
                        lambda: warm_train_compile(
                            n_paths_known, n_genes, hidden=cfg.sizeHiddenlayer,
                            learning_rate=cfg.learningRate,
                            max_epochs=cfg.epoch,
                            val_fraction=cfg.val_fraction,
                            decision_threshold=cfg.decision_threshold,
                            compute_dtype=cfg.compute_dtype,
                            param_dtype=cfg.param_dtype, mesh_ctx=mesh_ctx,
                            checkpoint_dir=cfg.checkpoint_dir,
                            checkpoint_every=cfg.checkpoint_every,
                            fused_eval=cfg.fused_eval,
                            epoch_superstep=cfg.epoch_superstep,
                            donate=cfg.donate_state,
                            kernel_autotune=cfg.kernel_autotune,
                            autotune_cache_path=autotune_path), console))
                gene_freq = count_gene_freq(paths, labels, data.gene, packed=True)
            _stage_edge("paths")
            n_paths = paths.shape[0]
            if n_paths < 2:
                raise ValueError(
                    "fewer than 2 distinct group-specific paths were generated — "
                    "the |PCC| > %.2f graphs are too sparse for this dataset; try "
                    "lowering --pcc-threshold or raising -r/--numRepetition"
                    % cfg.pcc_threshold)
            console("    n_paths : %d" % n_paths)
            console("    n_genes : %d\t(genes in good or poor random paths)" % len(gene_freq))
            metrics.emit("paths", n_paths=n_paths, n_path_genes=len(gene_freq),
                         walker_backend=walker_backend,
                         sampler_threads=sampler_threads,
                         walk_cache_hits=walk_cache_hits)
            timer.annotate("paths", walker_backend=walker_backend,
                           sampler_threads=sampler_threads,
                           walk_cache_hits=list(walk_cache_hits))

            console(">>> 4. Compute distributed representations using modified CBOW")
            console("     Start training the modified CBOW with early stopping")
            reporter = _EpochReporter(console, cfg.display_step)

            def on_epoch(step, acc_val, acc_tr, secs):
                reporter.on_epoch(step, acc_val, acc_tr, secs)
                metrics.emit("epoch", step=step, acc_val=acc_val, acc_tr=acc_tr, secs=secs)

            fault_point("train")
            fleet.note_phase("train")
            with timer.stage("train"):
                result = train_cbow(
                    paths, labels, packed_genes=n_genes,
                    hidden=cfg.sizeHiddenlayer, learning_rate=cfg.learningRate,
                    max_epochs=cfg.epoch, val_fraction=cfg.val_fraction,
                    decision_threshold=cfg.decision_threshold,
                    compute_dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
                    seed=(cfg.seed if cfg.train_seed is None else cfg.train_seed),
                    mesh_ctx=mesh_ctx, on_epoch=on_epoch,
                    checkpoint_dir=cfg.checkpoint_dir, resume=cfg.resume,
                    checkpoint_every=cfg.checkpoint_every,
                    checkpoint_layout=cfg.checkpoint_layout,
                    fused_eval=cfg.fused_eval,
                    epoch_superstep=cfg.epoch_superstep,
                    donate=cfg.donate_state,
                    kernel_autotune=cfg.kernel_autotune,
                    autotune_cache_path=autotune_path,
                    check=check,
                    # Joins the background chunk-program warm right before the
                    # trainer requests the executable (after the host-side
                    # packing it overlapped); None = compile in line.
                    pre_compile_hook=(
                        (lambda: overlap.result("warm_trainer"))
                        if use_overlap and overlap.has("warm_trainer")
                        else None))
            _stage_edge("train")
            if result.stopped_early:
                reporter.on_stop(result.stop_epoch, result.acc_val, result.acc_tr)
            console("    Optimization Finish")
            metrics.emit("train_done", stop_epoch=result.stop_epoch,
                         acc_val=result.acc_val, acc_tr=result.acc_tr,
                         stopped_early=result.stopped_early)

        console(">>> 5. Find L-groups")
        if use_overlap:
            # Join the k-means warm (long done by now — training ran in
            # between); find_lgroups then hits the compiled program.
            overlap.result("warm_lgroups")
        fault_point("lgroups")
        fleet.note_phase("lgroups")
        # Device residency through stages 5-6: the trainer snapshot's
        # embedding table feeds k-means / t-scores / minmax WITHOUT the
        # former host bounce (np.asarray before the jitted kmeans and
        # back); only the tiny per-cluster vote tallies and the final
        # lgroup/score vectors cross to the host, at the selection/writer
        # boundary. A distributed run's snapshot may be host-gathered
        # already (fetch_global) — result.w_ih is then the same bytes.
        from g2vec_tpu.analysis import find_lgroups_device, freq_index

        import jax.numpy as jnp

        embed_sharded = shard_ctx is not None and shard_ctx.spec.embed_split
        km_centers = None   # stage-5 centers (ANN seed); sharded runs
                            # never materialize them whole
        if embed_sharded:
            # Gene-range-sharded stages 5-6 (ROADMAP item 2): every
            # array below is this rank's [g_local] slice; only
            # per-cluster statistics and masked extrema cross ranks, and
            # the full [G]-shaped score/label vectors exist only at the
            # writer-boundary gathers. The [G, H] table never does.
            spec = shard_ctx.spec
            from g2vec_tpu.analysis import (biomarker_scores_sharded,
                                            find_lgroups_sharded,
                                            top_biomarkers)

            if result.params is not None:
                emb = result.params.w_ih.astype(jnp.float32)[:spec.g_local]
            else:
                emb = result.w_ih
            with timer.stage("lgroups"):
                lgroup_dev = find_lgroups_sharded(
                    emb, freq_index(data.gene, gene_freq)[spec.lo:spec.hi],
                    shard_ctx, key=jax.random.key(cfg.kmeans_seed),
                    k=cfg.n_lgroups,
                    compat_tiebreak=cfg.compat_lgroup_tiebreak,
                    iters=cfg.kmeans_iters)
            _stage_edge("lgroups")

            console(">>> 6. Select biomarkers with gene scores")
            fault_point("biomarkers")
            fleet.note_phase("biomarkers")
            with timer.stage("biomarkers"):
                labels_np = np.asarray(data.label)
                if cfg.permute_seed is not None:
                    # Permutation null: shuffled labels for the prognostic
                    # scoring ONLY — walks/graphs/training above saw the
                    # observed labels (stats/plan.py seed tree).
                    labels_np = permute_labels(labels_np, cfg.permute_seed)
                    console("    permutation null: stage-6 labels shuffled "
                            "(permute_seed=%d)" % cfg.permute_seed)
                expr_local = data.expr[:, spec.lo:spec.hi]
                scores2_local = np.asarray(biomarker_scores_sharded(
                    emb, expr_local[labels_np == 0],
                    expr_local[labels_np == 1], lgroup_dev, shard_ctx,
                    score_mix=cfg.score_mix))
                # Writer-boundary gathers: [2, G] scores + [G] L-groups
                # (small vectors — the selection itself is the solo
                # host logic on every rank, so the result is replicated).
                scores2 = shard_ctx.gather_concat("bm_scores",
                                                  scores2_local, axis=1)
                lgroup_idx = shard_ctx.gather_concat(
                    "lgroups", np.asarray(lgroup_dev), axis=0)
                biomarkers, _ = top_biomarkers(scores2, lgroup_idx,
                                               data.gene, cfg.numBiomarker)
            _stage_edge("biomarkers")
        else:
            if result.params is not None and not cfg.distributed:
                emb = result.params.w_ih.astype(jnp.float32)[:n_genes]
            else:
                emb = result.w_ih
            with timer.stage("lgroups"):
                lgroup_dev, km_centers_dev = find_lgroups_device(
                    emb, freq_index(data.gene, gene_freq),
                    key=jax.random.key(cfg.kmeans_seed), k=cfg.n_lgroups,
                    compat_tiebreak=cfg.compat_lgroup_tiebreak,
                    iters=cfg.kmeans_iters, return_centers=True)
                km_centers = np.asarray(km_centers_dev,
                                        dtype=np.float32)
            _stage_edge("lgroups")

            console(">>> 6. Select biomarkers with gene scores")
            fault_point("biomarkers")
            fleet.note_phase("biomarkers")
            with timer.stage("biomarkers"):
                scoring_label = data.label
                if cfg.permute_seed is not None:
                    # Permutation null: shuffled labels for the prognostic
                    # scoring ONLY — walks/graphs/training above saw the
                    # observed labels (stats/plan.py seed tree).
                    scoring_label = permute_labels(data.label,
                                                   cfg.permute_seed)
                    console("    permutation null: stage-6 labels shuffled "
                            "(permute_seed=%d)" % cfg.permute_seed)
                # select_biomarkers split open so the full [2, G] score
                # stack survives to the result (the query plane's
                # topk_biomarkers bundle vector) — identical arithmetic,
                # same two calls select_biomarkers makes internally.
                scoring_label = np.asarray(scoring_label)
                scores2 = np.asarray(biomarker_scores_device(
                    emb, data.expr[scoring_label == 0],
                    data.expr[scoring_label == 1], lgroup_dev,
                    cfg.score_mix))
                lgroup_idx = np.asarray(lgroup_dev)   # writer-boundary copy
                biomarkers, _ = top_biomarkers(scores2, lgroup_idx,
                                               data.gene, cfg.numBiomarker)
            _stage_edge("biomarkers")

        console(">>> 7. Save results")
        write_outputs = True
        if cfg.distributed or (shard_ctx is not None
                               and not shard_ctx.single):
            from g2vec_tpu.parallel.distributed import is_coordinator

            write_outputs = is_coordinator()
        fault_point("save")
        fleet.note_phase("save")
        with timer.stage("save"):
            outputs = []
            if embed_sharded:
                # The vectors write is COLLECTIVE (rank-by-rank slice
                # publish — io/writers.py); biomarkers/lgroups are
                # replicated and written by the coordinator alone.
                from g2vec_tpu.io.writers import write_vectors_sharded

                vec_path = write_vectors_sharded(
                    cfg.result_name, result.w_ih, data.gene, shard_ctx)
                if write_outputs:
                    outputs = [
                        write_biomarkers(cfg.result_name, biomarkers),
                        write_lgroups(cfg.result_name, lgroup_idx,
                                      data.gene),
                        vec_path,
                    ]
            elif write_outputs:
                outputs = [
                    write_biomarkers(cfg.result_name, biomarkers),
                    write_lgroups(cfg.result_name, lgroup_idx, data.gene),
                    write_vectors(cfg.result_name, result.w_ih, data.gene),
                ]
            if cfg.emit_inventory and write_outputs:
                if embed_sharded:
                    # The embedding never exists whole on one rank in
                    # sharded mode — the bundle would defeat the cap.
                    console("    --emit-inventory skipped: embedding is "
                            "gene-range sharded")
                else:
                    from g2vec_tpu.io.writers import write_inventory_bundle

                    bundle_root = cfg.result_name + "_inventory"
                    gen_dir = write_inventory_bundle(
                        bundle_root,
                        np.asarray(result.w_ih, dtype=np.float32),
                        list(data.gene), scores2,
                        {"source": "solo",
                         "result_name": os.path.basename(cfg.result_name)},
                        ann_nlist=cfg.ann_nlist,
                        seed_centroids=km_centers)
                    console("    %s" % gen_dir)
                    metrics.emit(
                        "inventory", bundle=os.path.basename(bundle_root),
                        bytes=sum(
                            os.path.getsize(os.path.join(gen_dir, f))
                            for f in os.listdir(gen_dir)),
                        outcome="published")
                    with open(os.path.join(gen_dir, "meta.json")) as mf:
                        ann_meta = json.load(mf).get("ann")
                    if ann_meta:
                        metrics.emit(
                            "ann_build",
                            bundle=os.path.basename(bundle_root),
                            nlist=ann_meta.get("nlist"), outcome="built",
                            ms=ann_meta.get("build_ms"),
                            seeded=ann_meta.get("seeded"),
                            postings=n_genes)
                    else:
                        metrics.emit(
                            "ann_build",
                            bundle=os.path.basename(bundle_root),
                            nlist=0, outcome="skipped")
        _stage_edge("save")
        for path in outputs:
            console("    %s" % path)
        overlap_saved = overlap.saved_seconds() if use_overlap else {}
        if overlap_saved:
            console("    [overlap] background time hidden under foreground "
                    "stages: " + ", ".join(
                        f"{k}={v:.2f}s"
                        for k, v in sorted(overlap_saved.items())))
        metrics.emit("done", outputs=outputs, stage_seconds=timer.as_dict(),
                     stage_extras=timer.extras_dict(),
                     walker_backend=walker_backend,
                     sampler_threads=sampler_threads,
                     overlap_saved_s=overlap_saved,
                     walk_cache_hits=walk_cache_hits)

        return PipelineResult(
            genes=data.gene, embeddings=result.w_ih, lgroup_idx=lgroup_idx,
            biomarkers=biomarkers, output_files=outputs,
            n_samples=n_samples, n_genes=n_genes, n_edges=n_edges,
            n_paths=n_paths, n_path_genes=len(gene_freq),
            train_history=result.history, acc_val=result.acc_val,
            stage_seconds=timer.as_dict(), walker_backend=walker_backend,
            sampler_threads=sampler_threads, overlap_saved_s=overlap_saved,
            walk_cache_hits=walk_cache_hits,
            stream_stats=(sres.stats.as_dict()
                          if cfg.train_mode == "streaming" else {}),
            edge_stats=edge_attrib, biomarker_scores=scores2,
            km_centers=km_centers)
    finally:
        if overlap is not None:
            # Drain, never raise: the exception in flight (if any) is the
            # one the caller must see; background task errors were either
            # already re-raised at a join or are warm-task noise.
            overlap.close()
        fleet.stop_heartbeat()
        if cfg.profile_dir:
            jax.profiler.stop_trace()
        metrics.close()
