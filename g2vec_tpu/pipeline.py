"""The seven-stage pipeline orchestrator.

Drives L0-L6 in the reference's order (ref: main, G2Vec.py:11-120) and
reproduces its console transcript (the only golden spec the reference
publishes, README.md:21-49): stage banners ``>>> N. ...``, the indented
preprocessing stats, the epoch log cadence, and the saved-file listing —
while running stages 3-5 on device (adjacency, walks, trainer, k-means all
jit-compiled JAX).

Differences from the reference, all deliberate (SURVEY.md §7):
- seeded end to end (the reference is unseeded);
- ``--epoch`` is honored (the reference hardcodes 500, G2Vec.py:262);
- structured JSONL metrics / profiler traces / checkpoints behind flags;
- stage 3 walks all sources in lockstep on device instead of one Python
  walker at a time (ops/walker.py docstring has the mapping).
"""
from __future__ import annotations

import dataclasses
from typing import Callable, Dict, List, Optional

import numpy as np

from g2vec_tpu.config import G2VecConfig
from g2vec_tpu.resilience.faults import fault_point, install_plan


@dataclasses.dataclass
class PipelineResult:
    genes: np.ndarray            # [G] str — global sorted-intersection order
    embeddings: np.ndarray       # [G, hidden] float32
    lgroup_idx: np.ndarray       # [G] int32 in {0 good, 1 poor, 2 other}
    biomarkers: List[str]
    output_files: List[str]
    n_samples: int = 0
    n_genes: int = 0
    n_edges: int = 0
    n_paths: int = 0
    n_path_genes: int = 0
    train_history: List[dict] = dataclasses.field(default_factory=list)
    acc_val: float = 0.0
    stage_seconds: Dict[str, float] = dataclasses.field(default_factory=dict)
    walker_backend: str = ""     # the RESOLVED stage-3 sampler ("device" |
                                 # "native") — what actually ran, not the
                                 # config value (which may be "auto")


class _EpochReporter:
    """Reproduces the reference's epoch log cadence (ref: G2Vec.py:269-278).

    A line is printed whenever ``step % display_step == 0``, showing the wall
    time accumulated since the previous printed line; on early stop the
    ``Epoch(stop)`` line reports the PREVIOUS epoch's accuracies.
    """

    def __init__(self, console: Callable[[str], None], display_step: int):
        self.console = console
        self.display_step = display_step
        self.block_secs = 0.0

    def on_epoch(self, step: int, acc_val: float, acc_tr: float, secs: float) -> None:
        self.block_secs += secs
        if step % self.display_step == 0:
            self.console("    - Epoch: %03d\tACC[val]=%.4f\tACC[tr]=%.4f (%.3f sec)"
                         % (step, acc_val, acc_tr, self.block_secs))
            self.block_secs = 0.0

    def on_stop(self, stop_epoch: int, acc_val: float, acc_tr: float) -> None:
        self.console("    - Epoch(stop): %03d\tACC[val]=%.4f\tACC[tr]=%.4f (%.3f sec)"
                     % (stop_epoch, acc_val, acc_tr, self.block_secs))


def run(cfg: G2VecConfig, console: Callable[[str], None] = print) -> PipelineResult:
    """Execute the full pipeline; returns all artifacts plus run stats."""
    # Deferred imports: jax must not be pulled in before the CLI has had the
    # chance to set platform env vars (see __main__.py).
    import jax

    from g2vec_tpu.analysis import find_lgroups, select_biomarkers
    from g2vec_tpu.io.readers import load_clinical, load_expression, load_network
    from g2vec_tpu.io.writers import write_biomarkers, write_lgroups, write_vectors
    from g2vec_tpu.ops.graph import neighbor_table, thresholded_edges
    from g2vec_tpu.ops.walker import (count_gene_freq, generate_path_set,
                                      integrate_path_sets)
    from g2vec_tpu.parallel.mesh import make_mesh_context
    from g2vec_tpu.preprocess import (edges_to_indices, find_common_genes,
                                      make_gene2idx, match_labels,
                                      restrict_data, restrict_network)
    from g2vec_tpu.train.trainer import train_cbow
    from g2vec_tpu.utils.metrics import MetricsWriter
    from g2vec_tpu.utils.timing import StageTimer

    cfg.validate()
    if cfg.fault_plan:
        # Config-driven fault injection (tests/chaos drills); the env-var
        # form needs no install. Re-installing on a supervised retry keeps
        # already-fired once-only entries fired.
        install_plan(cfg.fault_plan)
    if cfg.distributed:
        # Idempotent when __main__ already joined; after a runtime
        # teardown (distributed.shutdown) an in-process supervisor restart
        # re-initializes here.
        from g2vec_tpu.parallel.distributed import initialize

        initialize(cfg.coordinator, cfg.process_id, cfg.num_processes)
    from g2vec_tpu.resilience import fleet

    fleet.configure(liveness_dir=cfg.fleet_liveness_dir,
                    heartbeat_interval=cfg.fleet_heartbeat_interval,
                    watchdog_deadline=cfg.fleet_watchdog_deadline,
                    straggler_factor=cfg.fleet_straggler_factor)
    if cfg.debug_nans:
        jax.config.update("jax_debug_nans", True)
    if cfg.compilation_cache:
        # Persistent XLA cache: a warm repeat run skips the compiles that
        # dominate a cold pipeline's wall (the TPU acceptance run spends
        # most of its train/lgroups/biomarkers stage time compiling).
        jax.config.update("jax_compilation_cache_dir", cfg.compilation_cache)
        # Persist every program: a pipeline run compiles a bounded set of
        # programs, so cache-write cost is trivial next to ANY compile.
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    if cfg.distributed:
        # Worker processes compute shards but neither narrate nor write:
        # transcript, metrics stream, profiler trace, and the three outputs
        # all belong to the coordinator (checkpoint writes are gated inside
        # save_state, which every process must still enter — it gathers
        # cross-process shards collectively).
        from g2vec_tpu.parallel.distributed import is_coordinator

        if jax.process_count() > 1 and not cfg.mesh_shape:
            raise ValueError(
                f"--distributed with {jax.process_count()} processes needs "
                "--mesh (e.g. --mesh 8x1); without it every process would "
                "redundantly train the full model on one local device")
        if not is_coordinator():
            console = lambda s: None  # noqa: E731
            cfg = dataclasses.replace(cfg, metrics_jsonl=None,
                                      profile_dir=None)

    timer = StageTimer()
    # A resumed run APPENDS: its records continue the interrupted attempt's
    # stream (and the supervisor's retry/resume events in between survive).
    metrics = MetricsWriter(cfg.metrics_jsonl, append=cfg.resume)
    if cfg.distributed:
        # Structured init-outcome records (e.g. single_process_fallback —
        # the misconfigured-fleet hazard whose only other symptom is one
        # stderr line) land in the stream ahead of the run's own records.
        from g2vec_tpu.parallel.distributed import drain_pending_events

        for ev in drain_pending_events():
            metrics.emit(ev.pop("event"), **ev)
    # Liveness beacon + per-stage fleet barriers (no-ops unless --fleet-*
    # flags enable them; see resilience/fleet.py).
    fleet.start_heartbeat(metrics)

    def _stage_edge(name: str) -> None:
        # Post-stage fleet barrier + straggler check: a rank that died
        # mid-stage surfaces here as PeerTimeoutError naming it, at the
        # stage edge, instead of wedging an arbitrary later collective.
        if cfg.distributed:
            fleet.stage_barrier(name, timer.as_dict().get(name, 0.0),
                                metrics, console)

    if cfg.profile_dir:
        jax.profiler.start_trace(cfg.profile_dir)

    try:
        console(">>> 0. Arguments")
        console(str(cfg))
        metrics.emit("config", **{f.name: str(getattr(cfg, f.name))
                                  for f in dataclasses.fields(cfg)})

        console(">>> 1. Load data")
        fault_point("load")
        fleet.note_phase("load")
        with timer.stage("load"):
            data = load_expression(cfg.expression_file, use_native=cfg.use_native_io)
            clinical = load_clinical(cfg.clinical_file)
            network = load_network(cfg.network_file)
        _stage_edge("load")

        console(">>> 2. Preprocess data")
        fault_point("preprocess")
        fleet.note_phase("preprocess")
        with timer.stage("preprocess"):
            data.label = match_labels(clinical, data.sample)
            common = find_common_genes(network.genes, data.gene)
            network = restrict_network(network, common)
            data = restrict_data(data, common)
            gene2idx = make_gene2idx(data.gene)
            src, dst = edges_to_indices(network, gene2idx)
        _stage_edge("preprocess")
        n_samples, n_genes = data.expr.shape
        n_edges = len(network.edges)
        console("    n_samples: %d" % n_samples)
        console("    n_genes  : %d\t(common genes in both EXPRESSION and NETWORK)" % n_genes)
        console("    n_edges  : %d\t(edges with the common genes)" % n_edges)
        metrics.emit("preprocess", n_samples=n_samples, n_genes=n_genes, n_edges=n_edges)

        console(">>> 3. Generate random paths from each group")
        console("    *** most time consuming step ***")
        key = jax.random.key(cfg.seed)
        if cfg.distributed and cfg.mesh_shape:
            from g2vec_tpu.parallel.distributed import (cpu_fleet,
                                                        make_global_mesh)

            if cpu_fleet():
                # The CPU backend cannot compile cross-process XLA, so a
                # CPU fleet runs its device stages REPLICATED on a
                # process-local mesh (deterministic: every rank lands on
                # identical state) and divides only the host-side walk
                # work across ranks (sharded_native_path_set). The local
                # mesh is the global plan folded onto this rank's devices.
                local = fleet.plan_mesh(len(jax.local_devices()),
                                        prefer_model=cfg.mesh_shape[1])
                console(f"    [fleet] cpu backend: replicated local mesh "
                        f"{local[0]}x{local[1]} per rank "
                        f"(global plan {cfg.mesh_shape})")
                mesh_ctx = make_mesh_context(local,
                                             devices=jax.local_devices())
            else:
                mesh_ctx = make_global_mesh(cfg.mesh_shape)
        else:
            mesh_ctx = make_mesh_context(cfg.mesh_shape)
        # "auto" = host-walks-chip-trains: the walk step is CPU-shaped
        # (pointer-chase, no matmul), the trainer is MXU-shaped — measured
        # basis and resolution rules in ops/backend.py.
        from g2vec_tpu.ops.backend import resolve_walker_backend

        walker_backend = resolve_walker_backend(cfg)
        path_sets = []
        fault_point("paths")
        fleet.note_phase("paths")
        with timer.stage("paths"):
            for i, group in enumerate(["g", "p"]):
                expr_group = data.expr[data.label == i]
                # Sparse transitions: per-step walk cost O(W*D) instead of
                # O(W*G), and no dense G^2 matrix in HBM (ops/graph.py).
                s_k, d_k, w_k = thresholded_edges(expr_group, src, dst,
                                                  threshold=cfg.pcc_threshold)
                if walker_backend == "native":
                    # Threaded C++ CSR sampler (ops/host_walker.py): the
                    # default host path (ops/backend.py has the measured
                    # rationale). Same packed-row contract; its own
                    # deterministic PRNG family (module docstring). In a
                    # multi-process run each host walks its shard of the
                    # walker axis and the packed rows are allgathered —
                    # bit-identical to the single-host set.
                    if cfg.distributed:
                        # Collective; falls back to the plain single-host
                        # call itself when process_count == 1.
                        from g2vec_tpu.parallel.distributed import \
                            sharded_native_path_set

                        path_sets.append(sharded_native_path_set(
                            np.asarray(s_k), np.asarray(d_k),
                            np.asarray(w_k), n_genes,
                            len_path=cfg.lenPath, reps=cfg.numRepetition,
                            seed=(cfg.seed << 1) | i))
                        continue
                    from g2vec_tpu.ops.host_walker import \
                        generate_path_set_native

                    path_sets.append(generate_path_set_native(
                        s_k, d_k, w_k, n_genes, len_path=cfg.lenPath,
                        reps=cfg.numRepetition,
                        seed=(cfg.seed << 1) | i))
                    continue
                table = neighbor_table(s_k, d_k, w_k, n_genes)
                path_sets.append(generate_path_set(
                    table, jax.random.fold_in(key, i), len_path=cfg.lenPath,
                    reps=cfg.numRepetition, walker_batch=cfg.walker_batch,
                    walker_hbm_budget=cfg.walker_hbm_budget,
                    mesh_ctx=mesh_ctx))
            # Paths stay bit-packed from the walker all the way into the
            # trainer — the dense uint8 [n_paths, n_genes] matrix never
            # materializes on the host (8x smaller at any scale).
            paths, labels = integrate_path_sets(path_sets[0], path_sets[1],
                                                n_genes, packed=True)
            gene_freq = count_gene_freq(paths, labels, data.gene, packed=True)
        _stage_edge("paths")
        n_paths = paths.shape[0]
        if n_paths < 2:
            raise ValueError(
                "fewer than 2 distinct group-specific paths were generated — "
                "the |PCC| > %.2f graphs are too sparse for this dataset; try "
                "lowering --pcc-threshold or raising -r/--numRepetition"
                % cfg.pcc_threshold)
        console("    n_paths : %d" % n_paths)
        console("    n_genes : %d\t(genes in good or poor random paths)" % len(gene_freq))
        metrics.emit("paths", n_paths=n_paths, n_path_genes=len(gene_freq),
                     walker_backend=walker_backend)

        console(">>> 4. Compute distributed representations using modified CBOW")
        console("     Start training the modified CBOW with early stopping")
        reporter = _EpochReporter(console, cfg.display_step)

        def on_epoch(step, acc_val, acc_tr, secs):
            reporter.on_epoch(step, acc_val, acc_tr, secs)
            metrics.emit("epoch", step=step, acc_val=acc_val, acc_tr=acc_tr, secs=secs)

        fault_point("train")
        fleet.note_phase("train")
        with timer.stage("train"):
            result = train_cbow(
                paths, labels, packed_genes=n_genes,
                hidden=cfg.sizeHiddenlayer, learning_rate=cfg.learningRate,
                max_epochs=cfg.epoch, val_fraction=cfg.val_fraction,
                decision_threshold=cfg.decision_threshold,
                compute_dtype=cfg.compute_dtype, param_dtype=cfg.param_dtype,
                seed=cfg.seed, mesh_ctx=mesh_ctx, on_epoch=on_epoch,
                checkpoint_dir=cfg.checkpoint_dir, resume=cfg.resume,
                checkpoint_every=cfg.checkpoint_every,
                checkpoint_layout=cfg.checkpoint_layout)
        _stage_edge("train")
        if result.stopped_early:
            reporter.on_stop(result.stop_epoch, result.acc_val, result.acc_tr)
        console("    Optimization Finish")
        metrics.emit("train_done", stop_epoch=result.stop_epoch,
                     acc_val=result.acc_val, acc_tr=result.acc_tr,
                     stopped_early=result.stopped_early)

        console(">>> 5. Find L-groups")
        fault_point("lgroups")
        fleet.note_phase("lgroups")
        with timer.stage("lgroups"):
            lgroup_idx = find_lgroups(
                result.w_ih, data.gene, gene_freq,
                key=jax.random.key(cfg.kmeans_seed), k=cfg.n_lgroups,
                compat_tiebreak=cfg.compat_lgroup_tiebreak, iters=cfg.kmeans_iters)
        _stage_edge("lgroups")

        console(">>> 6. Select biomarkers with gene scores")
        fault_point("biomarkers")
        fleet.note_phase("biomarkers")
        with timer.stage("biomarkers"):
            biomarkers, _ = select_biomarkers(
                result.w_ih, data.expr, data.label, data.gene, lgroup_idx,
                cfg.numBiomarker, score_mix=cfg.score_mix)
        _stage_edge("biomarkers")

        console(">>> 7. Save results")
        write_outputs = True
        if cfg.distributed:
            from g2vec_tpu.parallel.distributed import is_coordinator

            write_outputs = is_coordinator()
        fault_point("save")
        fleet.note_phase("save")
        with timer.stage("save"):
            outputs = []
            if write_outputs:
                outputs = [
                    write_biomarkers(cfg.result_name, biomarkers),
                    write_lgroups(cfg.result_name, lgroup_idx, data.gene),
                    write_vectors(cfg.result_name, result.w_ih, data.gene),
                ]
        _stage_edge("save")
        for path in outputs:
            console("    %s" % path)
        metrics.emit("done", outputs=outputs, stage_seconds=timer.as_dict())

        return PipelineResult(
            genes=data.gene, embeddings=result.w_ih, lgroup_idx=lgroup_idx,
            biomarkers=biomarkers, output_files=outputs,
            n_samples=n_samples, n_genes=n_genes, n_edges=n_edges,
            n_paths=n_paths, n_path_genes=len(gene_freq),
            train_history=result.history, acc_val=result.acc_val,
            stage_seconds=timer.as_dict(), walker_backend=walker_backend)
    finally:
        fleet.stop_heartbeat()
        if cfg.profile_dir:
            jax.profiler.stop_trace()
        metrics.close()
