"""Dataset utilities: synthetic example generation (the bundled
``ex_EXPRESSION.txt`` is absent from the reference mount)."""
from g2vec_tpu.data.synthetic import SyntheticSpec, make_synthetic, write_synthetic_tsv  # noqa: F401
