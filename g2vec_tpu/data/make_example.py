"""CLI: write a synthetic reference-format dataset.

``python -m g2vec_tpu.data.make_example OUT_DIR [--scale small|medium|example]``

The reference bundles an example dataset whose expression matrix is absent
from this mount (SURVEY.md §0); this generates statistically similar
stand-ins. ``--scale example`` approximates the bundled example's shape
(135 samples, ~7.5k genes, planted co-expression modules so |PCC| > 0.5
edges and separable paths exist); ``small`` is a seconds-fast smoke size.
"""
from __future__ import annotations

import argparse

from g2vec_tpu.data.synthetic import SyntheticSpec, write_synthetic_tsv

SCALES = {
    # seconds-fast smoke size
    "small": SyntheticSpec(),
    # ~0.9 val-ACC achievable in well under a minute on CPU; used by the
    # acceptance test (tests/test_acceptance.py)
    "medium": SyntheticSpec(
        n_good=77, n_poor=58, module_size=100, shared_module_size=16,
        n_background=700, n_expr_only=20, n_net_only=20, module_chords=6,
        background_edges=2000, noise=0.25, seed=0),
    # matched to the reference's bundled-example statistics (README.md:26-32):
    # 135 samples (77/58 labels), ~7.5k common genes, ~3.7k genes reachable
    # by walks, tens of thousands of group-specific paths at -p 80 -r 10
    "example": SyntheticSpec(
        n_good=77, n_poor=58, module_size=1700, shared_module_size=150,
        n_background=2300, n_expr_only=80, n_net_only=80,
        module_chords=6, background_edges=20000, noise=0.25, seed=0),
}


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m g2vec_tpu.data.make_example")
    parser.add_argument("out_dir")
    parser.add_argument("--scale", choices=sorted(SCALES), default="small")
    parser.add_argument("--prefix", default="syn")
    parser.add_argument("--seed", type=int, default=None)
    args = parser.parse_args(argv)
    spec = SCALES[args.scale]
    if args.seed is not None:
        import dataclasses

        spec = dataclasses.replace(spec, seed=args.seed)
    paths = write_synthetic_tsv(spec, args.out_dir, prefix=args.prefix)
    for name, path in paths.items():
        print(f"{name}: {path}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
