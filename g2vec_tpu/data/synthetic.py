"""Synthetic dataset generator with planted prognostic structure.

The reference ships an example dataset (``ex_EXPRESSION.txt`` /
``ex_CLINICAL.txt`` / ``ex_NETWORK.txt``, ref: README.md:21-28) but the
expression matrix is stripped from this mount (.MISSING_LARGE_BLOBS). This
module generates statistically similar stand-ins at any scale:

- Three planted gene modules:
  * ``Mg`` — co-expressed ONLY in good-prognosis samples (so the good-group
    |PCC|>0.5 graph contains its edges) and differentially expressed between
    groups (so t-scores light up).
  * ``Mp`` — symmetric for the poor group.
  * ``Ms`` — co-expressed in BOTH groups: its edges appear in both graphs, so
    identical walk gene-sets arise in both path sets and exercise the
    common-path drop (ref: G2Vec.py:313-315).
- Background genes with iid noise; background edges get |PCC| ~ 0 and are
  dropped by the threshold.
- Extra expression-only genes and network-only genes exercise the
  intersection logic (ref: G2Vec.py:420-426).

Random walks over a group's graph stay inside that group's modules, so path
multi-hot vectors are (nearly) linearly separable by group — the modified
CBOW reaches high validation accuracy, mirroring the real example's 0.88+
trajectory (ref: README.md:35-41).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Tuple

import numpy as np

from g2vec_tpu.io.readers import ExpressionData, NetworkData


@dataclasses.dataclass
class SyntheticSpec:
    n_good: int = 40            # good-prognosis samples (label 0)
    n_poor: int = 30            # poor-prognosis samples (label 1)
    module_size: int = 24       # genes per group-specific planted module
    shared_module_size: int | None = None  # Ms size; None = module_size.
    # Keep Ms small relative to Mg/Mp for high-accuracy datasets: walks
    # through the shared module occur in BOTH group graphs with near-equal
    # gene support, so they are label-ambiguous by construction — the
    # fraction of Ms walks is an upper bound on the achievable error from
    # this source (it exists to exercise the common-path drop).
    n_background: int = 60      # noise genes in both expression and network
    n_expr_only: int = 8        # genes only in the expression file
    n_net_only: int = 8         # genes only in the network file
    module_chords: int = 3      # extra random in-module edges per gene (besides the ring)
    background_edges: int = 120
    noise: float = 0.3          # in-module residual std (corr ~ 1/(1+noise^2))
    shift: float = 1.2          # between-group mean shift for Mg/Mp genes
    seed: int = 0

    @property
    def n_samples(self) -> int:
        return self.n_good + self.n_poor


def _module_edges(genes: List[str], chords: int, rng: np.random.Generator
                  ) -> List[Tuple[str, str]]:
    """A directed ring (guarantees connectivity) plus random chords."""
    n = len(genes)
    edges = [(genes[i], genes[(i + 1) % n]) for i in range(n)]
    for i in range(n):
        for j in rng.choice(n, size=min(chords, n - 1), replace=False):
            if j != i:
                edges.append((genes[i], genes[int(j)]))
    return edges


def make_synthetic(spec: SyntheticSpec
                   ) -> Tuple[ExpressionData, Dict[str, int], NetworkData, Dict[str, List[str]]]:
    """Build (expression, clinical, network, module-membership) in memory."""
    rng = np.random.default_rng(spec.seed)
    m = spec.module_size
    ms_size = spec.shared_module_size if spec.shared_module_size is not None else m

    mg = [f"GMOD{i:04d}" for i in range(m)]              # good module
    mp = [f"PMOD{i:04d}" for i in range(m)]              # poor module
    ms = [f"SMOD{i:04d}" for i in range(ms_size)]        # shared module
    bg = [f"BACK{i:04d}" for i in range(spec.n_background)]
    expr_only = [f"XONL{i:04d}" for i in range(spec.n_expr_only)]
    net_only = [f"NONL{i:04d}" for i in range(spec.n_net_only)]

    expr_genes = mg + mp + ms + bg + expr_only
    # Shuffle so sorted order interleaves the modules (stress the index maps).
    order = rng.permutation(len(expr_genes))
    expr_genes = [expr_genes[i] for i in order]

    samples = np.array([f"SAMP-{i:04d}" for i in range(spec.n_samples)])
    labels = np.array([0] * spec.n_good + [1] * spec.n_poor, dtype=np.int32)
    clinical = {s: int(l) for s, l in zip(samples, labels)}

    good = labels == 0
    poor = labels == 1
    n = spec.n_samples

    # Per-sample latent factors.
    z_g = rng.standard_normal(n)   # drives Mg inside the good group
    z_p = rng.standard_normal(n)   # drives Mp inside the poor group
    z_s = rng.standard_normal(n)   # drives Ms everywhere

    cols: Dict[str, np.ndarray] = {}
    for g in mg:
        e = rng.standard_normal(n) * spec.noise
        col = np.where(good, z_g + e, rng.standard_normal(n))
        col = col + np.where(good, spec.shift, 0.0)       # differential expression
        cols[g] = col
    for g in mp:
        e = rng.standard_normal(n) * spec.noise
        col = np.where(poor, z_p + e, rng.standard_normal(n))
        col = col + np.where(poor, spec.shift, 0.0)
        cols[g] = col
    for g in ms:
        cols[g] = z_s + rng.standard_normal(n) * spec.noise
    for g in bg + expr_only:
        cols[g] = rng.standard_normal(n)

    expr = np.stack([cols[g] for g in expr_genes], axis=1).astype(np.float32)
    expression = ExpressionData(sample=samples, gene=np.array(expr_genes), expr=expr)

    edges: List[Tuple[str, str]] = []
    edges += _module_edges(mg, spec.module_chords, rng)
    edges += _module_edges(mp, spec.module_chords, rng)
    edges += _module_edges(ms, spec.module_chords, rng)
    pool = bg + net_only
    for _ in range(spec.background_edges):
        i, j = rng.choice(len(pool), size=2, replace=False)
        edges.append((pool[int(i)], pool[int(j)]))
    network = NetworkData(edges=edges, genes={g for e in edges for g in e})

    membership = {"good": mg, "poor": mp, "shared": ms, "background": bg}
    return expression, clinical, network, membership


def write_synthetic_tsv(spec: SyntheticSpec, out_dir: str,
                        prefix: str = "syn") -> Dict[str, str]:
    """Write the synthetic dataset as reference-format TSV files."""
    expression, clinical, network, _ = make_synthetic(spec)
    os.makedirs(out_dir, exist_ok=True)
    paths = {
        "expression": os.path.join(out_dir, f"{prefix}_EXPRESSION.txt"),
        "clinical": os.path.join(out_dir, f"{prefix}_CLINICAL.txt"),
        "network": os.path.join(out_dir, f"{prefix}_NETWORK.txt"),
    }
    with open(paths["expression"], "w") as f:
        f.write("PATIENT\t" + "\t".join(expression.sample) + "\n")
        for j, g in enumerate(expression.gene):
            vals = "\t".join("%.6f" % v for v in expression.expr[:, j])
            f.write(f"{g}\t{vals}\n")
    with open(paths["clinical"], "w") as f:
        f.write("PATIENT_BARCODE\tLABEL\n")
        for s in expression.sample:
            f.write(f"{s}\t{clinical[s]}\n")
    with open(paths["network"], "w") as f:
        f.write("src\tdest\n")
        for a, b in network.edges:
            f.write(f"{a}\t{b}\n")
    return paths
