"""Seeded scale-free synthetic inputs at parameterized gene counts.

The bundled-scale generator (data/synthetic.py) plants a few dozen
module genes — enough to exercise correctness, far too small to
exercise SCALE. This module builds reference-format inputs whose graph
is a preferential-attachment (Barabási–Albert-style) network at any
gene count, with expression engineered so each prognosis group's
|PCC|-thresholded graph keeps a large, group-specific edge subset:

- **Network**: every new node attaches to ``attach`` existing nodes
  sampled proportionally to degree (the classic repeated-endpoint
  trick), seeded from a small ring — one connected component, power-law
  degree tails, the shape real interactomes approximate.
- **Expression**: each gene is "active" in each group independently
  with probability ``active_prob``; active genes load (with a random
  sign) on that group's per-sample latent factor plus noise, so two
  active genes correlate within the group at |PCC| ~ 1/(1+noise^2) and
  an edge survives the threshold iff both endpoints are active there.
  Genes active in exactly one group also get a mean shift in that
  group, so differential-expression t-scores light up — the biomarker
  scorer has real signal to rank.
- Inactive/other genes see iid noise; their edges die at the
  threshold. The two groups' graphs are therefore large, overlapping
  but distinct subgraphs of one scale-free network — group-specific
  walks exist at every scale.

First brick of ROADMAP item 2 (million-node scale-out); the streaming
trainer's bench (bench.py --_stream_ab) uses it as the
beyond-bundled-scale input. Pure numpy, no jax.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Tuple

import numpy as np


@dataclasses.dataclass
class SynthGraphSpec:
    n_genes: int = 20_000
    n_good: int = 40
    n_poor: int = 40
    attach: int = 3              # edges per new node (mean degree ~2*attach)
    active_prob: float = 0.7     # per-(gene, group) activity
    noise: float = 0.3           # in-group residual std (corr ~ 1/(1+n^2))
    shift: float = 1.0           # mean shift for single-group-active genes
    seed: int = 0

    @property
    def n_samples(self) -> int:
        return self.n_good + self.n_poor


def iter_scale_free_edges(n_nodes: int, attach: int,
                          rng: np.random.Generator,
                          chunk_edges: int = 1 << 20):
    """Preferential-attachment edge stream: yields ``(src, dst)`` int64
    chunks of at most ``chunk_edges`` edges each.

    Endpoints of every accepted edge are appended to a repeat buffer;
    sampling uniformly from the buffer IS degree-proportional sampling.
    Seeded ring over the first ``attach + 1`` nodes guarantees one
    component. Peak memory is the repeat buffer
    (``2 * attach * n_nodes`` int64 — ~48 MB at a million nodes) plus
    one chunk, never the full edge list; concatenating the chunks
    reproduces :func:`make_scale_free_edges` exactly (same rng call
    order).
    """
    if n_nodes < attach + 2:
        raise ValueError(
            f"need at least attach+2={attach + 2} nodes, got {n_nodes}")
    m = attach
    cap = 2 * m * n_nodes + 4 * (m + 1)
    rep = np.empty(cap, dtype=np.int64)
    buf_src = np.empty(chunk_edges, dtype=np.int64)
    buf_dst = np.empty(chunk_edges, dtype=np.int64)
    fill = 0
    count = 0
    for i in range(m + 1):
        j = (i + 1) % (m + 1)
        buf_src[fill] = i
        buf_dst[fill] = j
        fill += 1
        rep[count:count + 2] = (i, j)
        count += 2
    for v in range(m + 1, n_nodes):
        picks = np.unique(rep[rng.integers(0, count, size=m)])
        for u in picks:
            if fill == chunk_edges:
                yield buf_src.copy(), buf_dst.copy()
                fill = 0
            buf_src[fill] = v
            buf_dst[fill] = u
            fill += 1
            rep[count:count + 2] = (v, int(u))
            count += 2
    if fill:
        yield buf_src[:fill].copy(), buf_dst[:fill].copy()


def make_scale_free_edges(n_nodes: int, attach: int,
                          rng: np.random.Generator
                          ) -> Tuple[np.ndarray, np.ndarray]:
    """Materialized :func:`iter_scale_free_edges` (directed as written;
    the pipeline's graph stage treats edges per its own convention)."""
    chunks = list(iter_scale_free_edges(n_nodes, attach, rng))
    return (np.concatenate([c[0] for c in chunks]),
            np.concatenate([c[1] for c in chunks]))


def make_synth_graph(spec: SynthGraphSpec):
    """(gene names, samples, labels, expr [S, G] f32, (src, dst) edges).

    Deterministic in ``spec.seed`` — the CLI (tools/make_synth_graph.py)
    and the stream bench regenerate identical inputs from the spec
    alone.
    """
    rng = np.random.default_rng(spec.seed)
    G, S = spec.n_genes, spec.n_samples
    genes = np.array([f"SG{i:07d}" for i in range(G)])
    samples = np.array([f"SAMP-{i:05d}" for i in range(S)])
    labels = np.array([0] * spec.n_good + [1] * spec.n_poor, dtype=np.int32)

    src, dst = make_scale_free_edges(G, spec.attach, rng)

    act = rng.random((2, G)) < spec.active_prob        # per-group activity
    sign = rng.choice(np.array([-1.0, 1.0]), size=(2, G)).astype(np.float32)
    z = rng.standard_normal((2, S)).astype(np.float32)  # per-group factors

    expr = rng.standard_normal((S, G)).astype(np.float32) * spec.noise
    for gi in range(2):
        rows = labels == gi
        cols = act[gi]
        # Active gene in its group: signed factor loading + the noise the
        # background already holds; inactive genes keep iid noise scaled
        # UP to unit-ish variance so their correlations stay ~0 but their
        # variance does not advertise activity.
        expr[np.ix_(rows, cols)] += sign[gi, cols] * z[gi, rows][:, None]
        only = act[gi] & ~act[1 - gi]
        expr[np.ix_(rows, only)] += spec.shift
    inactive_everywhere = ~act[0] & ~act[1]
    expr[:, inactive_everywhere] += (
        rng.standard_normal((S, int(inactive_everywhere.sum())))
        .astype(np.float32))
    return genes, samples, labels, expr, (src, dst)


def write_synth_graph(spec: SynthGraphSpec, out_dir: str,
                      prefix: str = "big") -> Dict[str, str]:
    """Write the dataset as reference-format TSVs (same layout as
    data/synthetic.write_synthetic_tsv); returns the three paths plus
    edge/gene counts for the caller's report."""
    genes, samples, labels, expr, (src, dst) = make_synth_graph(spec)
    os.makedirs(out_dir, exist_ok=True)
    paths = {
        "expression": os.path.join(out_dir, f"{prefix}_EXPRESSION.txt"),
        "clinical": os.path.join(out_dir, f"{prefix}_CLINICAL.txt"),
        "network": os.path.join(out_dir, f"{prefix}_NETWORK.txt"),
        "n_genes": str(len(genes)), "n_edges": str(len(src)),
    }
    with open(paths["expression"], "w") as f:
        f.write("PATIENT\t" + "\t".join(samples) + "\n")
        # One formatted row per gene; %.4f keeps a 100k-gene file in the
        # tens of MB instead of hundreds.
        for j, g in enumerate(genes):
            f.write(g + "\t" + "\t".join("%.4f" % v for v in expr[:, j])
                    + "\n")
    with open(paths["clinical"], "w") as f:
        f.write("PATIENT_BARCODE\tLABEL\n")
        for s, l in zip(samples, labels):
            f.write(f"{s}\t{int(l)}\n")
    with open(paths["network"], "w") as f:
        f.write("src\tdest\n")
        for a, b in zip(src, dst):
            f.write(f"{genes[a]}\t{genes[b]}\n")
    return paths


_EXPR_BLOCK = 16384   # fixed gene block => bytes independent of chunking


def _streamed_expr_block(spec: SynthGraphSpec, labels: np.ndarray,
                         z: np.ndarray, block: int, lo: int, hi: int
                         ) -> np.ndarray:
    """One ``[S, hi-lo]`` expression block of the STREAMED dataset.

    Per-gene randomness comes from a child stream keyed on the block
    index over the fixed ``_EXPR_BLOCK`` grid, so any writer chunking
    produces the same values; the per-sample group factors ``z`` are
    global (shared across blocks) so in-group gene-gene correlation —
    the property the PCC threshold keys on — survives the split.
    """
    rng = np.random.default_rng([spec.seed, 2, block])
    gb = hi - lo
    S = spec.n_samples
    act = rng.random((2, gb)) < spec.active_prob
    sign = rng.choice(np.array([-1.0, 1.0]), size=(2, gb)).astype(np.float32)
    expr = rng.standard_normal((S, gb)).astype(np.float32) * spec.noise
    for gi in range(2):
        rows = labels == gi
        cols = act[gi]
        expr[np.ix_(rows, cols)] += sign[gi, cols] * z[gi, rows][:, None]
        only = act[gi] & ~act[1 - gi]
        expr[np.ix_(rows, only)] += spec.shift
    inactive = ~act[0] & ~act[1]
    expr[:, inactive] += (
        rng.standard_normal((S, int(inactive.sum()))).astype(np.float32))
    return expr


def write_synth_graph_streamed(spec: SynthGraphSpec, out_dir: str,
                               prefix: str = "big",
                               edge_chunk: int = 1 << 20,
                               partitions: int = 0) -> Dict[str, str]:
    """:func:`write_synth_graph` at million-node scale: every stage
    streams to disk in bounded chunks — the edge list never
    materializes (``iter_scale_free_edges``) and expression is
    generated per fixed ``_EXPR_BLOCK``-gene block from per-block child
    seeds, so peak memory is O(block), not O(S x G) + O(edges).

    Deterministic in ``spec`` and in ``edge_chunk``-independent bytes;
    NOT byte-identical to :func:`write_synth_graph` (different rng
    stream layout) — same distribution, same formats, same loaders.

    ``partitions > 0`` writes the network PRE-PARTITIONED for
    ``--edge-partition`` fleets: ``R`` part files (edges routed by the
    owner of their src node under parallel/shard.edge_range splits over
    node ids), a genes sidecar (the endpoint set, so ranks scan names
    without touching edges), and a sha256 manifest
    (utils/integrity) that io/readers verifies before a range read.
    Because the generator emits edges in non-decreasing src order, every
    src's edges land whole in one part in original relative order —
    concatenating the parts in manifest order reproduces the
    unpartitioned file's body exactly (the smoke-test contract).
    """
    G, S = spec.n_genes, spec.n_samples
    if G < spec.attach + 2:
        raise ValueError(
            f"need at least attach+2={spec.attach + 2} genes, got {G}")
    labels = np.array([0] * spec.n_good + [1] * spec.n_poor, dtype=np.int32)
    samples = [f"SAMP-{i:05d}" for i in range(S)]
    os.makedirs(out_dir, exist_ok=True)
    paths = {
        "expression": os.path.join(out_dir, f"{prefix}_EXPRESSION.txt"),
        "clinical": os.path.join(out_dir, f"{prefix}_CLINICAL.txt"),
        "network": os.path.join(out_dir, f"{prefix}_NETWORK.txt"),
        "n_genes": str(G),
    }
    with open(paths["clinical"], "w") as f:
        f.write("PATIENT_BARCODE\tLABEL\n")
        for s, l in zip(samples, labels):
            f.write(f"{s}\t{int(l)}\n")

    z = (np.random.default_rng([spec.seed, 1])
         .standard_normal((2, S)).astype(np.float32))
    row_fmt = "\t%.4f" * S
    with open(paths["expression"], "w") as f:
        f.write("PATIENT\t" + "\t".join(samples) + "\n")
        for lo in range(0, G, _EXPR_BLOCK):
            hi = min(lo + _EXPR_BLOCK, G)
            expr = _streamed_expr_block(spec, labels, z,
                                        lo // _EXPR_BLOCK, lo, hi)
            f.write("".join(
                "SG%07d%s\n" % (lo + j, row_fmt % tuple(expr[:, j]))
                for j in range(hi - lo)))

    edge_rng = np.random.default_rng([spec.seed, 0])
    edge_iter = iter_scale_free_edges(G, spec.attach, edge_rng,
                                      chunk_edges=edge_chunk)
    if partitions <= 0:
        n_edges = 0
        with open(paths["network"], "w") as f:
            f.write("src\tdest\n")
            for src, dst in edge_iter:
                f.write("".join("SG%07d\tSG%07d\n" % (a, b)
                                for a, b in zip(src, dst)))
                n_edges += len(src)
        paths["n_edges"] = str(n_edges)
        return paths

    from g2vec_tpu.utils.integrity import sha256_file, write_json_atomic

    bounds = np.array([p * G // partitions for p in range(partitions)],
                      dtype=np.int64)
    part_names = [f"{prefix}_NETWORK.part{p:03d}.txt"
                  for p in range(partitions)]
    part_edges = [0] * partitions
    seen = np.zeros(G, dtype=bool)
    files = [open(os.path.join(out_dir, name), "w") for name in part_names]
    try:
        for f in files:
            f.write("src\tdest\n")
        n_edges = 0
        for src, dst in edge_iter:
            seen[src] = True
            seen[dst] = True
            owner = np.searchsorted(bounds, src, side="right") - 1
            for p in np.unique(owner):
                sel = owner == p
                files[p].write("".join(
                    "SG%07d\tSG%07d\n" % (a, b)
                    for a, b in zip(src[sel], dst[sel])))
                part_edges[p] += int(sel.sum())
            n_edges += len(src)
    finally:
        for f in files:
            f.close()
    genes_name = f"{prefix}_NETWORK.genes.txt"
    with open(os.path.join(out_dir, genes_name), "w") as f:
        f.write("".join("SG%07d\n" % g for g in np.nonzero(seen)[0]))
    hi_bounds = [int(bounds[p + 1]) if p + 1 < partitions else G
                 for p in range(partitions)]
    manifest_path = os.path.join(out_dir, f"{prefix}_NETWORK.manifest.json")
    write_json_atomic(manifest_path, {
        "format": "g2vec-network-partitions-v1",
        "partitions": partitions,
        "n_genes": G,
        "genes_file": genes_name,
        "files": [
            {"name": part_names[p],
             "sha256": sha256_file(os.path.join(out_dir, part_names[p])),
             "n_edges": part_edges[p],
             # Inclusive src NAME range of the part's node split — the
             # reader prunes part files by name-range intersection.
             "gene_lo": "SG%07d" % int(bounds[p]),
             "gene_hi": "SG%07d" % (hi_bounds[p] - 1)}
            for p in range(partitions)],
    })
    paths["network"] = manifest_path
    paths["n_edges"] = str(n_edges)
    return paths
