"""Synthetic expression against the REAL bundled network + clinical files.

The reference ships ``ex_NETWORK.txt`` (298,799 directed edges over 9,904
genes) and ``ex_CLINICAL.txt`` (135 samples, 77 good / 58 poor) but the
expression matrix is stripped from this mount
(``/root/reference/.MISSING_LARGE_BLOBS``). This module synthesizes an
expression matrix CONSISTENT with those two real files so the full pipeline
can run at the reference's true scale and topology (README.md:26-32:
n_genes=7523, n_edges=216540, n_paths=45402, path genes 3773):

- **Common gene subset**: ``n_common`` of the network's genes, chosen as a
  top-degree core plus a random fill where the core size is bisected until
  the induced edge count matches ``target_edges`` — reproducing the
  restricted-network scale of the transcript (README.md:28).
- **Active modules**: three disjoint connected regions of the real graph
  (BFS balls by default; ``module_growth="dense"`` grows by greedy
  max-connectivity). A_good genes share one latent factor over the GOOD
  samples only (pairwise PCC ~ rho > 0.5, so their real edges survive the
  |PCC| threshold in the good-group graph and walks traverse real
  topology); over poor samples they are iid noise. Symmetric for A_poor.
  A third ``n_shared``-gene module correlates within BOTH groups (its own
  factor per group, no differential shift) — it walks in both group
  graphs, which is what pushes unique-path yield toward the transcript's
  12 paths/gene (see tools/calibrate_real.py and the tradeoff account in
  tests/test_acceptance_real.py). Everything else is noise everywhere, so
  background edges die at the threshold — matching the transcript's
  sparse path-gene count (3,773 of 7,523 genes ever appear in a path,
  README.md:32).
- **Differential shift** on active genes in their group lights up the
  t-scores the biomarker stage mixes in (ref: G2Vec.py:96-102).
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Tuple

import numpy as np

from g2vec_tpu.io.readers import ExpressionData, load_clinical, load_network


@dataclasses.dataclass
class RealExampleSpec:
    n_common: int = 7523        # transcript: n_genes (README.md:27)
    target_edges: int = 216540  # transcript: n_edges (README.md:28)
    n_active_per_group: int = 1880   # with n_shared: path genes ~ 3,773
    n_shared: int = 120         # genes of a module correlated within BOTH
                                # groups (separate latent factor per group,
                                # no differential shift). Why it exists:
                                # with disjoint modules, n_paths maxes out
                                # near reps*path_genes + singletons, but
                                # the transcript shows 12.03 paths/gene at
                                # reps=10 — only reachable if the groups'
                                # active regions OVERLAP (a core whose
                                # edges survive in both graphs walks in
                                # both, and each group's dead-elsewhere
                                # genes add surviving singletons). See
                                # tools/calibrate_real.py.
    rho: float = 0.72           # in-module PCC; P(sample PCC < 0.5) ~ 1e-4
    shift: float = 1.0          # differential expression of active genes
    seed: int = 0
    module_growth: str = "bfs"  # "bfs" = breadth-first ball; "dense" =
                                # greedy max-connectivity growth (more
                                # internal edges per gene -> branchier
                                # walks; see tools/calibrate_real.py)


def _select_common(deg: np.ndarray, src: np.ndarray, dst: np.ndarray,
                   n_common: int, target_edges: int,
                   rng: np.random.Generator) -> np.ndarray:
    """Gene mask whose induced edge count ~= target: bisect the size of a
    top-degree core filled up with uniformly random genes."""
    order = np.argsort(-deg)

    def induced(k: int) -> Tuple[int, np.ndarray]:
        mask = np.zeros(deg.size, bool)
        mask[order[:k]] = True
        extra = rng.choice(order[k:], n_common - k, replace=False)
        mask[extra] = True
        return int((mask[src] & mask[dst]).sum()), mask

    lo, hi = 0, n_common
    while hi - lo > 8:
        mid = (lo + hi) // 2
        e, _ = induced(mid)
        if e < target_edges:
            lo = mid
        else:
            hi = mid
    _, mask = induced(hi)
    return mask


def _bfs_region(adj: Dict[int, list], seeds, size: int, allowed: np.ndarray
                ) -> np.ndarray:
    """Grow a connected region to ``size`` genes by BFS over the undirected
    graph, restricted to ``allowed`` (bool mask); returns the member ids."""
    from collections import deque

    member = set()
    queue = deque(s for s in seeds if allowed[s])
    while queue and len(member) < size:
        u = queue.popleft()
        if u in member:
            continue
        member.add(u)
        for v in adj.get(u, ()):
            if allowed[v] and v not in member:
                queue.append(v)
    return np.fromiter(member, dtype=np.int64)


def _dense_region(adj: Dict[int, list], seeds, size: int,
                  allowed: np.ndarray) -> np.ndarray:
    """Greedy max-connectivity growth: always add the frontier gene with the
    most edges into the current member set.

    A BFS ball reaches ``size`` with a large tree-ish fringe (every fringe
    gene touches the module through ~1 edge), so walks entering the fringe
    branch little and collapse onto few distinct gene sets. Picking the
    best-connected candidate instead maximizes internal degree — walks
    branch more, and the unique-path yield per path-gene rises toward the
    real transcript's (tools/calibrate_real.py measures exactly this).
    """
    import heapq

    member: set = set()
    # Max-heap by connections-into-member; lazy counts (re-push on change).
    conn: Dict[int, int] = {}
    heap: list = []
    for s in seeds:
        if allowed[s]:
            conn[int(s)] = 0
            heapq.heappush(heap, (0, int(s)))
    while heap and len(member) < size:
        neg, u = heapq.heappop(heap)
        if u in member or -neg != conn.get(u, 0):
            continue        # stale entry
        member.add(u)
        for v in adj.get(u, ()):
            v = int(v)
            if allowed[v] and v not in member:
                conn[v] = conn.get(v, 0) + 1
                heapq.heappush(heap, (-conn[v], v))
    return np.fromiter(member, dtype=np.int64)


def make_real_expression(network_path: str, clinical_path: str,
                         spec: RealExampleSpec
                         ) -> Tuple[ExpressionData, Dict[str, np.ndarray]]:
    """Build the expression stand-in; returns (expression, info).

    ``info``: {"active_good", "active_poor"}: gene-NAME arrays of the two
    planted modules (for test assertions)."""
    if spec.module_growth not in ("bfs", "dense"):
        raise ValueError(
            f"module_growth must be bfs|dense, got {spec.module_growth!r}")
    rng = np.random.default_rng(spec.seed)
    clinical = load_clinical(clinical_path)
    network = load_network(network_path)

    genes = sorted(network.genes)
    g2i = {g: i for i, g in enumerate(genes)}
    src = np.fromiter((g2i[a] for a, _ in network.edges), np.int64)
    dst = np.fromiter((g2i[b] for _, b in network.edges), np.int64)
    deg = (np.bincount(src, minlength=len(genes))
           + np.bincount(dst, minlength=len(genes)))

    common_mask = _select_common(deg, src, dst, spec.n_common,
                                 spec.target_edges, rng)

    # Undirected adjacency restricted to the common set, for module growth.
    adj: Dict[int, list] = {}
    keep = common_mask[src] & common_mask[dst]
    for a, b in zip(src[keep], dst[keep]):
        adj.setdefault(int(a), []).append(int(b))
        adj.setdefault(int(b), []).append(int(a))

    by_degree = np.argsort(-deg)
    hubs = [int(i) for i in by_degree if common_mask[i]]
    grow = _dense_region if spec.module_growth == "dense" else _bfs_region
    a_good = grow(adj, hubs[:1], spec.n_active_per_group, common_mask)
    remaining = common_mask.copy()
    remaining[a_good] = False
    seeds = [h for h in hubs if remaining[h]]
    a_poor = grow(adj, seeds[:1], spec.n_active_per_group, remaining)
    remaining[a_poor] = False
    if spec.n_shared > 0:
        seeds = [h for h in hubs if remaining[h]]
        a_shared = grow(adj, seeds[:1], spec.n_shared, remaining)
    else:
        a_shared = np.empty(0, dtype=np.int64)

    samples = np.array(list(clinical.keys()))
    labels = np.array([clinical[s] for s in samples], dtype=np.int32)
    good = labels == 0
    n = samples.size

    common_ids = np.flatnonzero(common_mask)
    good_set, poor_set = set(a_good.tolist()), set(a_poor.tolist())
    shared_set = set(a_shared.tolist())
    z_good = rng.standard_normal(n)
    z_poor = rng.standard_normal(n)
    # The shared module correlates within EACH group via its own factor —
    # its edges survive both group graphs — but carries no shift (no label
    # signal; its walks are label-ambiguous, as real housekeeping
    # correlation structure is).
    z_sh_g = rng.standard_normal(n)
    z_sh_p = rng.standard_normal(n)
    w_sig = np.sqrt(spec.rho)
    w_eps = np.sqrt(1.0 - spec.rho)

    expr = rng.standard_normal((n, common_ids.size)).astype(np.float64)
    for j, gid in enumerate(common_ids):
        if gid in good_set:
            expr[good, j] = (w_sig * z_good[good]
                             + w_eps * expr[good, j] + spec.shift)
        elif gid in poor_set:
            expr[~good, j] = (w_sig * z_poor[~good]
                              + w_eps * expr[~good, j] + spec.shift)
        elif gid in shared_set:
            expr[good, j] = w_sig * z_sh_g[good] + w_eps * expr[good, j]
            expr[~good, j] = w_sig * z_sh_p[~good] + w_eps * expr[~good, j]

    gene_names = np.array([genes[i] for i in common_ids])
    order = rng.permutation(gene_names.size)   # file order != sorted order
    expression = ExpressionData(
        sample=samples, gene=gene_names[order],
        expr=expr[:, order].astype(np.float32))
    info = {"active_good": np.array([genes[i] for i in a_good]),
            "active_poor": np.array([genes[i] for i in a_poor]),
            "active_shared": np.array([genes[i] for i in a_shared])}
    return expression, info


def write_real_expression_tsv(network_path: str, clinical_path: str,
                              out_path: str,
                              spec: RealExampleSpec | None = None
                              ) -> Dict[str, np.ndarray]:
    """Write the stand-in expression as a reference-format TSV."""
    spec = spec or RealExampleSpec()
    expression, info = make_real_expression(network_path, clinical_path, spec)
    os.makedirs(os.path.dirname(out_path) or ".", exist_ok=True)
    with open(out_path, "w") as f:
        f.write("PATIENT\t" + "\t".join(expression.sample) + "\n")
        for j, g in enumerate(expression.gene):
            vals = "\t".join("%.6f" % v for v in expression.expr[:, j])
            f.write(f"{g}\t{vals}\n")
    return info
