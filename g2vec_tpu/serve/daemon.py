"""The resident job daemon: one warm device owner, many jobs.

Every pre-serve invocation — even a PR 5 batched manifest — pays process
startup, jax init, and cold compiles before its first useful FLOP. HUGE
(arXiv:2307.14490) keeps a TPU embedding pipeline resident across jobs
for exactly this reason, and GraphVite (arXiv:1903.00757) overlaps
CPU-side sampling with accelerator work inside one long-lived process.
This daemon is that shape for g2vec:

- **One ResidentEngine** (batch/engine.py) owns the device for the daemon
  lifetime: the jit/LRU chunk programs, the persistent XLA tier, the
  SharedWalkTier memo, and the preprocessed-dataset memo all stay warm
  across jobs; newly seen shapes warm in the background on the engine's
  overlap pool while earlier buckets train.
- **Admission control**: a bounded multi-tenant queue. A full queue
  rejects with a structured ``queue_full`` error (back-pressure belongs
  at the edge, not as an OOM three stages later); malformed jobs reject
  at submit time with the offending key named.
- **Shape-bucket-aware scheduling**: when a job is popped, every queued
  job whose non-variant config coincides (``_join_key``) joins the same
  batch — their lanes plan into the engine's shape buckets together, so
  K compatible single-run jobs cost one walk product set and one vmapped
  trainer program instead of K solo runs.
- **Tenant fairness**: the queue pops round-robin across tenants, so one
  tenant's burst cannot starve another's single job.
- **Per-job JSONL result streaming**: a submitting client holds its
  connection and receives the job's events (accepted/started/lane_done/
  job_done) as they happen; a disconnected client loses nothing — the
  terminal record is also written to ``<state-dir>/results/<job_id>.json``.
- **Crash recovery**: accepted jobs are journaled to
  ``<state-dir>/jobs/<job_id>.json`` and un-journaled on completion. A
  relaunch (the ``--supervise`` watchdog, resilience/supervisor.py
  ``supervise_serve``) re-queues every journaled job; the persistent
  ``--cache-dir`` tiers restore the compile and walk caches, so the
  re-run is warm-start, not cold. Streaming jobs additionally resume
  from their (epoch, shard) cursor under ``<state-dir>/ckpt/`` — a
  relaunch re-enters training mid-epoch instead of re-running it.
- **Job lifecycle** (PR 9): per-job ``priority`` (interactive/batch with
  aging so batch never starves), ``deadline_s`` (measured from original
  submission, survives restarts), client ``cancel`` (cooperative — the
  trainers' check hook raises at the next shard/chunk boundary), and
  graceful drain (SIGTERM or the ``drain`` op: admission closes,
  in-flight streaming jobs checkpoint, everything unfinished stays
  journaled, the process exits 0). Every job walks a monotone
  ``queued → started → (checkpointed|resumed)* → terminal`` state
  machine, emitted as ``job_state`` metrics and counted on /status.

Outputs are BYTE-IDENTICAL to the same config run solo (float32, same
backend): jobs execute through the engine's lane machinery, whose parity
contract tests/test_batch_engine.py pins; the daemon only renames the
spool files to each job's requested ``result_name``.
"""
from __future__ import annotations

import dataclasses
import glob
import hmac
import os
import queue
import re
import secrets
import shutil
import socket
import sys
import threading
import time
import uuid
from collections import Counter, OrderedDict, deque
from typing import Callable, Dict, List, Optional, Tuple

from g2vec_tpu.batch.engine import (LaneVariant, ManifestError,
                                    ResidentEngine, _variant_from_dict,
                                    seed_sweep_variants)
from g2vec_tpu.config import (G2VecConfig, config_from_job,
                              serve_join_key)
from g2vec_tpu.resilience.lifecycle import (DrainRequested, JobCancelled,
                                            JobDeadlineExceeded,
                                            JobInterrupted, TokenBucket,
                                            shed_decision)
from g2vec_tpu.serve import inventory, leader, protocol
from g2vec_tpu.utils.integrity import write_json_atomic
from g2vec_tpu.utils.metrics import MetricsWriter

_TENANT_MAX = 64
#: Job priority classes: ``interactive`` pops before ``batch``; aging
#: (ServeOptions.aging_s) promotes a long-waiting batch job so a steady
#: interactive stream can never starve it.
PRIORITIES = ("interactive", "batch")
#: Lanes one job may submit; a bigger sweep should be several jobs (the
#: scheduler joins them anyway) so admission stays per-tenant fair.
MAX_JOB_LANES = 64

#: The job-join key moved to config.serve_join_key (PR 11) so the router
#: — a jax-free process — can consistent-hash it without importing the
#: engine; this alias keeps the daemon's call sites and older tests alive.
_join_key = serve_join_key

#: Client-generated idempotency keys (``idem_key`` in a submit payload).
#: The daemon derives the job_id from the key, so the SAME submission —
#: retried through a router after a replica death, or re-queued onto a
#: survivor — maps onto one job everywhere: one journal entry, one
#: streaming-cursor directory, one terminal record. Defined in
#: protocol.py so the jax-free router shares the derivation.
MAX_IDEM_KEY = protocol.MAX_IDEM_KEY
idem_job_id = protocol.idem_job_id

#: Explicit ``job_id`` on a submit payload (router failover resubmits of
#: keyless jobs — see router._failover). job_ids name files under the
#: state dir, so the charset is locked down: no separators, no dotfiles.
_JOB_ID_RE = re.compile(r"[A-Za-z0-9][A-Za-z0-9._-]{0,79}")


class QueueFull(RuntimeError):
    """Admission rejected: the bounded job queue is at capacity."""


@dataclasses.dataclass
class ServeOptions:
    """Daemon configuration (the ``g2vec serve`` flag surface)."""

    socket_path: str
    state_dir: str
    queue_depth: int = 16        # max jobs queued (not yet executing)
    max_join: int = 4            # max jobs merged into one engine batch
    job_retries: int = 1         # in-process retries for retryable failures
    aging_s: float = 30.0        # batch job older than this outranks interactive
    cache_dir: Optional[str] = None
    metrics_jsonl: Optional[str] = None
    fault_plan: Optional[str] = None
    #: TCP front door ("host:port", port 0 = ephemeral): a second listener
    #: speaking the same JSONL protocol + HTTP /status. The UNIX socket
    #: stays — local clients and the router keep their cheap path.
    listen: Optional[str] = None
    #: Shared-secret tenancy for the network listener: when set, every
    #: MUTATING op (submit/cancel/drain/shutdown) must carry a matching
    #: ``auth_token`` field or is rejected at admission. ``status``/
    #: ``ping`` stay open — health probes must not need secrets.
    auth_token: Optional[str] = None
    #: Per-connection socket deadline: a client that stalls mid-request
    #: (or stops reading its event stream) is disconnected instead of
    #: holding a handler thread forever.
    read_deadline_s: float = 30.0
    #: Hard bound on one request line; an oversized request is answered
    #: with a structured error, never buffered past this.
    max_request_bytes: int = 0   # 0 = protocol.MAX_LINE_BYTES
    #: Query plane (PR 15): byte budget for the memory-mapped bundle
    #: LRU — resident cost is mapped PAGES the kernels touch, so this
    #: bounds address-space bookkeeping, not copies.
    inventory_budget_bytes: int = 256 << 20
    #: Entries in the keyed query-result LRU (results are tiny —
    #: k genes + k floats — so a count bound suffices).
    query_cache_entries: int = 128
    #: Extra catalog root beyond ``<state>/inventory`` — point the
    #: daemon at a directory of solo ``--emit-inventory`` bundles to
    #: make them queryable without a serve job.
    inventory_dir: Optional[str] = None
    #: IVF list count for published bundles (ops/ann.resolve_nlist):
    #: 0 auto-indexes bundles past the size threshold, <0 disables the
    #: approximate plane entirely, >0 forces a list count (tests use
    #: this to index tiny bundles).
    ann_nlist: int = 0
    #: Server-side cap on one ``result`` response; an over-cap record
    #: becomes a structured ``oversized_result`` error (see
    #: protocol.bound_record). 0 = protocol.MAX_LINE_BYTES.
    max_result_bytes: int = 0
    #: Per-tenant admission SLOs: ``name:rate:burst[:weight];...`` —
    #: ``rate`` submissions/second refilling a ``burst``-deep token
    #: bucket, plus a weighted-fair queue share (see
    #: :func:`parse_tenant_quotas`). ``*`` names the default applied to
    #: unlisted tenants; with no ``*`` entry, unlisted tenants are
    #: unlimited (weight 1). None disables rate limiting entirely.
    tenant_quotas: Optional[str] = None
    #: Deadline-aware load shedding: reject a deadlined job at admission
    #: (structured ``shed`` + ``retry_after_s``) when the estimated
    #: queue wait already exceeds its whole ``deadline_s`` — refusing
    #: up-front beats accepting work that dies of deadline_exceeded
    #: after burning a lane (lifecycle.shed_decision has the boundary
    #: semantics).
    shed: bool = False


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """One tenant's admission SLO: token-bucket rate limit + fair share."""

    rate: float                  # submissions/second refill
    burst: float                 # bucket capacity (max burst size)
    weight: int = 1              # weighted-fair queue share


def parse_tenant_quotas(spec: Optional[str]) -> Dict[str, TenantQuota]:
    """Parse a ``--tenant-quotas`` spec: semicolon-separated
    ``name:rate:burst[:weight]`` entries, e.g.
    ``gold:4:8:3;bulk:0.5:2:1;*:2:4:1``. ``*`` is the default for
    tenants not named. Raises ValueError naming the bad entry."""
    quotas: Dict[str, TenantQuota] = {}
    if not spec:
        return quotas
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        parts = entry.split(":")
        if len(parts) not in (3, 4):
            raise ValueError(
                f"bad --tenant-quotas entry {entry!r}: expected "
                f"name:rate:burst[:weight]")
        name = parts[0].strip()
        if not name or len(name) > _TENANT_MAX:
            raise ValueError(f"bad --tenant-quotas tenant name {name!r}")
        if name in quotas:
            raise ValueError(f"duplicate --tenant-quotas tenant {name!r}")
        try:
            rate, burst = float(parts[1]), float(parts[2])
            weight = int(parts[3]) if len(parts) == 4 else 1
        except ValueError:
            raise ValueError(
                f"bad --tenant-quotas entry {entry!r}: rate/burst must "
                f"be numbers, weight an int") from None
        if rate <= 0 or burst < 1:
            raise ValueError(
                f"bad --tenant-quotas entry {entry!r}: need rate > 0 "
                f"and burst >= 1")
        if weight < 1:
            raise ValueError(
                f"bad --tenant-quotas entry {entry!r}: weight must "
                f"be >= 1")
        quotas[name] = TenantQuota(rate=rate, burst=burst, weight=weight)
    return quotas


@dataclasses.dataclass
class ServeJob:
    """One admitted job: a validated config + planned lanes + routing."""

    job_id: str
    tenant: str
    cfg: G2VecConfig
    variants: List[LaneVariant]
    raw: dict                    # the submit payload, journal currency
    submitted_at: float
    join_key: Tuple = ()
    attempts: int = 0
    subscriber: Optional["queue.Queue"] = None
    priority: str = "batch"
    #: Wall-clock budget measured from ``submitted_at`` (the ORIGINAL
    #: submission, surviving journal recovery — a deadline is a promise
    #: to the client, not to whichever daemon incarnation runs the job).
    deadline_s: Optional[float] = None
    queued_at: float = 0.0       # set at each (re)queue; drives aging
    #: Client-generated idempotency key; job_id is derived from it, so a
    #: retried/re-routed submission dedups instead of duplicating.
    idem_key: Optional[str] = None
    #: The write half of the read plane: set (to the target bundle's
    #: job_id) when this job is an ``update`` op. Update jobs carry no
    #: lanes — they bypass the engine batch and run the incremental
    #: engine (delta re-walk + warm-start fine-tune), then republish
    #: the target bundle as a new generation. Their join_key is unique,
    #: so they never merge into a training batch.
    update_of: Optional[str] = None
    update_variant: Optional[str] = None
    update_epochs: int = 0       # 0 = incremental.run_update's default
    cancel_ev: threading.Event = dataclasses.field(
        default_factory=threading.Event)

    def deadline_expired(self, now: Optional[float] = None) -> bool:
        if self.deadline_s is None:
            return False
        return (time.time() if now is None else now) \
            > self.submitted_at + self.deadline_s


class _FairQueue:
    """Bounded multi-tenant, two-priority FIFO with round-robin pop.

    Per-tenant deques inside two priority tiers. ``pop`` order is
    strict-priority with aging: an aged batch job (queued longer than
    ``aging_s``) first, then any interactive job, then any batch job —
    so interactive jobs cut the line but can never starve batch work.
    Within a tier tenants are served weighted round-robin: a tenant
    with ``weight`` w (from ``--tenant-quotas``, default 1) gets up to w
    consecutive pops before rotating to the back — over a full rotation
    the tenants' service counts converge to their weight ratio. With no
    weights configured this degenerates to exactly the old one-pop
    round-robin, so a tenant submitting N jobs waits behind every other
    tenant once per own job, not zero times.
    ``take_compatible`` pulls additional queued jobs with a matching join
    key (any tenant or priority, FIFO within each) for batch joining —
    those jobs would only have waited longer by staying queued.
    """

    def __init__(self, depth: int, aging_s: float = 30.0,
                 weights: Optional[Dict[str, int]] = None):
        self._depth = depth
        self._aging_s = aging_s
        #: tenant -> fair-share weight; ``*`` is the default for
        #: unlisted tenants. Immutable after construction.
        self._weights: Dict[str, int] = dict(weights or {})
        # guarded-by: _lock
        self._tiers: Dict[str, "OrderedDict[str, deque]"] = {
            p: OrderedDict() for p in PRIORITIES}
        #: Per-tier deficit counters for the weighted round-robin:
        #: remaining consecutive pops before this tenant rotates.
        # guarded-by: _lock
        self._credits: Dict[str, Dict[str, int]] = {
            p: {} for p in PRIORITIES}
        self._n = 0                  # guarded-by: _lock
        self._lock = threading.Lock()
        # Holding _not_empty IS holding _lock (Condition wraps it) —
        # the checker understands the aliasing.
        self._not_empty = threading.Condition(self._lock)

    def _weight(self, tenant: str) -> int:
        return max(1, self._weights.get(tenant,
                                        self._weights.get("*", 1)))

    def depth(self) -> int:
        with self._lock:
            return self._n

    def depths(self) -> Dict[str, int]:
        with self._lock:
            return {p: sum(len(dq) for dq in tier.values())
                    for p, tier in self._tiers.items()}

    def push(self, job: ServeJob) -> None:
        with self._lock:
            if self._n >= self._depth:
                raise QueueFull(
                    f"job queue is full ({self._n}/{self._depth})")
            job.queued_at = time.time()
            tier = self._tiers[job.priority]
            tier.setdefault(job.tenant, deque()).append(job)
            self._n += 1
            self._not_empty.notify()

    # analyze: holds[_lock] — pop()'s wait loop already owns the
    # Condition; the checker verifies every call site holds the lock.
    def _pop_tier(self, pname: str,
                  min_age: float = 0.0) -> Optional[ServeJob]:
        tier = self._tiers[pname]
        credits = self._credits[pname]
        now = time.time()
        for name, dq in list(tier.items()):
            if dq and (not min_age or now - dq[0].queued_at >= min_age):
                cr = credits.get(name, self._weight(name)) - 1
                if cr <= 0:
                    # Share spent: reset and rotate to the back.
                    credits[name] = self._weight(name)
                    tier.move_to_end(name)
                else:
                    credits[name] = cr      # keep serving this tenant
                self._n -= 1
                return dq.popleft()
        return None

    def pop(self, timeout: Optional[float] = None) -> Optional[ServeJob]:
        with self._not_empty:
            if not self._n:
                self._not_empty.wait(timeout)
            job = self._pop_tier("batch",
                                 min_age=self._aging_s)     # aged first
            if job is None:
                job = self._pop_tier("interactive")
            if job is None:
                job = self._pop_tier("batch")
            return job

    def take_compatible(self, key: Tuple, limit: int) -> List[ServeJob]:
        out: List[ServeJob] = []
        if limit <= 0:
            return out
        with self._lock:
            for tier in self._tiers.values():
                for name, dq in list(tier.items()):
                    keep: deque = deque()
                    while dq:
                        j = dq.popleft()
                        if len(out) < limit and j.join_key == key:
                            out.append(j)
                        else:
                            keep.append(j)
                    tier[name] = keep
            self._n -= len(out)
        return out

    def remove(self, job_id: str) -> Optional[ServeJob]:
        """Pull a specific queued job (the queued-cancel path)."""
        with self._lock:
            for tier in self._tiers.values():
                for name, dq in tier.items():
                    for j in dq:
                        if j.job_id == job_id:
                            dq.remove(j)
                            self._n -= 1
                            return j
        return None


class ServeDaemon:
    """See the module docstring. Scheduling (:meth:`step`) and admission
    (:meth:`admit`) are plain methods so tests drive them in-process;
    :meth:`serve_forever` adds the socket front-end and the scheduler
    thread for the real daemon."""

    def __init__(self, opts: ServeOptions,
                 console: Callable[[str], None] = print):
        if opts.queue_depth < 1:
            raise ValueError(f"--queue-depth must be >= 1, "
                             f"got {opts.queue_depth}")
        if opts.max_join < 1:
            raise ValueError(f"--max-join must be >= 1, "
                             f"got {opts.max_join}")
        if opts.job_retries < 0:
            raise ValueError(f"--job-retries must be >= 0, "
                             f"got {opts.job_retries}")
        self.opts = opts
        self.console = console
        self._jobs_dir = os.path.join(opts.state_dir, "jobs")
        self._results_dir = os.path.join(opts.state_dir, "results")
        self._spool_dir = os.path.join(opts.state_dir, "spool")
        for d in (self._jobs_dir, self._results_dir, self._spool_dir):
            os.makedirs(d, exist_ok=True)
        self._ckpt_dir = os.path.join(opts.state_dir, "ckpt")
        #: Per-replica migration secret: ``requeue``/``submitted_at`` on
        #: a submit are honored only when the payload carries this token
        #: (see _trusted_requeue). It lives as a 0600 file in the state
        #: dir, so possession proves filesystem access to THIS replica's
        #: durable state — the router qualifies (it co-hosts the state
        #: dirs and fences/migrates their journals), a network client
        #: holding the shared fleet auth_token does not. Kept across
        #: relaunches so a failover racing a relaunch stays consistent.
        self._relay_token = self._load_relay_token()
        #: The query plane's read substrate: bundles published under
        #: <state>/inventory/<job_id>/<variant>/ (plus an optional
        #: --inventory-dir of solo bundles), memory-mapped behind a
        #: byte-budgeted LRU. The catalog rebuilds itself from disk on
        #: demand, so boot needs no replay.
        self._inventory_dir = os.path.join(opts.state_dir, "inventory")
        roots = [self._inventory_dir]
        if opts.inventory_dir:
            roots.append(opts.inventory_dir)
        self.catalog = inventory.InventoryCatalog(
            roots, budget_bytes=opts.inventory_budget_bytes)
        #: Cached scan_bundles view for query resolution. This daemon
        #: is the only writer of its inventory root, so the cache is
        #: exact between publishes: every publish/republish resets it,
        #: and any resolution MISS rescans before erroring (which also
        #: picks up bundles dropped into an external --inventory-dir).
        #: Whole-dict swaps are GIL-atomic; no lock needed.
        self._inv_known: Dict[str, str] = {}
        self.qcache = inventory.QueryCache(opts.query_cache_entries)
        self.metrics = MetricsWriter(opts.metrics_jsonl, append=True)
        self.engine = ResidentEngine(cache_dir=opts.cache_dir)
        #: tenant -> TenantQuota, parsed once; immutable after init
        #: (ValueError on a bad spec surfaces at construction, not on
        #: the first unlucky tenant's submit).
        self._quotas = parse_tenant_quotas(opts.tenant_quotas)
        self._queue = _FairQueue(
            opts.queue_depth, aging_s=opts.aging_s,
            weights={t: q.weight for t, q in self._quotas.items()})
        #: Lazily-built per-tenant token buckets. Admission runs on
        #: per-connection threads, and a bucket's refill+take must be
        #: one atomic step or two concurrent submits both spend the
        #: last token.
        self._buckets: Dict[str, TokenBucket] = {}  # guarded-by: _tenant_lock
        self._tenant_lock = threading.Lock()
        #: Recent per-job service times (batch wall / jobs in batch) —
        #: the evidence behind the shed estimate. Bounded so the
        #: estimate tracks the CURRENT workload mix.
        self._service_times: "deque[float]" = deque(maxlen=32)  # guarded-by: _lock
        #: Per-tenant SLO ledger (admitted/done/shed/quota_rejected/
        #: failed/cancelled/deadline_exceeded) for /status and the
        #: router's fleet aggregation.
        self._tenant_stats: Dict[str, "Counter[str]"] = {}  # guarded-by: _lock
        self._defaults = G2VecConfig()
        #: In-flight jobs and the lifecycle counters below are touched
        #: from the scheduler thread AND per-connection threads (admit,
        #: cancel_job, /status) — every mutation under _lock; the
        #: lock-discipline checker (analyze/locks.py) enforces it.
        self._running: Dict[str, ServeJob] = {}   # guarded-by: _lock
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._draining = False       # racy-read bool by design: writers
        #                            # converge, readers only see it late
        # guarded-by: _lock
        self._state_counts: "Counter[str]" = Counter()
        self._t0 = time.time()
        self._serial = 0             # guarded-by: _lock
        self._batches = 0            # scheduler-thread only
        self.jobs_done = 0           # guarded-by: _lock
        self.jobs_failed = 0         # guarded-by: _lock
        self._last_beat = self._t0   # scheduler liveness, see /status
        self.tcp_addr: Optional[Tuple[str, int]] = None
        #: idem_key -> job_id for every job this state dir has seen
        #: (journaled, running, or terminally recorded) — the dedup table
        #: behind exactly-once acks. Rebuilt from disk at boot so a
        #: relaunch keeps refusing duplicates it acked in a past life.
        #: guarded-by: _idem_lock — admit() runs on per-connection
        #: threads, and lookup + reservation must be one atomic step or
        #: two concurrent same-key submits both miss the table and run.
        self._idem: Dict[str, str] = {}
        self._idem_lock = threading.Lock()
        self._load_idem_table()
        #: Leadership-fencing state (serve/leader.py). The highest
        #: router epoch this state dir has EVER witnessed, persisted so
        #: a relaunch keeps rejecting a zombie ex-leader's commands;
        #: a mutating op carrying a lower epoch gets a structured
        #: ``stale_epoch`` reject in _handle_conn. Epoch-less payloads
        #: (single-router fleets, degraded-mode clients) always pass.
        self._epoch_path = os.path.join(opts.state_dir,
                                        leader.ROUTER_EPOCH_FILE)
        # guarded-by: _epoch_lock
        self._router_epoch = leader.read_epoch_file(self._epoch_path)
        self._epoch_lock = threading.Lock()
        #: Fence-marker latch: flips True once, on first sighting of
        #: <state>/fenced (racy-read bool like _draining — writers
        #: converge, readers only see it late, and "late" here means
        #: one extra marker stat()).
        self._quarantined = False
        if opts.fault_plan:
            from g2vec_tpu.resilience.faults import install_plan

            install_plan(opts.fault_plan)
        self._recover_journal()

    def _load_idem_table(self) -> None:
        import json

        for d, extract in ((self._jobs_dir,
                            lambda r: r.get("payload", {}).get("idem_key")),
                           (self._results_dir,
                            lambda r: r.get("idem_key"))):
            for fn in os.listdir(d):
                if not fn.endswith(".json"):
                    continue
                try:
                    with open(os.path.join(d, fn)) as f:
                        rec = json.load(f)
                except (OSError, ValueError):
                    continue
                key = extract(rec)
                if isinstance(key, str) and key:
                    # analyze: allow[lock-discipline] boot-time rebuild,
                    # runs from __init__ before any connection thread
                    self._idem[key] = rec.get("job_id", fn[:-5])

    def _load_relay_token(self) -> str:
        """Load (or mint, 0600) ``<state-dir>/relay_token``."""
        path = os.path.join(self.opts.state_dir, "relay_token")
        try:
            with open(path) as f:
                tok = f.read().strip()
            if tok:
                return tok
        except OSError:
            pass
        tok = secrets.token_hex(16)
        fd = os.open(path, os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
        with os.fdopen(fd, "w") as f:
            f.write(tok)
        return tok

    def _trusted_requeue(self, payload: dict) -> bool:
        """Is this submit the router's own journal-migration resubmit?
        Only then are ``requeue`` (skip the tenant-quota and shed gates)
        and ``submitted_at`` (deadline-clock continuity) honored. Trust
        is possession of this replica's ``relay_token``; the shared
        fleet ``auth_token`` proves nothing here — every client has it,
        and a client that could forge requeue would bypass the very SLO
        gates tenancy exists for (and forward-date its own deadline)."""
        if not payload.get("requeue"):
            return False
        tok = payload.get("relay_token")
        return isinstance(tok, str) \
            and hmac.compare_digest(tok, self._relay_token)

    # ---- leadership fencing ----------------------------------------------

    def _observe_epoch(self, payload: dict) -> Optional[dict]:
        """The fencing-epoch gate for mutating ops.

        A payload carrying ``router_epoch`` >= the highest epoch this
        state dir has witnessed advances (and persists) the watermark
        and passes; a LOWER epoch means the sender lost the leadership
        lease to a successor — return the structured ``stale_epoch``
        reject so the zombie learns it must stop trusting its own
        failure detector. Absent/0 epochs always pass: single-router
        fleets and degraded-mode clients carry none, and this gate must
        be inert for them (the PR 16 contract)."""
        e = payload.get("router_epoch")
        if not isinstance(e, int) or isinstance(e, bool) or e <= 0:
            return None
        with self._epoch_lock:
            cur = self._router_epoch
            if e >= cur:
                if e > cur:
                    self._router_epoch = e
                    leader.write_epoch_file(self._epoch_path, e)
                return None
        self.metrics.emit("stale_epoch", op=payload.get("op"),
                          got_epoch=e, seen_epoch=cur, side="daemon")
        return {"event": "rejected", "error": "stale_epoch",
                "got_epoch": e, "seen_epoch": cur,
                "detail": f"router epoch {e} is stale (this replica has "
                          f"seen {cur}); the leadership lease moved on"}

    def _fenced(self) -> bool:
        """Has the leader fenced this replica (``<state>/fenced``)?

        Checked at every admission and at the trainers' shard/superstep
        boundaries. Marker presence alone quarantines — a torn marker
        reads as epoch 0, still fenced — because the marker only exists
        when a journal migration is underway and running on means
        double execution. First sighting emits ``quarantine`` and
        latches; the marker's epoch also advances the persisted
        watermark so the fencing leader's successor is never stale."""
        ep = leader.read_fence_marker(self.opts.state_dir)
        if ep is None:
            return False
        if not self._quarantined:
            self._quarantined = True
            with self._lock:
                parked = len(self._running)
            parked += self._queue.depth()
            with self._epoch_lock:
                if ep > self._router_epoch:
                    self._router_epoch = ep
                    leader.write_epoch_file(self._epoch_path, ep)
            self.metrics.emit("quarantine", epoch=ep, parked=parked)
            self.console(f"[serve] fenced at epoch {ep}: admission "
                         f"closed, in-flight work parks at the next "
                         f"boundary, no further results/inventory "
                         f"publish ({parked} job(s) stay journaled)")
        return True

    # ---- admission --------------------------------------------------------

    def _new_job_id(self) -> str:
        # admit() runs on per-connection threads: an unlocked increment
        # can hand two concurrent keyless submits the same serial.
        with self._lock:
            self._serial += 1
            serial = self._serial
        return f"j{serial:04d}-{uuid.uuid4().hex[:8]}"

    def _plan_job(self, payload: dict, job_id: Optional[str] = None,
                  submitted_at: Optional[float] = None) -> ServeJob:
        """Validate a submit payload into a ServeJob (raises ValueError /
        ManifestError naming the problem — rejection happens at admission,
        never mid-batch)."""
        if not isinstance(payload, dict):
            raise ValueError(
                f"submit payload must be an object, got "
                f"{type(payload).__name__}")
        tenant = payload.get("tenant", "default")
        if not isinstance(tenant, str) or not tenant \
                or len(tenant) > _TENANT_MAX:
            raise ValueError(f"'tenant' must be a 1-{_TENANT_MAX} char "
                             f"string, got {tenant!r}")
        priority = payload.get("priority", "batch")
        if priority not in PRIORITIES:
            raise ValueError(f"'priority' must be one of {PRIORITIES}, "
                             f"got {priority!r}")
        deadline_s = payload.get("deadline_s")
        if deadline_s is not None:
            if not isinstance(deadline_s, (int, float)) \
                    or isinstance(deadline_s, bool) or deadline_s <= 0:
                raise ValueError(f"'deadline_s' must be a positive number, "
                                 f"got {deadline_s!r}")
            deadline_s = float(deadline_s)
        idem_key = payload.get("idem_key")
        if idem_key is not None:
            if not isinstance(idem_key, str) or not idem_key \
                    or len(idem_key) > MAX_IDEM_KEY:
                raise ValueError(f"'idem_key' must be a 1-{MAX_IDEM_KEY} "
                                 f"char string, got {idem_key!r}")
        jobd = payload.get("job")
        if not isinstance(jobd, dict):
            raise ValueError("submit needs a 'job' object")
        base = dict(jobd)
        variants_spec = base.pop("variants", None)
        seeds = base.pop("seeds", 0)
        cfg = config_from_job(base, self._defaults)
        #: ``update`` payloads share the submit pipeline end to end
        #: (validation, idem dedup, quotas, journal, recovery) but plan
        #: into a lane-less job the scheduler hands to the incremental
        #: engine instead of a training batch.
        ureq = payload if payload.get("op") == "update" else None
        if ureq is not None:
            if not idem_key:
                raise ValueError(
                    "update requires 'idem_key' — the op is "
                    "idempotency-keyed by contract (resubmits after a "
                    "lost ack must dedup, and failover re-derives the "
                    "same id on any replica)")
            target = ureq.get("job_id")
            if not isinstance(target, str) or not target:
                raise ValueError("update needs a 'job_id' string naming "
                                 "the target bundle")
            uvariant = ureq.get("variant")
            if uvariant is not None and not isinstance(uvariant, str):
                raise ValueError(f"'variant' must be a string, "
                                 f"got {uvariant!r}")
            epochs = ureq.get("epochs", 0)
            if not isinstance(epochs, int) or isinstance(epochs, bool) \
                    or epochs < 0:
                raise ValueError(f"'epochs' must be a non-negative int, "
                                 f"got {epochs!r}")
            if variants_spec is not None or seeds:
                raise ValueError("an update job cannot set 'variants' "
                                 "or 'seeds' — it targets one existing "
                                 "bundle")
            raw = {k: v for k, v in payload.items()
                   if k not in ("auth_token", "relay_token", "requeue",
                                "submitted_at", "router_epoch")}
            if submitted_at is None and self._trusted_requeue(payload):
                sa = payload.get("submitted_at")
                if isinstance(sa, (int, float)) \
                        and not isinstance(sa, bool):
                    submitted_at = float(sa)
            job = ServeJob(
                job_id=(idem_job_id(idem_key) if job_id is None
                        else job_id),
                tenant=tenant, cfg=cfg, variants=[], raw=raw,
                submitted_at=(time.time() if submitted_at is None
                              else submitted_at),
                priority=priority, deadline_s=deadline_s,
                idem_key=idem_key, update_of=target,
                update_variant=uvariant, update_epochs=epochs)
            # Unique join key: an update must never merge into an
            # engine batch, and two updates of one bundle must run
            # serially (distinct ids -> distinct keys -> no join).
            job.join_key = ("update", job.job_id)
            return job
        if variants_spec is not None and seeds:
            raise ValueError("job sets both 'variants' and 'seeds' — "
                             "pick one")
        if seeds:
            if not isinstance(seeds, int) or isinstance(seeds, bool) \
                    or not (1 <= seeds <= MAX_JOB_LANES):
                raise ValueError(f"'seeds' must be an int in "
                                 f"[1, {MAX_JOB_LANES}], got {seeds!r}")
            variants = seed_sweep_variants(cfg, seeds)
        elif variants_spec is not None:
            if not isinstance(variants_spec, list) or not variants_spec:
                raise ValueError("'variants' must be a non-empty list of "
                                 "variant objects")
            if len(variants_spec) > MAX_JOB_LANES:
                raise ValueError(
                    f"job has {len(variants_spec)} variants; the per-job "
                    f"cap is {MAX_JOB_LANES} (submit several jobs — the "
                    f"scheduler joins compatible ones anyway)")
            variants = [_variant_from_dict(i, o, cfg)
                        for i, o in enumerate(variants_spec)]
            names = [v.name for v in variants]
            dupes = sorted({n for n in names if names.count(n) > 1})
            if dupes:
                raise ValueError(f"duplicate variant name(s) {dupes} — "
                                 f"lane outputs would overwrite each other")
        else:
            variants = [_variant_from_dict(0, {"name": "v"}, cfg)]
        if job_id is None:
            # The id is DERIVED from the idempotency key: the same
            # submission lands on the same job_id on any daemon (journal
            # entry, ckpt cursor dirs, and result record all share the
            # name), which is what makes cross-replica failover resume
            # instead of restart.
            if idem_key:
                job_id = idem_job_id(idem_key)
            elif payload.get("job_id") is not None:
                # Keyless jobs have no derivable id, so a router
                # failover resubmit passes the journaled job_id through
                # explicitly — the migrated checkpoint cursors and the
                # client's poll handle keep their names.
                explicit = payload["job_id"]
                if not isinstance(explicit, str) \
                        or not _JOB_ID_RE.fullmatch(explicit):
                    raise ValueError(
                        f"'job_id' must match {_JOB_ID_RE.pattern!r}, "
                        f"got {explicit!r}")
                job_id = explicit
            else:
                job_id = self._new_job_id()
        # Never journal the admission secrets or relay metadata: raw is
        # persisted verbatim to <state>/jobs/*.json (and re-sent on
        # failover, where the router attaches fresh auth/relay tokens
        # and the journal record's own submitted_at), so none of these
        # may outlive the admission check.
        raw = {k: v for k, v in payload.items()
               if k not in ("auth_token", "relay_token", "requeue",
                            "submitted_at", "router_epoch")}
        if submitted_at is None and self._trusted_requeue(payload):
            # Deadline-clock continuity across failover: the router's
            # journal migration resubmits with the ORIGINAL admission
            # time, so deadline_s keeps measuring from when the client
            # was acked — a replica death must never reset the clock
            # (honored only with a relay-token-proven requeue, so
            # ordinary clients cannot back- or forward-date their own
            # deadlines).
            sa = payload.get("submitted_at")
            if isinstance(sa, (int, float)) and not isinstance(sa, bool):
                submitted_at = float(sa)
        job = ServeJob(job_id=job_id, tenant=tenant,
                       cfg=cfg, variants=variants, raw=raw,
                       submitted_at=(time.time() if submitted_at is None
                                     else submitted_at),
                       priority=priority, deadline_s=deadline_s,
                       idem_key=idem_key)
        job.join_key = _join_key(cfg)
        return job

    def _quota_for(self, tenant: str) -> Optional[TenantQuota]:
        return self._quotas.get(tenant, self._quotas.get("*"))

    def _tenant_count(self, tenant: str, field: str) -> None:
        with self._lock:
            self._tenant_stats.setdefault(tenant, Counter())[field] += 1

    def _service_time_s(self) -> Optional[float]:
        """Mean observed per-job service time, None before the first
        completed batch (no evidence → shed_decision never sheds)."""
        with self._lock:
            times = list(self._service_times)
        if not times:
            return None
        return sum(times) / len(times)

    def admit(self, payload: dict,
              subscriber: Optional["queue.Queue"] = None) -> dict:
        """Admission control: validate + enqueue, or reject with a
        structured error. Returns the ``accepted``/``rejected`` event.

        Beyond validity and queue capacity, two SLO gates (both AFTER
        the idempotency dedup — a duplicate of an already-accepted job
        must re-ack, never be shed):

        - **tenant token bucket** (``--tenant-quotas``): an over-rate
          tenant gets a structured ``tenant_quota`` rejection carrying
          ``retry_after_s`` — exactly when the next token exists.
        - **deadline shed** (``--shed``): a deadlined job whose
          estimated wait (queue depth × observed mean service time)
          already exceeds ``deadline_s`` gets a structured ``shed``
          rejection with ``retry_after_s`` instead of an accept that is
          contractually doomed to ``deadline_exceeded``."""
        try:
            job = self._plan_job(payload)
        except (ValueError, TypeError, ManifestError) as e:
            self.metrics.emit("job_rejected", error="bad_job",
                              detail=str(e)[:300])
            return {"event": "rejected", "error": "bad_job",
                    "detail": str(e)[:500]}
        reserved = False
        if job.idem_key is not None:
            # Exactly-once ack: if this submission (same client-generated
            # idem_key) was already accepted by this state dir — maybe in
            # a previous daemon incarnation, maybe re-routed here after a
            # failover the client never saw — never run it twice: answer
            # with the ORIGINAL job_id; if it already finished, stream
            # the durable record so the caller needn't even poll. The
            # lookup and the reservation are ONE step under _idem_lock:
            # admit() runs on per-connection threads, and an unlocked
            # check-then-insert lets two concurrent same-key submits (a
            # client retrying after an ack timeout, a failover resubmit
            # racing a sticky retry) both miss the table and both run.
            with self._idem_lock:
                orig = self._idem.get(job.idem_key)
                if orig is None:
                    self._idem[job.idem_key] = job.job_id
                    reserved = True
            if not reserved:
                return self._deduped_ack(orig, job.tenant, subscriber)
        elif isinstance(payload.get("job_id"), str) \
                and self._has_durable_trace(job.job_id):
            # Keyless failover resubmit (explicit job_id, see _plan_job)
            # that this state dir already journaled, ran, or finished —
            # e.g. a router retrying a migration whose unlink raced a
            # crash. Same exactly-once answer as the idem path.
            return self._deduped_ack(job.job_id, job.tenant, subscriber)

        def _unreserve() -> None:
            if reserved:
                with self._idem_lock:
                    self._idem.pop(job.idem_key, None)

        if self._stop.is_set() or self._draining:
            _unreserve()
            return {"event": "rejected",
                    "error": ("draining" if self._draining
                              else "shutting_down"),
                    "job_id": job.job_id}
        if self._fenced():
            # Quarantined: the leader is migrating this state dir's
            # journal. Admitting now would journal a job the migration
            # can miss — the client must go to the survivor (dedup by
            # idem key makes the retry safe).
            _unreserve()
            return {"event": "rejected", "error": "fenced",
                    "job_id": job.job_id,
                    "detail": "replica is quarantined by the router's "
                              "fence marker; resubmit to the fleet"}
        # A failover/recovery resubmission (requeue=True + this
        # replica's relay_token, set only by the router's journal
        # migration) already paid the SLO gates when it was FIRST
        # admitted — the client holds an ack. Shedding or rate-limiting
        # it now would turn a replica death into a broken admission
        # contract: the job would sit journaled on the corpse until its
        # relaunch instead of migrating to a live survivor. Capacity
        # (queue_full) still applies — a full queue is a real resource
        # bound, and the router leaves the entry journaled for the
        # corpse's own recovery in that case. A requeue flag WITHOUT
        # the token degrades to a normal submit (all gates apply) —
        # that direction is safe, and loud so a router whose token read
        # failed shows up in the log instead of silently re-gating
        # already-acked migrations.
        requeue = self._trusted_requeue(payload)
        if payload.get("requeue") and not requeue:
            self.console(f"[serve] untrusted requeue flag on "
                         f"{job.job_id} ignored (no/bad relay_token)")
        quota = self._quota_for(job.tenant) if not requeue else None
        if quota is not None:
            now = time.time()
            with self._tenant_lock:
                bucket = self._buckets.get(job.tenant)
                if bucket is None:
                    bucket = TokenBucket(quota.rate, quota.burst)
                    self._buckets[job.tenant] = bucket
                allowed = bucket.take(now)
                retry_after = 0.0 if allowed else bucket.retry_after(now)
            if not allowed:
                _unreserve()
                self._tenant_count(job.tenant, "quota_rejected")
                self.metrics.bind_job(job.job_id).emit(
                    "tenant_quota", tenant=job.tenant,
                    retry_after_s=round(retry_after, 3))
                return {"event": "rejected", "error": "tenant_quota",
                        "tenant": job.tenant, "job_id": job.job_id,
                        "retry_after_s": round(retry_after, 3),
                        "detail": f"tenant {job.tenant!r} is over its "
                                  f"{quota.rate}/s rate limit "
                                  f"(burst {quota.burst:g})"}
        if self.opts.shed and not requeue:
            service = self._service_time_s()
            queued = self._queue.depth()
            retry_after = shed_decision(job.deadline_s, queued, service)
            if retry_after is not None:
                _unreserve()
                est_wait = queued * service
                self._tenant_count(job.tenant, "shed")
                self.metrics.bind_job(job.job_id).emit(
                    "shed", tenant=job.tenant,
                    retry_after_s=round(retry_after, 3),
                    est_wait_s=round(est_wait, 3))
                return {"event": "rejected", "error": "shed",
                        "tenant": job.tenant, "job_id": job.job_id,
                        "retry_after_s": round(retry_after, 3),
                        "est_wait_s": round(est_wait, 3),
                        "detail": f"estimated wait {est_wait:.1f}s "
                                  f"({queued} queued x {service:.2f}s/job) "
                                  f"exceeds deadline_s={job.deadline_s:g}"}
        job.subscriber = subscriber
        try:
            self._queue.push(job)
        except QueueFull:
            _unreserve()
            self.metrics.bind_job(job.job_id).emit(
                "job_rejected", error="queue_full", tenant=job.tenant)
            return {"event": "rejected", "error": "queue_full",
                    "detail": f"admission queue is at its "
                              f"--queue-depth cap ({self.opts.queue_depth})",
                    "queue_depth": self.opts.queue_depth,
                    "job_id": job.job_id}
        self._journal(job)
        self._tenant_count(job.tenant, "admitted")
        self._job_state(job.job_id, "queued", tenant=job.tenant,
                        priority=job.priority)
        self.metrics.bind_job(job.job_id).emit(
            "job_accepted", tenant=job.tenant, n_lanes=len(job.variants),
            priority=job.priority, queued=self._queue.depth())
        return {"event": "accepted", "job_id": job.job_id,
                "tenant": job.tenant, "n_lanes": len(job.variants),
                "priority": job.priority,
                "state_dir": self.opts.state_dir}

    def _deduped_ack(self, job_id: str, tenant: str,
                     subscriber: Optional["queue.Queue"]) -> dict:
        """The exactly-once duplicate answer: ack the ORIGINAL job_id,
        and if it already finished stream the durable record."""
        self.metrics.bind_job(job_id).emit("job_deduped", tenant=tenant)
        resp = {"event": "accepted", "job_id": job_id,
                "tenant": tenant, "deduped": True,
                "state_dir": self.opts.state_dir}
        if subscriber is not None:
            rec = self._read_result(job_id)
            if rec is not None:
                subscriber.put(rec)
            subscriber.put(None)
        return resp

    def _has_durable_trace(self, job_id: str) -> bool:
        """Whether this state dir already owns ``job_id`` — journaled
        (queued or running survives a relaunch), running, or terminally
        recorded. The keyless analogue of an _idem hit."""
        if os.path.exists(os.path.join(self._jobs_dir,
                                       f"{job_id}.json")) \
                or os.path.exists(os.path.join(self._results_dir,
                                               f"{job_id}.json")):
            return True
        with self._lock:
            return job_id in self._running

    # ---- journal / crash recovery ----------------------------------------

    def _journal(self, job: ServeJob) -> None:
        write_json_atomic(
            os.path.join(self._jobs_dir, f"{job.job_id}.json"),
            {"job_id": job.job_id, "tenant": job.tenant,
             "submitted_at": job.submitted_at, "payload": job.raw})

    def _unjournal(self, job: ServeJob) -> None:
        try:
            os.unlink(os.path.join(self._jobs_dir, f"{job.job_id}.json"))
        except OSError:
            pass

    def _read_result(self, job_id: str) -> Optional[dict]:
        import json

        path = os.path.join(self._results_dir, f"{job_id}.json")
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def journal_depth(self) -> int:
        """Accepted-but-unfinished jobs on disk — what a relaunch would
        re-queue, and what the router migrates off a dead replica."""
        try:
            return sum(1 for fn in os.listdir(self._jobs_dir)
                       if fn.endswith(".json"))
        except OSError:
            return 0

    def _recover_journal(self) -> None:
        """Re-queue every journaled (accepted, unfinished) job — the
        supervisor relaunch path. Jobs whose payload no longer validates
        (input files gone) fail with a result record instead of wedging
        the daemon."""
        import json

        recs = []
        for fn in os.listdir(self._jobs_dir):
            if not fn.endswith(".json"):
                continue
            try:
                with open(os.path.join(self._jobs_dir, fn)) as f:
                    recs.append(json.load(f))
            except (OSError, ValueError):
                self.console(f"[serve] dropping unreadable journal {fn}")
                os.unlink(os.path.join(self._jobs_dir, fn))
        for rec in sorted(recs, key=lambda r: r.get("submitted_at", 0.0)):
            job_id = rec.get("job_id", "?")
            if os.path.exists(os.path.join(self._results_dir,
                                           f"{job_id}.json")):
                # Exactly-once: the previous daemon died in the window
                # between writing the durable result and unlinking the
                # journal entry. The job finished — re-running it would
                # duplicate work (and terminal events).
                try:
                    os.unlink(os.path.join(self._jobs_dir,
                                           f"{job_id}.json"))
                except OSError:
                    pass
                self.metrics.bind_job(job_id).emit(
                    "job_recovered_complete")
                self.console(f"[serve] journal entry {job_id} already has "
                             f"a result record; dropping (exactly-once)")
                continue
            with self._lock:
                self._serial += 1      # keep new ids monotonic-ish
            try:
                job = self._plan_job(rec["payload"], job_id=job_id,
                                     submitted_at=rec.get("submitted_at"))
                self._queue.push(job)
            except (KeyError, ValueError, TypeError, ManifestError,
                    QueueFull) as e:
                self._finish_failed(
                    ServeJob(job_id=job_id, tenant=rec.get("tenant", "?"),
                             cfg=self._defaults, variants=[],
                             raw=rec.get("payload", {}),
                             submitted_at=rec.get("submitted_at", 0.0)),
                    f"requeue failed: {type(e).__name__}: {e}",
                    classified="fatal")
                continue
            self._job_state(job_id, "queued", tenant=job.tenant,
                            priority=job.priority, recovered=True)
            self.metrics.bind_job(job_id).emit("job_requeued",
                                               tenant=job.tenant)
            self.console(f"[serve] re-queued journaled job {job_id} "
                         f"(tenant {job.tenant!r})")

    # ---- job lifecycle ----------------------------------------------------

    def _job_state(self, job_id: str, state: str, **info) -> None:
        """One edge of the per-job state machine
        (queued → started → (checkpointed|resumed)* → terminal, where
        terminal ∈ {done, failed, cancelled, deadline_exceeded}; ``drained``
        marks a checkpoint-and-requeue pause, not an end state). Every edge
        lands in the metrics JSONL and the ``/status`` per-state counters.
        Runs on the scheduler thread AND connection threads (admit /
        cancel), racing the /status snapshot — hence the lock."""
        with self._lock:
            self._state_counts[state] += 1
        self.metrics.bind_job(job_id).emit("job_state", state=state, **info)

    def _cleanup_ckpt(self, job_id: str) -> None:
        """Drop a terminal job's streaming cursor directories (one per
        lane, named ``<job_id>.<variant>``) — a finished job must never
        leave a cursor a future same-id job could resume from."""
        for d in glob.glob(os.path.join(self._ckpt_dir, f"{job_id}.*")):
            shutil.rmtree(d, ignore_errors=True)

    def _finish_terminal(self, job: ServeJob, status: str,
                         detail: str) -> None:
        """Record a cancelled / deadline_exceeded terminal state: result
        record, journal removal, cursor cleanup, subscriber notice."""
        if self._fenced():
            # A fenced replica must not mint terminal records — the
            # survivor owns this job's fate now, and two terminal
            # states for one ack breaks exactly-once. Stay journaled.
            self._notify(job, {"event": "job_drained",
                               "job_id": job.job_id,
                               "note": "replica fenced; job stays "
                                       "journaled for migration"})
            self._notify(job, None)
            return
        record = {"event": f"job_{status}", "job_id": job.job_id,
                  "tenant": job.tenant, "status": status, "detail": detail,
                  "idem_key": job.idem_key,
                  "submitted_at": job.submitted_at,
                  "finished_at": time.time()}
        write_json_atomic(
            os.path.join(self._results_dir, f"{job.job_id}.json"), record)
        self._unjournal(job)
        self._cleanup_ckpt(job.job_id)
        with self._lock:
            self.jobs_failed += 1
        self._tenant_count(job.tenant, status)
        self._job_state(job.job_id, status, detail=detail)
        self._notify(job, record)
        self._notify(job, None)

    def cancel_job(self, job_id: str) -> dict:
        """Client-initiated cancel. A queued job dies immediately; a
        running job gets its cancel flag set and the trainers' check hook
        raises JobCancelled at the next shard/chunk boundary."""
        queued = self._queue.remove(job_id)
        if queued is not None:
            self._finish_terminal(queued, "cancelled",
                                  "cancelled while queued")
            return {"event": "cancelled", "job_id": job_id,
                    "where": "queued"}
        with self._lock:
            running = self._running.get(job_id)
        if running is not None:
            running.cancel_ev.set()
            self.metrics.bind_job(job_id).emit("job_cancel_requested")
            return {"event": "cancelling", "job_id": job_id,
                    "where": "running",
                    "note": "cooperative — takes effect at the next "
                            "shard/chunk boundary"}
        return {"event": "error", "error": f"unknown job {job_id!r} "
                                           f"(not queued, not running)"}

    def _begin_drain(self, source: str) -> None:
        """Graceful drain: stop admitting, let the in-flight batch hit its
        next boundary (where DrainRequested checkpoints streaming jobs and
        leaves everything journaled), then exit 0. Idempotent."""
        if self._draining:
            return
        self._draining = True
        self.metrics.emit("drain_begin", source=source,
                          queued=self._queue.depth(),
                          running=len(self._running))
        self.console(f"[serve] draining ({source}): admission closed, "
                     f"in-flight jobs checkpoint at the next boundary")
        from g2vec_tpu.resilience.faults import fault_point

        try:
            fault_point("drain")
        finally:
            self._stop.set()

    # ---- scheduling / execution ------------------------------------------

    def step(self, timeout: float = 0.2) -> int:
        """One scheduling cycle: pop the next job (tenant-fair), join every
        shape-compatible queued job into the same engine batch, execute,
        route results. Returns the number of jobs completed (0 = idle)."""
        if self._fenced():
            # Quarantined: leave the queue journaled and untouched for
            # the migration; starting a batch now is double execution.
            time.sleep(min(timeout, 0.2))
            return 0
        job = self._queue.pop(timeout=timeout)
        if job is None:
            return 0
        if job.update_of is not None:
            if job.cancel_ev.is_set():
                self._finish_terminal(job, "cancelled",
                                      "cancelled while queued")
                return 0
            if job.deadline_expired():
                self._finish_terminal(
                    job, "deadline_exceeded",
                    f"deadline_s={job.deadline_s} elapsed while queued")
                return 0
            return self._run_update_job(job)
        batch = [job] + self._queue.take_compatible(
            job.join_key, self.opts.max_join - 1)
        # Pre-execution lifecycle filter: a job cancelled or past its
        # deadline while queued terminates here, without costing a lane.
        live: List[ServeJob] = []
        for j in batch:
            if j.cancel_ev.is_set():
                self._finish_terminal(j, "cancelled",
                                      "cancelled while queued")
            elif j.deadline_expired():
                self._finish_terminal(
                    j, "deadline_exceeded",
                    f"deadline_s={j.deadline_s} elapsed while queued")
            else:
                live.append(j)
        if not live:
            return 0
        return self._run_jobs(live)

    def _notify(self, job: ServeJob, event: Optional[dict]) -> None:
        q = job.subscriber
        if q is not None:
            q.put(event)

    def _run_jobs(self, batch: List[ServeJob]) -> int:
        self._batches += 1
        bid = self._batches
        with self._lock:
            self._running.update({j.job_id: j for j in batch})
        merged: List[LaneVariant] = []
        lane_jobs: List[str] = []
        lane_owner: List[Tuple[ServeJob, LaneVariant]] = []
        for j in batch:
            for v in j.variants:
                merged.append(dataclasses.replace(
                    v, index=len(merged), name=f"{j.job_id}.{v.name}"))
                lane_jobs.append(j.job_id)
                lane_owner.append((j, v))
        spool = os.path.join(self._spool_dir, f"batch{bid}")
        exec_cfg = dataclasses.replace(
            batch[0].cfg, result_name=os.path.join(spool, "out"),
            metrics_jsonl=None, manifest=None, batch_seeds=0)
        if exec_cfg.train_mode == "streaming":
            # Durable streaming: every lane checkpoints its cursor under
            # <state-dir>/ckpt/<job_id>.<variant> and resumes from it on a
            # journal re-queue (the lane names are restart-stable).
            exec_cfg = dataclasses.replace(
                exec_cfg, checkpoint_dir=self._ckpt_dir, resume=True)
        self.metrics.emit("batch_start", batch=bid,
                          jobs=[j.job_id for j in batch],
                          n_lanes=len(merged))
        for j in batch:
            self._job_state(j.job_id, "started", batch=bid,
                            attempt=j.attempts)
            self._notify(j, {"event": "started", "job_id": j.job_id,
                             "batch": bid, "joined_jobs": len(batch),
                             "n_lanes": len(j.variants)})

        def check() -> None:
            """Cooperative-interruption hook (resilience/lifecycle.py):
            the trainers call this at shard/chunk boundaries, the only
            points where stopping leaves a consistent, checkpointable
            state."""
            if self._draining:
                raise DrainRequested(detail="daemon drain")
            if self._fenced():
                # Self-quarantine park: the batch checkpoints at this
                # boundary and every job stays journaled — the fenced
                # replica must never finish work whose journal the
                # leader is migrating to a survivor.
                raise DrainRequested(detail="fenced by router")
            now = time.time()
            for j in batch:
                if j.cancel_ev.is_set():
                    raise JobCancelled(j.job_id)
                if j.deadline_expired(now):
                    raise JobDeadlineExceeded(
                        j.job_id, detail=f"deadline_s={j.deadline_s}")

        def lifecycle(job_id: str, state: str, info: dict) -> None:
            self._job_state(job_id, state,
                            **{k: info[k] for k in ("epoch", "shard", "done")
                               if k in info})

        t0 = time.time()
        try:
            res = self.engine.execute(exec_cfg, merged,
                                      console=self.console,
                                      metrics=self.metrics,
                                      lane_jobs=lane_jobs,
                                      check=check, lifecycle=lifecycle)
        except JobInterrupted as e:
            self._handle_interrupt(batch, e, bid, spool)
            return 0
        except BaseException as e:  # noqa: BLE001 — classified below
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            from g2vec_tpu.resilience.supervisor import classify_exception

            verdict = classify_exception(e)
            err = f"{type(e).__name__}: {e}"[:500]
            self.console(f"[serve] batch {bid} failed ({verdict}): {err}")
            for j in batch:
                self._fail_or_requeue(j, err, verdict)
            shutil.rmtree(spool, ignore_errors=True)
            with self._lock:
                for j in batch:
                    self._running.pop(j.job_id, None)
            return 0

        wall = time.time() - t0
        if self._fenced():
            # The marker landed after the last boundary check but
            # before the terminal write: publishing now would hand the
            # client a result the survivor may also produce. Park the
            # whole batch exactly as a drain would — journaled, no
            # record, no inventory — and let idem dedup on the survivor
            # keep the accounting exactly-once.
            shutil.rmtree(spool, ignore_errors=True)
            for j in batch:
                self._job_state(j.job_id, "drained", batch=bid)
                self._notify(j, {"event": "job_drained",
                                 "job_id": j.job_id,
                                 "note": "replica fenced; job stays "
                                         "journaled for migration"})
                self._notify(j, None)
            with self._lock:
                for j in batch:
                    self._running.pop(j.job_id, None)
            return 0
        # The shed estimator's evidence: one completed batch contributes
        # its per-job share of the wall (joined jobs amortize the batch).
        with self._lock:
            self._service_times.append(wall / max(1, len(batch)))
        by_job: Dict[str, Dict] = {}
        for (j, v), lane in zip(lane_owner, res.lanes):
            outs = self._route_outputs(j, v, lane)
            self._publish_inventory(j, v, lane)
            by_job.setdefault(j.job_id, {})[v.name] = {
                "outputs": outs, "stop_epoch": len(lane.train_history),
                "acc_val": lane.acc_val}
            self._notify(j, {"event": "lane_done", "job_id": j.job_id,
                             "variant": v.name, "outputs": outs,
                             "acc_val": lane.acc_val})
        shutil.rmtree(spool, ignore_errors=True)
        now = time.time()
        for j in batch:
            record = {"event": "job_done", "job_id": j.job_id,
                      "tenant": j.tenant, "status": "done",
                      "idem_key": j.idem_key,
                      "variants": by_job.get(j.job_id, {}),
                      "batch": bid, "joined_jobs": len(batch),
                      "batch_wall_seconds": round(wall, 3),
                      "latency_seconds": round(now - j.submitted_at, 3),
                      "submitted_at": j.submitted_at, "finished_at": now}
            write_json_atomic(
                os.path.join(self._results_dir, f"{j.job_id}.json"), record)
            self._unjournal(j)
            self._cleanup_ckpt(j.job_id)
            with self._lock:
                self.jobs_done += 1
            self._tenant_count(j.tenant, "done")
            self._job_state(j.job_id, "done", batch=bid)
            self.metrics.bind_job(j.job_id).emit(
                "job_done", tenant=j.tenant, batch=bid,
                joined_jobs=len(batch),
                latency_seconds=record["latency_seconds"])
            self._notify(j, record)
            self._notify(j, None)
        with self._lock:
            for j in batch:
                self._running.pop(j.job_id, None)
        self.console(f"[serve] batch {bid}: {len(batch)} job(s), "
                     f"{len(merged)} lane(s) in {wall:.2f}s "
                     f"({res.runs_per_hour:.0f} runs/hour)")
        return len(batch)

    def _handle_interrupt(self, batch: List[ServeJob], exc: JobInterrupted,
                          bid: int, spool: str) -> None:
        """A cooperative interruption surfaced from the trainers.

        - DrainRequested: every job in the batch stays journaled (streaming
          lanes just checkpointed their cursors); the restart re-queues and
          resumes them. No terminal record is written — the job is paused,
          not over.
        - JobCancelled / JobDeadlineExceeded: the culprit job (named by
          ``exc.job_id``) terminates; innocent batch-mates re-queue WITHOUT
          an attempt charge — they did nothing wrong, the batch did.
        """
        shutil.rmtree(spool, ignore_errors=True)
        if isinstance(exc, DrainRequested):
            for j in batch:
                self._job_state(j.job_id, "drained", batch=bid)
                self._notify(j, {"event": "job_drained",
                                 "job_id": j.job_id,
                                 "note": "daemon draining; job stays "
                                         "journaled and resumes on the "
                                         "next start"})
                self._notify(j, None)
            self.console(f"[serve] batch {bid} drained "
                         f"({len(batch)} job(s) checkpointed + journaled)")
        else:
            for j in batch:
                if j.job_id == exc.job_id:
                    self._finish_terminal(j, exc.reason, str(exc))
                    continue
                try:
                    self._queue.push(j)
                    self._job_state(j.job_id, "queued",
                                    requeued_after=exc.reason)
                except QueueFull:
                    self._finish_failed(
                        j, f"requeue after batch-mate "
                           f"{exc.reason} found the queue full", "fatal")
        with self._lock:
            for j in batch:
                self._running.pop(j.job_id, None)

    def _run_update_job(self, job: ServeJob) -> int:
        """One ``update`` job: delta re-walk + warm-start fine-tune
        (incremental.run_update) against the target bundle's live
        generation, then a generation-atomic republish. Shares the
        submit lifecycle — journaled until the durable record lands
        (SIGKILL replays from the journal), parks when fenced, honors
        cancel/deadline/drain at the engine's check boundaries."""
        with self._lock:
            self._running[job.job_id] = job
        try:
            return self._run_update_inner(job)
        finally:
            with self._lock:
                self._running.pop(job.job_id, None)

    def _run_update_inner(self, job: ServeJob) -> int:
        key, err = self._resolve_bundle(job.update_of,
                                        job.update_variant)
        if err is not None:
            self._fail_or_requeue(
                job, f"update target: {err.get('error')}: "
                     f"{err.get('detail')}", "fatal")
            return 0
        path = self._inv_known.get(key)
        self._job_state(job.job_id, "started", update_of=key,
                        attempt=job.attempts)
        self._notify(job, {"event": "started", "job_id": job.job_id,
                           "update_of": key,
                           "epochs": job.update_epochs})

        def check() -> None:
            if self._draining:
                raise DrainRequested(detail="daemon drain")
            if self._fenced():
                raise DrainRequested(detail="fenced by router")
            if job.cancel_ev.is_set():
                raise JobCancelled(job.job_id)
            if job.deadline_expired():
                raise JobDeadlineExceeded(
                    job.job_id, detail=f"deadline_s={job.deadline_s}")

        from g2vec_tpu.cache import resolve_cache_tiers
        from g2vec_tpu.incremental import run_update

        _, wc = resolve_cache_tiers(
            job.cfg.cache_dir or self.opts.cache_dir, None,
            job.cfg.walk_cache)
        t0 = time.time()
        try:
            res = run_update(
                job.cfg, path, walk_cache=wc,
                epochs=job.update_epochs, console=self.console,
                check=check,
                emit=lambda kind, **f: self.metrics.emit(
                    kind, bundle=key, job_id=job.job_id, **f))
        except JobInterrupted as e:
            if isinstance(e, DrainRequested):
                self._job_state(job.job_id, "drained", update_of=key)
                self._notify(job, {"event": "job_drained",
                                   "job_id": job.job_id,
                                   "note": "update stays journaled and "
                                           "re-runs on the next start"})
                self._notify(job, None)
            else:
                self._finish_terminal(job, e.reason, str(e))
            return 0
        except BaseException as e:  # noqa: BLE001 — classified below
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                raise
            from g2vec_tpu.resilience.supervisor import classify_exception

            err_s = f"{type(e).__name__}: {e}"[:500]
            self.console(f"[serve] update {job.job_id} failed: {err_s}")
            self._fail_or_requeue(job, err_s, classify_exception(e))
            return 0
        wall = time.time() - t0
        if self._fenced():
            # Between the last check boundary and the publish: the
            # survivor owns this update now. No pointer flip, no record.
            self._job_state(job.job_id, "drained", update_of=key)
            self._notify(job, {"event": "job_drained",
                               "job_id": job.job_id,
                               "note": "replica fenced; update stays "
                                       "journaled for migration"})
            self._notify(job, None)
            return 0
        from g2vec_tpu.io.writers import write_inventory_bundle

        try:
            gen_dir = write_inventory_bundle(
                path, res.embeddings, res.genes, res.biomarker_scores,
                {"source": "update", "job_id": job.update_of,
                 "variant": job.update_variant, "tenant": job.tenant,
                 "updated_by": job.job_id, "mode": res.stats["mode"]},
                ann_nlist=self.opts.ann_nlist,
                seed_centroids=res.km_centers,
                extra_files={"delta_fingerprints.json":
                             res.fingerprints})
        except (OSError, ValueError) as e:
            # Unlike a submit's best-effort publish, republication IS
            # the update's deliverable — failure fails the job.
            self._fail_or_requeue(
                job, f"republish failed: {type(e).__name__}: {e}"[:500],
                "retryable" if isinstance(e, OSError) else "fatal")
            return 0
        generation = os.path.basename(gen_dir)
        # The invalidation triple (same order as _publish_inventory):
        # readers re-map the new generation, every cached answer keyed
        # to the old generation becomes unreachable, resolution rescans.
        self.catalog.invalidate(key)
        self.qcache.invalidate_bundle(key)
        self._inv_known = {}
        self.metrics.emit(
            "republish", bundle=key, generation=generation,
            mode=res.stats["mode"],
            bytes=sum(os.path.getsize(os.path.join(gen_dir, fn))
                      for fn in os.listdir(gen_dir)))
        self._emit_ann_build(key, gen_dir)
        now = time.time()
        acc = res.acc_val
        record = {"event": "job_done", "job_id": job.job_id,
                  "tenant": job.tenant, "status": "done",
                  "idem_key": job.idem_key, "update_of": key,
                  "generation": generation, "stats": res.stats,
                  "acc_val": (None if acc != acc else round(acc, 6)),
                  "wall_seconds": round(wall, 3),
                  "latency_seconds": round(now - job.submitted_at, 3),
                  "submitted_at": job.submitted_at, "finished_at": now}
        write_json_atomic(
            os.path.join(self._results_dir, f"{job.job_id}.json"),
            record)
        self._unjournal(job)
        self._cleanup_ckpt(job.job_id)
        with self._lock:
            self.jobs_done += 1
        self._tenant_count(job.tenant, "done")
        self._job_state(job.job_id, "done", update_of=key)
        self.metrics.emit("update", bundle=key, job_id=job.job_id,
                          generation=generation, **res.stats)
        self._notify(job, record)
        self._notify(job, None)
        self.console(f"[serve] update {job.job_id} -> {key} "
                     f"({generation}, mode={res.stats['mode']}, "
                     f"walked={res.stats['walked_rows']}) in {wall:.2f}s")
        return 1

    def _route_outputs(self, job: ServeJob, v: LaneVariant, lane) -> List[str]:
        """Move a lane's spool files to the job's requested result_name —
        a rename, so served bytes ARE the engine's lane bytes."""
        dest_dir = os.path.dirname(job.cfg.result_name)
        if dest_dir:
            os.makedirs(dest_dir, exist_ok=True)
        outs = []
        for f in lane.output_files:
            suffix = f.rsplit("_", 1)[1]        # biomarkers|lgroups|vectors
            dest = f"{job.cfg.result_name}.{v.name}_{suffix}"
            shutil.move(f, dest)
            outs.append(dest)
        return outs

    def _publish_inventory(self, job: ServeJob, v: LaneVariant,
                           lane) -> None:
        """Publish the lane's query-plane bundle under
        ``<state>/inventory/<job_id>/<variant>/``. Publication failure
        is a metrics event, never a job failure — the durable record
        and the text outputs stay the source of truth, and the bundle
        can be lazily rebuilt from them (:meth:`_republish`)."""
        from g2vec_tpu.io.writers import write_inventory_bundle

        key = f"{job.job_id}/{v.name}"
        if self._fenced():
            # Belt over the _run_jobs park: a fenced replica must not
            # publish query-plane bytes the survivor will re-derive.
            self.metrics.emit("inventory", bundle=key, bytes=0,
                              outcome="skipped", error="replica fenced")
            return
        dest = os.path.join(self._inventory_dir, job.job_id, v.name)
        if lane.embeddings is None:
            self.metrics.emit("inventory", bundle=key, bytes=0,
                              outcome="skipped",
                              error="lane carried no embedding table")
            return
        try:
            gen_dir = write_inventory_bundle(
                dest, lane.embeddings, list(lane.genes),
                lane.biomarker_scores,
                {"source": "serve", "job_id": job.job_id,
                 "variant": v.name, "tenant": job.tenant},
                ann_nlist=self.opts.ann_nlist,
                # Stage-5 k-means centers seed the IVF coarse quantizer
                # for free when the engine carried them through.
                seed_centroids=getattr(lane, "km_centers", None))
        except (OSError, ValueError) as e:
            self.metrics.emit("inventory", bundle=key, bytes=0,
                              outcome="publish_failed",
                              error=f"{type(e).__name__}: {e}"[:200])
            return
        # A re-run of the same job_id replaces the bundle: drop any
        # stale mapping + cached results so readers see the new bytes,
        # and reset the resolution cache so the new key is visible
        # (and omitted-variant auto-resolve stays exact).
        self.catalog.invalidate(key)
        self.qcache.invalidate_bundle(key)
        self._inv_known = {}
        self.metrics.emit(
            "inventory", bundle=key,
            bytes=sum(os.path.getsize(os.path.join(gen_dir, fn))
                      for fn in os.listdir(gen_dir)),
            generation=os.path.basename(gen_dir),
            outcome="published")
        self._emit_ann_build(key, gen_dir)

    def _emit_ann_build(self, key: str, dest: str) -> None:
        """One ``ann_build`` event per publication, read back from the
        sealed bundle's meta.json so what is reported is what was
        actually published (including the no-index case)."""
        import json as _json

        try:
            with open(os.path.join(dest, "meta.json")) as f:
                meta = _json.load(f)
        except (OSError, ValueError):
            meta = {}
        ann = meta.get("ann")
        if ann:
            self.metrics.emit("ann_build", bundle=key,
                              nlist=ann.get("nlist"), outcome="built",
                              ms=ann.get("build_ms"),
                              seeded=ann.get("seeded"),
                              postings=meta.get("n_genes"))
        else:
            self.metrics.emit("ann_build", bundle=key, nlist=0,
                              outcome="skipped")

    def _republish(self, job_id: str, key: str) -> bool:
        """Rebuild a lost/torn/tampered bundle from the durable
        record's ``_vectors.txt`` output. Partial by design: the
        ``[2, G]`` score matrix is not recoverable from text outputs,
        so the republished bundle answers ``neighbors``/``meta`` but
        ``topk_biomarkers`` returns ``scores_unavailable``."""
        variant = key.split("/", 1)[1] if "/" in key else None
        rec = self._read_result(job_id)
        vec_path = None
        if rec is not None and variant is not None:
            outs = rec.get("variants", {}).get(variant, {}) \
                      .get("outputs", [])
            vec_path = next((p for p in outs
                             if p.endswith("_vectors.txt")), None)
        if vec_path is None or not os.path.exists(vec_path):
            self.metrics.emit("inventory", bundle=key, bytes=0,
                              outcome="republish_unavailable")
            return False
        from g2vec_tpu.io.writers import write_inventory_bundle

        dest = os.path.join(self._inventory_dir, job_id, variant)
        try:
            genes, emb = inventory.read_vectors_txt(vec_path)
            # The index is rebuilt too (no seed centroids — they are
            # not recoverable from text outputs, so the deterministic
            # row seeding applies): a republished bundle must not
            # silently lose its approximate path.
            gen_dir = write_inventory_bundle(
                dest, emb, genes, None,
                {"source": "republish", "job_id": job_id,
                 "variant": variant,
                 "from": os.path.basename(vec_path)},
                ann_nlist=self.opts.ann_nlist)
        except (OSError, ValueError) as e:
            self.metrics.emit("inventory", bundle=key, bytes=0,
                              outcome="republish_failed",
                              error=f"{type(e).__name__}: {e}"[:200])
            return False
        self.catalog.invalidate(key)
        self.qcache.invalidate_bundle(key)
        self._inv_known = {}
        self.metrics.emit(
            "inventory", bundle=key,
            bytes=sum(os.path.getsize(os.path.join(gen_dir, fn))
                      for fn in os.listdir(gen_dir)),
            generation=os.path.basename(gen_dir),
            outcome="republished")
        self._emit_ann_build(key, gen_dir)
        return True

    def _fail_or_requeue(self, job: ServeJob, err: str,
                         classified: str) -> None:
        if classified == "retryable" and job.attempts < self.opts.job_retries:
            job.attempts += 1
            try:
                self._queue.push(job)
            except QueueFull:
                self._finish_failed(job, f"{err} (retry queue full)",
                                    classified)
                return
            self._job_state(job.job_id, "queued", retry=job.attempts)
            self.metrics.bind_job(job.job_id).emit(
                "job_retry", attempt=job.attempts, error=err)
            self._notify(job, {"event": "job_retry", "job_id": job.job_id,
                               "attempt": job.attempts, "error": err})
            return
        self._finish_failed(job, err, classified)

    def _finish_failed(self, job: ServeJob, err: str,
                       classified: str) -> None:
        if self._fenced():
            # Same contract as _finish_terminal: no terminal records
            # after fencing — the job stays journaled for the survivor.
            self._notify(job, {"event": "job_drained",
                               "job_id": job.job_id,
                               "note": "replica fenced; job stays "
                                       "journaled for migration"})
            self._notify(job, None)
            return
        record = {"event": "job_failed", "job_id": job.job_id,
                  "tenant": job.tenant, "status": "failed", "error": err,
                  "idem_key": job.idem_key, "classified": classified,
                  "submitted_at": job.submitted_at,
                  "finished_at": time.time()}
        write_json_atomic(
            os.path.join(self._results_dir, f"{job.job_id}.json"), record)
        self._unjournal(job)
        self._cleanup_ckpt(job.job_id)
        with self._lock:
            self.jobs_failed += 1
        self._tenant_count(job.tenant, "failed")
        self._job_state(job.job_id, "failed", classified=classified)
        self.metrics.bind_job(job.job_id).emit("job_failed", error=err,
                                               classified=classified)
        self._notify(job, record)
        self._notify(job, None)

    # ---- query plane ------------------------------------------------------

    def _resolve_bundle(self, job_id: str, variant) \
            -> Tuple[Optional[str], Optional[dict]]:
        """Resolve against the cached disk view; only a resolution
        that FAILS on the cache pays a rescan (then retries once on
        the fresh view). Keeps the warm query path free of directory
        walks without ever turning a publishable answer into an
        error."""
        key, err = inventory.resolve_bundle_key(
            self._inv_known, job_id, variant)
        if err is None:
            return key, None
        known = inventory.scan_bundles(self.catalog.roots)
        self._inv_known = known
        return inventory.resolve_bundle_key(known, job_id, variant)

    def handle_query(self, qreq: dict) -> dict:
        """The read plane: one ``query`` sub-op (inventory.QUERY_SUBOPS)
        against this replica's bundles, behind the keyed result cache.
        A torn/tampered bundle is lazily republished from the durable
        record's text outputs and the query retried once — corruption
        costs latency, never a wrong answer."""
        q = qreq.get("q")
        t0 = time.time()
        if q == "list":
            resp = {"event": "query_result", "q": "list",
                    "bundles": self.catalog.listing()}
            self.metrics.emit("query", q="list", cache="none",
                              ms=round((time.time() - t0) * 1e3, 3))
            return resp
        if q not in inventory.QUERY_SUBOPS:
            return {"event": "error", "error": "bad_query",
                    "detail": f"unknown sub-op {q!r}; expected one of "
                              f"{inventory.QUERY_SUBOPS}"}
        job_id = qreq.get("job_id")
        if not isinstance(job_id, str) or not job_id:
            return {"event": "error", "error": "bad_query",
                    "detail": "query needs a 'job_id' string"}
        key, err = self._resolve_bundle(job_id, qreq.get("variant"))
        if err is not None:
            return err
        gene = qreq.get("gene")
        if gene is not None and not isinstance(gene, str):
            return {"event": "error", "error": "bad_query",
                    "detail": f"'gene' must be a string, got {gene!r}"}
        k = qreq.get("k", 10)
        if not isinstance(k, int) or isinstance(k, bool):
            return {"event": "error", "error": "bad_query",
                    "detail": f"'k' must be an int, got {k!r}"}
        mode = qreq.get("mode", "approx")
        if mode not in inventory.QUERY_MODES:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'mode' must be one of "
                              f"{inventory.QUERY_MODES}, got {mode!r}"}
        nprobe = qreq.get("nprobe", 0)
        if not isinstance(nprobe, int) or isinstance(nprobe, bool) \
                or nprobe < 0:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'nprobe' must be a non-negative int, "
                              f"got {nprobe!r}"}

        def compute() -> dict:
            try:
                return inventory.run_query(self.catalog, q, key,
                                           gene=gene, k=k, mode=mode,
                                           nprobe=nprobe)
            except inventory.InventoryError as e:
                if e.code in ("torn", "tampered") \
                        and self._republish(job_id, key):
                    return inventory.run_query(self.catalog, q, key,
                                               gene=gene, k=k,
                                               mode=mode, nprobe=nprobe)
                raise

        try:
            # The generation joins the cache key: a republish flips the
            # pointer, which changes every key, which makes any cached
            # pre-flip answer structurally unreachable — the cache can
            # never serve a stale generation (tests/test_update.py).
            resp, was_hit = self.qcache.get_or_put(
                inventory.cache_key(key, q, gene, k, mode, nprobe,
                                    self.catalog.generation(key)),
                compute)
        except inventory.InventoryError as e:
            self.metrics.emit("query", q=q, cache="miss", bundle=key,
                              ms=round((time.time() - t0) * 1e3, 3),
                              error=e.code)
            return {"event": "error", "error": e.code,
                    "detail": e.detail, "job_id": job_id, "bundle": key}
        out = dict(resp)
        out["event"] = "query_result"
        self.metrics.emit("query", q=q,
                          cache="hit" if was_hit else "miss", bundle=key,
                          ms=round((time.time() - t0) * 1e3, 3),
                          mode=mode, recall_mode=out.get("recall_mode"))
        return out

    def handle_fquery(self, fqreq: dict) -> dict:
        """The federated read plane, single-replica flavor: one
        ``fquery`` sub-op (inventory.FQUERY_SUBOPS) across EVERY bundle
        this replica serves. The router scatter-gathers this very
        handler across the fleet and merges; standalone daemons answer
        directly with the same shape (minus cross-replica
        attribution)."""
        t0 = time.time()
        fq = fqreq.get("fq")
        gene = fqreq.get("gene")
        if not isinstance(gene, str) or not gene:
            return {"event": "error", "error": "bad_query",
                    "detail": "fquery needs a 'gene' string"}
        k = fqreq.get("k", 50)
        if not isinstance(k, int) or isinstance(k, bool):
            return {"event": "error", "error": "bad_query",
                    "detail": f"'k' must be an int, got {k!r}"}
        mode = fqreq.get("mode", "approx")
        nprobe = fqreq.get("nprobe", 0)
        if not isinstance(nprobe, int) or isinstance(nprobe, bool) \
                or nprobe < 0:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'nprobe' must be a non-negative int, "
                              f"got {nprobe!r}"}
        ref_genes = fqreq.get("ref_genes")
        if ref_genes is not None and not (
                isinstance(ref_genes, list)
                and all(isinstance(g, str) for g in ref_genes)):
            return {"event": "error", "error": "bad_query",
                    "detail": "'ref_genes' must be a list of strings"}
        if fq == "bundle_overlap" and not ref_genes:
            # Standalone convenience: derive the reference neighbor set
            # from the named bundle so a single-daemon client need not
            # run two requests. The router resolves this itself and
            # always forwards ref_genes.
            job_id = fqreq.get("job_id")
            if not isinstance(job_id, str) or not job_id:
                return {"event": "error", "error": "bad_query",
                        "detail": "bundle_overlap needs 'ref_genes' or "
                                  "a reference 'job_id'"}
            ref_key, err = self._resolve_bundle(job_id,
                                                fqreq.get("variant"))
            if err is not None:
                return err
            try:
                ref_resp = inventory.run_query(
                    self.catalog, "neighbors", ref_key, gene=gene, k=k,
                    mode=mode, nprobe=nprobe)
            except inventory.InventoryError as e:
                return {"event": "error", "error": e.code,
                        "detail": e.detail, "bundle": ref_key}
            ref_genes = ref_resp["neighbors"]
        try:
            partials = inventory.run_fquery(
                self.catalog, fq, gene, k=k, mode=mode, nprobe=nprobe,
                ref_genes=ref_genes)
        except inventory.InventoryError as e:
            self.metrics.emit("fquery", fq=fq,
                              ms=round((time.time() - t0) * 1e3, 3),
                              error=e.code)
            return {"event": "error", "error": e.code, "detail": e.detail}
        self.metrics.emit("fquery", fq=fq,
                          ms=round((time.time() - t0) * 1e3, 3),
                          bundles=len(partials))
        return {"event": "fquery_result", "fq": fq, "gene": gene,
                "k": k, "mode": mode,
                "bundles": inventory.merge_fquery(fq, partials),
                "ref_genes": ref_genes if fq == "bundle_overlap"
                else None}

    # ---- status -----------------------------------------------------------

    def status(self) -> dict:
        """The warm-state + queue inventory (the ``/status`` payload)."""
        from g2vec_tpu.cache import cache_stats

        with self._lock:
            running = sorted(self._running)
            # One consistent snapshot: copying the Counter while a
            # connection thread bumps it can RuntimeError mid-iteration.
            job_states = dict(self._state_counts)
            jobs_done, jobs_failed = self.jobs_done, self.jobs_failed
            service_times = list(self._service_times)
            tenants = {t: dict(c) for t, c in self._tenant_stats.items()}
        with self._epoch_lock:
            router_epoch = self._router_epoch
        service = (round(sum(service_times) / len(service_times), 3)
                   if service_times else None)
        return {"event": "status", "pid": os.getpid(),
                "uptime_s": round(time.time() - self._t0, 1),
                "socket": self.opts.socket_path,
                "listen": (f"{self.tcp_addr[0]}:{self.tcp_addr[1]}"
                           if self.tcp_addr else None),
                "state_dir": self.opts.state_dir,
                #: Scheduler-loop liveness. Observability only — this age
                #: grows through any long batch (step() blocks in the
                #: engine), so the router's health machine keys on probe
                #: reachability, never on this number.
                "last_heartbeat_age_s": round(time.time()
                                              - self._last_beat, 3),
                "journal_depth": self.journal_depth(),
                "queued": self._queue.depth(), "running": running,
                "queued_by_priority": self._queue.depths(),
                "draining": self._draining,
                #: Leadership-fencing plane: the highest router epoch
                #: witnessed and whether the leader has quarantined
                #: this state dir (serve/leader.py fence marker).
                "router_epoch": router_epoch,
                "fenced": self._fenced(),
                "job_states": job_states,
                "queue_depth_limit": self.opts.queue_depth,
                "max_join": self.opts.max_join,
                "jobs_done": jobs_done,
                "jobs_failed": jobs_failed,
                #: Admission-SLO plane: the shed estimator's current
                #: evidence plus the per-tenant ledger the router sums
                #: into its fleet-wide /status aggregate.
                "service_time_s": service,
                "shed_enabled": self.opts.shed,
                "tenants": tenants,
                "engine": self.engine.status(),
                "cache": cache_stats(),
                "inventory": {**self.catalog.stats(),
                              "query_cache": self.qcache.stats()}}

    # ---- socket front-end -------------------------------------------------

    def _handle_conn(self, conn: "socket.socket") -> None:
        # Per-connection deadline: bounds the request read AND any later
        # send to a client that stopped reading its event stream. A
        # stalled or byte-trickling peer costs one thread for at most
        # read_deadline_s, never forever (the PR 11 front-door contract,
        # applied to the UNIX listener too).
        try:
            conn.settimeout(self.opts.read_deadline_s)
        except OSError:
            pass
        max_bytes = self.opts.max_request_bytes or protocol.MAX_LINE_BYTES
        f = conn.makefile("rwb")
        try:
            try:
                first = f.readline(max_bytes + 1)
            except socket.timeout:
                return                      # stalled before a full request
            if not first:
                return
            if len(first) > max_bytes and not first.endswith(b"\n"):
                protocol.write_event(
                    f, {"event": "error", "error": "oversized_request",
                        "detail": f"request line exceeds the "
                                  f"{max_bytes}-byte bound"})
                return
            if first.startswith(b"GET "):
                self._serve_http(f, first)
                return
            import json

            try:
                req = json.loads(first)
                if not isinstance(req, dict):
                    raise ValueError("request must be a JSON object")
            except ValueError as e:
                protocol.write_event(f, {"event": "error",
                                         "error": f"bad request: {e}"})
                return
            op = req.get("op")
            if self.opts.auth_token is not None \
                    and op in ("submit", "update", "cancel", "drain",
                               "shutdown", "query", "fquery") \
                    and req.get("auth_token") != self.opts.auth_token:
                # Tenancy is checked AT ADMISSION: a mutating op without
                # the shared secret never reaches planning or the queue.
                # ``query`` is a READ but still gated — it exposes
                # tenant embeddings/scores, not just health. status/
                # ping stay open — the router's health probes (and any
                # curl) must not need credentials.
                self.metrics.emit("auth_rejected", op=op)
                protocol.write_event(
                    f, {"event": "rejected", "error": "unauthorized",
                        "detail": f"op {op!r} requires a valid "
                                  f"'auth_token' on this listener"})
                return
            if op in ("submit", "update", "cancel", "drain", "shutdown"):
                # Fencing gate, mutating ops only: a command stamped
                # with a superseded leadership epoch comes from a
                # zombie ex-leader — reject it structurally so the
                # zombie stops fencing/migrating. Reads (status, ping,
                # result, query) stay open to everyone: a stale router
                # observing the fleet is harmless and useful.
                stale = self._observe_epoch(req)
                if stale is not None:
                    protocol.write_event(f, stale)
                    return
            if op in ("submit", "update"):
                # ``update`` is a write: it rides the submit pipeline
                # (idem dedup, quotas, journal, event stream) and is
                # told apart at planning by its op field.
                sub: "queue.Queue" = queue.Queue()
                resp = self.admit(req, subscriber=sub)
                protocol.write_event(f, resp)
                if resp["event"] != "accepted":
                    return
                while True:
                    ev = sub.get()
                    if ev is None:
                        break
                    protocol.write_event(f, ev)
            elif op == "status":
                protocol.write_event(f, self.status())
            elif op == "ping":
                protocol.write_event(f, {"event": "pong",
                                         "pid": os.getpid()})
            elif op == "result":
                # Durable-record lookup: the network recovery path after
                # a lost stream (client.poll_result_net) — works without
                # filesystem access to the state dir. Bounded: the
                # response honors the client's fields/max_bytes and the
                # server's --max-result-bytes cap (protocol.bound_record)
                # instead of streaming the whole record unconditionally.
                rreq = req
                job_id = rreq.get("job_id")
                if not isinstance(job_id, str) or not job_id:
                    protocol.write_event(
                        f, {"event": "error",
                            "error": "result needs a 'job_id' string"})
                else:
                    rec = self._read_result(job_id)
                    if rec is None:
                        protocol.write_event(
                            f, {"event": "pending", "job_id": job_id,
                                "journaled": os.path.exists(os.path.join(
                                    self._jobs_dir, f"{job_id}.json"))})
                    else:
                        protocol.write_event(f, protocol.bound_record(
                            rec, rreq.get("fields"),
                            rreq.get("max_bytes"),
                            self.opts.max_result_bytes
                            or protocol.MAX_LINE_BYTES))
            elif op == "query":
                qreq = req
                protocol.write_event(f, self.handle_query(qreq))
            elif op == "fquery":
                fqreq = req
                protocol.write_event(f, self.handle_fquery(fqreq))
            elif op == "cancel":
                job_id = req.get("job_id")
                if not isinstance(job_id, str) or not job_id:
                    protocol.write_event(
                        f, {"event": "error",
                            "error": "cancel needs a 'job_id' string"})
                else:
                    protocol.write_event(f, self.cancel_job(job_id))
            elif op == "drain":
                protocol.write_event(
                    f, {"event": "draining",
                        "queued": self._queue.depth(),
                        "running": len(self._running),
                        "note": "in-flight jobs checkpoint + stay "
                                "journaled; restart resumes them"})
                threading.Thread(target=self._begin_drain,
                                 args=("client",), daemon=True).start()
            elif op == "shutdown":
                protocol.write_event(
                    f, {"event": "shutting_down",
                        "queued": self._queue.depth(),
                        "note": "queued jobs stay journaled and re-queue "
                                "on the next start"})
                self._stop.set()
            else:
                protocol.write_event(f, {"event": "error",
                                         "error": f"unknown op {op!r}"})
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass      # client went away; any running job continues
        finally:
            try:
                f.close()
                conn.close()
            except OSError:
                pass

    def _serve_http(self, f, first: bytes) -> None:
        import json

        parts = first.split()
        path = parts[1].decode("latin-1") if len(parts) >= 2 else "/"
        if path in ("/status", "/status/"):
            body = json.dumps(self.status()).encode()
            head = b"HTTP/1.0 200 OK\r\n"
        else:
            body = json.dumps({"error": f"unknown path {path!r}; "
                                        f"try /status"}).encode()
            head = b"HTTP/1.0 404 Not Found\r\n"
        f.write(head + b"Content-Type: application/json\r\n"
                + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        f.flush()

    def serve_forever(self) -> int:
        """Bind the socket, run the scheduler thread, serve until a
        ``shutdown`` op or SIGTERM. Returns the process exit code."""
        import signal

        # Mixed interactive/batch process: query threads share the GIL
        # with training lanes, and CPython's default 5 ms switch
        # interval means a compute-bound training thread can park a
        # 2 ms query behind one-to-two 5 ms GIL holds — the whole warm
        # p99 budget lost to scheduling. 1 ms caps any single hold at
        # ~1/10 of the query budget for a ~1% bytecode-dispatch tax on
        # training (XLA/BLAS kernels release the GIL anyway). Scoped to
        # the real daemon process, not library import, so tests and
        # solo runs keep the interpreter default.
        sys.setswitchinterval(1e-3)

        def _sched():
            while not self._stop.is_set():
                try:
                    self._last_beat = time.time()
                    self.step(timeout=0.2)
                except Exception as e:  # noqa: BLE001 — daemon must live
                    self.console(f"[serve] scheduler error: "
                                 f"{type(e).__name__}: {e}")
                    self.metrics.emit("scheduler_error",
                                      error=f"{type(e).__name__}: {e}"[:300])

        sched = threading.Thread(target=_sched, name="g2v-serve-sched",
                                 daemon=True)
        sched.start()
        def _on_sigterm(*_):
            # Signal context: just flip the flags and let the scheduler /
            # accept loops do the actual drain work on their own threads.
            threading.Thread(target=self._begin_drain, args=("sigterm",),
                             daemon=True).start()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass      # not the main thread (tests) — SIGTERM unhandled
        if os.path.exists(self.opts.socket_path):
            os.unlink(self.opts.socket_path)    # stale socket from a kill
        srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        srv.bind(self.opts.socket_path)
        srv.listen(16)
        srv.settimeout(0.25)
        # Pidfile: the last-resort fence target. A router that restarts
        # and cannot probe this replica (busy, wedged) must still be
        # able to kill it before launching a successor on the same
        # state dir — an unfenced zombie would race the successor on
        # the same journal. Verified against /proc cmdline by the
        # reader, so a recycled pid is never killed.
        pid_file = os.path.join(self.opts.state_dir, "serve.pid")
        with open(pid_file + ".tmp", "w") as fh:
            fh.write(f"{os.getpid()}\n")
        os.replace(pid_file + ".tmp", pid_file)
        tcp_srv = None
        if self.opts.listen:
            host, port = protocol.parse_addr(self.opts.listen)
            tcp_srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
            tcp_srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
            tcp_srv.bind((host, port))
            tcp_srv.listen(64)
            tcp_srv.settimeout(0.25)
            self.tcp_addr = tcp_srv.getsockname()[:2]
            # Discovery file: router/clients learn the ephemeral port
            # (port 0 requests) without parsing our stderr.
            addr_file = os.path.join(self.opts.state_dir, "tcp_addr")
            with open(addr_file + ".tmp", "w") as fh:
                fh.write(f"{self.tcp_addr[0]}:{self.tcp_addr[1]}\n")
            os.replace(addr_file + ".tmp", addr_file)

        def _accept_loop(lsock):
            while not self._stop.is_set():
                try:
                    conn, _ = lsock.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._handle_conn, args=(conn,),
                                 name="g2v-serve-conn", daemon=True).start()

        self.metrics.emit("serve_start", pid=os.getpid(),
                          socket=self.opts.socket_path,
                          listen=(f"{self.tcp_addr[0]}:{self.tcp_addr[1]}"
                                  if self.tcp_addr else None),
                          state_dir=self.opts.state_dir,
                          queued=self._queue.depth())
        self.console(f"[serve] listening on {self.opts.socket_path}"
                     + (f" + tcp {self.tcp_addr[0]}:{self.tcp_addr[1]}"
                        if self.tcp_addr else "")
                     + f" (state {self.opts.state_dir}, queue depth "
                       f"{self.opts.queue_depth}, max join "
                       f"{self.opts.max_join})")
        tcp_thread = None
        if tcp_srv is not None:
            tcp_thread = threading.Thread(target=_accept_loop,
                                          args=(tcp_srv,),
                                          name="g2v-serve-tcp", daemon=True)
            tcp_thread.start()
        try:
            _accept_loop(srv)
        finally:
            srv.close()
            if tcp_srv is not None:
                tcp_srv.close()
                if tcp_thread is not None:
                    tcp_thread.join(timeout=2.0)
            try:
                os.unlink(self.opts.socket_path)
            except OSError:
                pass
            try:
                os.unlink(pid_file)    # clean exit: nothing to fence
            except OSError:
                pass
            sched.join(timeout=600.0)
            self.metrics.emit("serve_stop", jobs_done=self.jobs_done,
                              jobs_failed=self.jobs_failed,
                              queued=self._queue.depth())
            self.console(f"[serve] stopped ({self.jobs_done} job(s) done, "
                         f"{self._queue.depth()} still queued/journaled)")
            self.close()
        return 0

    def close(self) -> None:
        self._stop.set()
        self.engine.close()
        self.metrics.close()
