"""The ``g2vec serve`` subcommand: daemon, watchdog, and client modes.

Daemon::

    g2vec serve --socket /tmp/g2vec.sock --state-dir /tmp/g2vec-serve \\
        [--queue-depth 16] [--max-join 4] [--cache-dir DIR] \\
        [--metrics-jsonl F] [--platform cpu] [--supervise]

Router (fleet front door; --state-dir becomes the fleet root)::

    g2vec serve --replicas 3 --listen 0.0.0.0:7433 --state-dir /srv/g2vec \\
        [--auth-token-file F] [--probe-interval 0.5] [--probe-deadline 2] \\
        [--cache-dir DIR] [--queue-depth 16] [--max-join 4] \\
        [--lease-ttl-s 5] [--standby] [--join-spread K] \\
        [--remote-replicas]

Client (same flag, a client op instead of --state-dir; --socket accepts a
UNIX path or a TCP host:port — a daemon or the router)::

    g2vec serve --socket /tmp/g2vec.sock --submit job.json [--tenant me] \\
        [--priority interactive|batch] [--deadline-s SECS] \\
        [--auth-token-file F]
    g2vec serve --socket host:7433 --status | --ping | --shutdown
    g2vec serve --socket host:7433 --cancel JOB_ID | --drain
    g2vec serve --socket host:7433 --drain-replica r1
    g2vec serve --socket host:7433 --query list
    g2vec serve --socket host:7433 --query neighbors --query-job i1234 \\
        --query-gene TP53 --query-k 10 [--query-variant v] \\
        [--exact | --nprobe N]
    g2vec serve --socket host:7433 --query topk_biomarkers --query-job i1234
    g2vec serve --socket host:7433 --fquery gene_rank --query-gene TP53
    g2vec serve --socket host:7433 --fquery bundle_overlap \\
        --query-gene TP53 --query-job i1234 [--query-k 50]
    g2vec serve --socket host:7433 --result JOB_ID \\
        [--fields event,variants] [--max-bytes 65536]

``--submit`` streams the job's JSONL events to stdout and exits 0 on
``job_done``, 4 on ``rejected``, 5 on ``job_failed`` (or any other
terminal state), 6 when the daemon connection is lost mid-job (the job
is journaled — poll ``<state-dir>/results/<job_id>.json`` or resubmit
--status later). ``--drain`` asks the daemon to stop admitting,
checkpoint in-flight streaming jobs, and exit 0 with everything
unfinished journaled for the next start.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="g2vec serve",
        description="Resident g2vec service: a long-lived daemon owning "
                    "the device and every warm cache, accepting streaming "
                    "job manifests over a local UNIX socket with admission "
                    "control and shape-bucket-aware scheduling.")
    p.add_argument("--socket", default=None, metavar="ADDR",
                   help="UNIX socket path the daemon listens on. Client "
                        "ops also accept a TCP host:port here (a daemon's "
                        "--listen address or the router). curl "
                        "--unix-socket / plain curl work for /status.")
    p.add_argument("--listen", type=str, default=None, metavar="HOST:PORT",
                   help="TCP front door: ALSO listen on this address "
                        "(port 0 = ephemeral; the bound address lands in "
                        "<state-dir>/tcp_addr). Same JSONL protocol + "
                        "GET /status as the UNIX socket.")
    p.add_argument("--auth-token-file", type=str, default=None,
                   metavar="FILE",
                   help="Shared-secret tenancy: mutating ops (submit/"
                        "cancel/drain/shutdown) must carry this file's "
                        "token as 'auth_token'; status/ping stay open. "
                        "In client mode, the token to send.")
    p.add_argument("--read-deadline-s", type=float, default=30.0,
                   metavar="S",
                   help="Per-connection socket deadline (default 30): a "
                        "stalled or byte-trickling client can hold an "
                        "acceptor thread at most this long.")
    p.add_argument("--max-request-bytes", type=int, default=0,
                   metavar="N",
                   help="Reject request lines over this size (default 0 "
                        "= the protocol's 8 MiB line bound).")
    # router mode
    p.add_argument("--replicas", type=int, default=None, metavar="N",
                   help="Router mode: front N daemon replicas under "
                        "--state-dir (consistent-hash placement, health "
                        "probes, exactly-once failover). Needs --listen.")
    p.add_argument("--probe-interval", type=float, default=0.5,
                   metavar="S",
                   help="Router health-probe cadence for healthy replicas "
                        "(default 0.5); unhealthy ones back off "
                        "exponentially.")
    p.add_argument("--probe-deadline", type=float, default=2.0,
                   metavar="S",
                   help="One probe's socket deadline (default 2); a "
                        "replica that cannot answer /status within it "
                        "fails the probe.")
    p.add_argument("--sticky-deadline", type=float, default=120.0,
                   metavar="S",
                   help="How long a keyed submit waits for an already-"
                        "journaled key's home replica to recover before "
                        "it is refused with retry_later (default 120); "
                        "never ring-placed elsewhere, which would run "
                        "the job twice.")
    p.add_argument("--min-replicas", type=int, default=0, metavar="N",
                   help="Router mode: elastic floor — the scaling "
                        "controller may drain the active set down to N "
                        "(default 0 = track --replicas; the fleet stays "
                        "static unless min < max).")
    p.add_argument("--max-replicas", type=int, default=0, metavar="N",
                   help="Router mode: elastic ceiling — sustained queue "
                        "pressure grows the active set up to N "
                        "(default 0 = track --replicas).")
    p.add_argument("--warm-spares", type=int, default=0, metavar="N",
                   help="Router mode: keep N spare daemons launched "
                        "(jax initialized, zero jobs) but out of the "
                        "ring, so a scale-up is a ring add instead of a "
                        "cold boot; the pool refills in the background "
                        "after each promotion (default 0).")
    p.add_argument("--warmup-job", default=None, metavar="FILE",
                   help="Router mode: canary job payload (JSON) run "
                        "through every spare right after it parks in "
                        "the warm pool — out of the ring, result "
                        "discarded — so jax init, tracing, and the hot "
                        "shapes' XLA compiles are paid while the spare "
                        "idles instead of on its first post-promotion "
                        "batch (default: no pre-warming).")
    p.add_argument("--scale-interval", type=float, default=1.0,
                   metavar="S",
                   help="Router scaling-control cadence: one /status "
                        "sweep of the active set and one policy tick "
                        "per interval (default 1.0).")
    p.add_argument("--standby", action="store_true",
                   help="Router mode: start as a STANDBY — watch the "
                        "fleet's leadership lease and take over (epoch "
                        "+1, adopting the running replicas) only when "
                        "the active router's lease expires or is "
                        "released. Implies leased leadership.")
    p.add_argument("--lease-ttl-s", type=float, default=0.0, metavar="S",
                   help="Router mode: enable leased leadership with "
                        "this ttl (default 0 = no lease machinery; "
                        "--standby without it uses 5s). The leader "
                        "renews at ttl/3; on loss it keeps serving "
                        "reads while daemons fence its mutations as "
                        "stale_epoch.")
    p.add_argument("--join-spread", type=int, default=1, metavar="K",
                   help="Router mode: bounded per-join-key placement "
                        "spread — a hot key may land on any of K salted "
                        "ring candidates, least-loaded first (default 1 "
                        "= classic single-home placement). Keyed "
                        "(idem_key) submits stay sticky regardless.")
    p.add_argument("--remote-replicas", action="store_true",
                   help="Router mode: the fleet's daemons are launched "
                        "and supervised elsewhere — adopt them via "
                        "their published tcp_addr files, never spawn, "
                        "SIGKILL-verify, or relaunch locally. An "
                        "unreachable remote replica is fenced (marker + "
                        "epoch) before its journal migrates.")
    p.add_argument("--state-dir", default=None, metavar="DIR",
                   help="Daemon state root: jobs/ (journal of accepted, "
                        "unfinished jobs — re-queued on restart), "
                        "results/ (durable per-job terminal records), "
                        "spool/ (in-flight lane outputs before routing).")
    p.add_argument("--queue-depth", type=int, default=16, metavar="N",
                   help="Max queued jobs before admission rejects with a "
                        "structured queue_full error (default 16).")
    p.add_argument("--tenant-quotas", type=str, default=None,
                   metavar="SPEC",
                   help="Per-tenant admission SLOs: semicolon-separated "
                        "'name:rate:burst[:weight]' entries — a token "
                        "bucket (rate jobs/s, burst capacity) plus a "
                        "weighted-fair queue share; '*' sets the "
                        "default for unlisted tenants. Over-rate "
                        "submits reject with tenant_quota + "
                        "retry_after_s.")
    p.add_argument("--shed", action="store_true",
                   help="Deadline-aware load shedding: reject a "
                        "deadline-carrying submit whose estimated wait "
                        "(queue depth x observed service time) already "
                        "exceeds its deadline_s — a structured 'shed' "
                        "response with retry_after_s, instead of "
                        "accepting work that will die of "
                        "deadline_exceeded.")
    p.add_argument("--max-join", type=int, default=4, metavar="K",
                   help="Max shape-compatible jobs merged into one engine "
                        "batch per scheduling cycle (default 4).")
    p.add_argument("--job-retries", type=int, default=1, metavar="N",
                   help="In-process re-queues for a job whose batch failed "
                        "retryably (default 1).")
    p.add_argument("--cache-dir", type=str, default=None, metavar="DIR",
                   help="Persistent cache root (XLA compile + walk "
                        "artifacts) — what makes a supervised relaunch "
                        "warm-start instead of cold.")
    p.add_argument("--metrics-jsonl", type=str, default=None,
                   help="Daemon-lifetime JSONL metrics stream; every "
                        "job-scoped event carries job_id (and lane).")
    p.add_argument("--platform", type=str, default=None,
                   help="Force a jax platform (e.g. cpu) before first "
                        "device use.")
    p.add_argument("--fault-plan", type=str, default=None, metavar="SPEC",
                   help="Fault-injection spec for chaos drills "
                        "(resilience/faults.py grammar).")
    # query plane (daemon-side knobs)
    p.add_argument("--inventory-budget-bytes", type=int,
                   default=256 << 20, metavar="N",
                   help="Byte budget for the memory-mapped bundle LRU "
                        "(default 256 MiB); least-recently-queried "
                        "bundles unmap when the mapped set exceeds it.")
    p.add_argument("--query-cache-entries", type=int, default=128,
                   metavar="N",
                   help="Entries in the keyed query-result LRU "
                        "(default 128).")
    p.add_argument("--inventory-dir", type=str, default=None,
                   metavar="DIR",
                   help="Extra inventory root beyond <state-dir>/"
                        "inventory — point the daemon at a directory of "
                        "solo --emit-inventory bundles to make them "
                        "queryable.")
    p.add_argument("--ann-nlist", type=int, default=0, metavar="N",
                   help="IVF coarse-quantizer list count for published "
                        "bundle indexes: 0 (default) auto-sizes to "
                        "~sqrt(G) once a bundle clears the row floor, "
                        "N>0 forces N lists on every bundle, N<0 "
                        "disables index builds entirely. Approx "
                        "queries on index-less bundles silently serve "
                        "exact.")
    p.add_argument("--max-result-bytes", type=int, default=0, metavar="N",
                   help="Server-side cap on one 'result' response "
                        "(default 0 = the 8 MiB line bound); over-cap "
                        "records answer with a structured "
                        "oversized_result error naming the available "
                        "fields.")
    # watchdog
    p.add_argument("--supervise", action="store_true",
                   help="Run the daemon under the relaunch watchdog: a "
                        "crash/SIGKILL restarts it, the journal re-queues "
                        "in-flight jobs, --cache-dir restores warm state.")
    p.add_argument("--supervise-retries", type=int, default=3)
    p.add_argument("--supervise-backoff", type=float, default=1.0)
    # client ops
    p.add_argument("--submit", type=str, default=None, metavar="JOB.json",
                   help="Client mode: submit this job file and stream its "
                        "events to stdout ('-' reads stdin).")
    p.add_argument("--tenant", type=str, default="default",
                   help="Tenant name for --submit (fair-share unit).")
    p.add_argument("--priority", type=str, default=None,
                   choices=("interactive", "batch"),
                   help="Priority class for --submit: interactive pops "
                        "before batch; aging keeps batch from starving "
                        "(default batch).")
    p.add_argument("--deadline-s", type=float, default=None, metavar="S",
                   help="Wall-clock budget for --submit, measured from "
                        "submission (survives daemon restarts); an "
                        "overdue job terminates deadline_exceeded at the "
                        "next shard/chunk boundary.")
    p.add_argument("--status", action="store_true",
                   help="Client mode: print the daemon status JSON.")
    p.add_argument("--ping", action="store_true",
                   help="Client mode: liveness probe (exit 0 iff alive).")
    p.add_argument("--shutdown", action="store_true",
                   help="Client mode: stop the daemon after its current "
                        "batch; queued jobs stay journaled.")
    p.add_argument("--cancel", type=str, default=None, metavar="JOB_ID",
                   help="Client mode: cancel a queued (immediate) or "
                        "running (next boundary) job.")
    p.add_argument("--drain", action="store_true",
                   help="Client mode: graceful drain — stop admitting, "
                        "checkpoint in-flight streaming jobs, journal "
                        "everything unfinished, exit 0.")
    p.add_argument("--drain-replica", type=str, default=None,
                   metavar="NAME",
                   help="Client mode (router): drain one replica "
                        "synchronously and relaunch it; prints the exit "
                        "code the drained daemon returned.")
    # client query plane
    p.add_argument("--query", type=str, default=None,
                   choices=("neighbors", "topk_biomarkers", "meta",
                            "list"),
                   help="Client mode: one read-plane query against a "
                        "daemon or the router (token-gated — query "
                        "responses carry tenant embeddings/scores).")
    p.add_argument("--query-job", type=str, default=None,
                   metavar="JOB_ID",
                   help="Bundle address for --query: the job whose "
                        "published embedding bundle to read (or a solo "
                        "bundle's directory name under --inventory-dir).")
    p.add_argument("--query-variant", type=str, default=None,
                   metavar="NAME",
                   help="Variant lane of --query-job (optional when the "
                        "job has exactly one).")
    p.add_argument("--query-gene", type=str, default=None, metavar="SYM",
                   help="Gene symbol for --query neighbors.")
    p.add_argument("--query-k", type=int, default=10, metavar="K",
                   help="Result count for --query neighbors / "
                        "topk_biomarkers (default 10).")
    p.add_argument("--exact", action="store_true",
                   help="Force the exact scan for --query neighbors "
                        "(mode=exact), bypassing any ANN index — the "
                        "ground-truth baseline for the approx plane.")
    p.add_argument("--nprobe", type=int, default=0, metavar="N",
                   help="IVF lists probed per approx neighbors query "
                        "(default 0 = the server's default; values >= "
                        "nlist are exact-equivalent).")
    p.add_argument("--fquery", type=str, default=None,
                   choices=("gene_rank", "bundle_overlap"),
                   help="Client mode: one federated cross-bundle query "
                        "— gene_rank ('which bundles rank --query-gene "
                        "in their top --query-k biomarkers') or "
                        "bundle_overlap ('bundles nearest the "
                        "reference bundle by neighbor-set overlap'; "
                        "the reference is --query-job/--query-variant). "
                        "Routed, it scatter-gathers across the fleet; "
                        "dead replicas' bundles answer from shared "
                        "disk with replica_down attribution.")
    p.add_argument("--result", type=str, default=None, metavar="JOB_ID",
                   help="Client mode: fetch a job's durable terminal "
                        "record via the 'result' op.")
    p.add_argument("--fields", type=str, default=None, metavar="K1,K2",
                   help="Comma-separated top-level record keys --result "
                        "should return (default: all).")
    p.add_argument("--max-bytes", type=int, default=None, metavar="N",
                   help="Client-side cap on the --result response; an "
                        "over-cap record answers oversized_result with "
                        "the available field names.")
    return p


def _read_token(path: Optional[str]) -> Optional[str]:
    if not path:
        return None
    with open(path) as f:
        tok = f.read().strip()
    if not tok:
        raise SystemExit(f"auth token file {path!r} is empty")
    return tok


def serve_main(argv: Optional[List[str]] = None) -> int:
    args = build_serve_parser().parse_args(argv)
    from g2vec_tpu.serve import client

    if args.status or args.ping or args.shutdown or args.submit \
            or args.cancel or args.drain or args.drain_replica \
            or args.query or args.fquery or args.result:
        if not args.socket:
            build_serve_parser().error(
                "client ops need --socket (a UNIX path or host:port)")
        token = _read_token(args.auth_token_file)
        try:
            if args.status:
                print(json.dumps(client.status(args.socket), indent=1))
                return 0
            if args.ping:
                print(json.dumps(client.ping(args.socket)))
                return 0
            if args.shutdown:
                ev = client.shutdown(args.socket, auth_token=token)
                print(json.dumps(ev))
                return 0 if ev.get("event") not in ("rejected",
                                                    "error") else 4
            if args.cancel:
                ev = client.cancel(args.socket, args.cancel,
                                   auth_token=token)
                print(json.dumps(ev))
                return 0 if ev.get("event") not in ("rejected",
                                                    "error") else 4
            if args.drain:
                ev = client.drain(args.socket, auth_token=token)
                print(json.dumps(ev))
                return 0 if ev.get("event") not in ("rejected",
                                                    "error") else 4
            if args.query:
                ev = client.query(args.socket, args.query,
                                  job_id=args.query_job,
                                  variant=args.query_variant,
                                  gene=args.query_gene,
                                  k=args.query_k,
                                  mode=("exact" if args.exact else None),
                                  nprobe=(args.nprobe or None),
                                  auth_token=token)
                print(json.dumps(ev, indent=1))
                return 0 if ev.get("event") == "query_result" else 4
            if args.fquery:
                ev = client.fquery(args.socket, args.fquery,
                                   args.query_gene,
                                   k=args.query_k,
                                   mode=("exact" if args.exact
                                         else None),
                                   nprobe=(args.nprobe or None),
                                   job_id=args.query_job,
                                   variant=args.query_variant,
                                   auth_token=token)
                print(json.dumps(ev, indent=1))
                return 0 if ev.get("event") == "fquery_result" else 4
            if args.result:
                ev = client.result(
                    args.socket, args.result,
                    fields=(args.fields.split(",") if args.fields
                            else None),
                    max_bytes=args.max_bytes, auth_token=token)
                print(json.dumps(ev, indent=1))
                return 0 if ev.get("event") not in ("rejected",
                                                    "error") else 4
            if args.drain_replica:
                for ev in client.request(
                        args.socket,
                        {"op": "drain_replica",
                         "replica": args.drain_replica,
                         "auth_token": token}, timeout=600.0):
                    print(json.dumps(ev))
                    return 0 if ev.get("event") == "drained" else 4
                return 4
            src = sys.stdin if args.submit == "-" else open(args.submit)
            with src:
                job = json.load(src)
            try:
                #: a submit file may carry the whole ``update`` op
                #: envelope (op/job_id/idem_key/job/...) — route it to
                #: the incremental-retrain path instead of nesting the
                #: envelope inside a plain submit's job object (where
                #: the schema gate would reject `op` as an unknown key).
                if isinstance(job, dict) and job.get("op") == "update":
                    events = client.update_job(
                        args.socket, job.get("job_id", ""),
                        job.get("job", {}),
                        idem_key=job.get("idem_key", ""),
                        variant=job.get("variant"),
                        epochs=int(job.get("epochs", 0) or 0),
                        tenant=args.tenant,
                        priority=args.priority,
                        deadline_s=args.deadline_s,
                        auth_token=token)
                else:
                    events = client.submit_job(args.socket, job,
                                               tenant=args.tenant,
                                               priority=args.priority,
                                               deadline_s=args.deadline_s,
                                               auth_token=token)
            except client.ServeConnectionLost as e:
                print(json.dumps({"event": "connection_lost",
                                  "job_id": e.job_id, "error": str(e)}))
                return 6
            for ev in events:
                print(json.dumps(ev))
            last = events[-1].get("event") if events else None
            return {"job_done": 0, "rejected": 4}.get(last, 5)
        except OSError as e:
            print(json.dumps({"event": "error",
                              "error": f"cannot reach daemon at "
                                       f"{args.socket}: {e}"}),
                  file=sys.stderr)
            return 3

    if not args.state_dir:
        build_serve_parser().error(
            "daemon/router mode needs --state-dir (or pass a client op: "
            "--submit/--status/--ping/--shutdown)")
    if args.replicas is not None:
        # Router mode: jax-free by construction — the replicas own the
        # devices; this process only probes, places, and fails over.
        if args.replicas < 1:
            build_serve_parser().error("--replicas must be >= 1")
        if not args.listen:
            build_serve_parser().error("router mode needs --listen")
        from g2vec_tpu.serve.router import Router, RouterOptions

        fwd: List[str] = ["--queue-depth", str(args.queue_depth),
                          "--max-join", str(args.max_join),
                          "--job-retries", str(args.job_retries),
                          "--read-deadline-s", str(args.read_deadline_s),
                          "--inventory-budget-bytes",
                          str(args.inventory_budget_bytes),
                          "--query-cache-entries",
                          str(args.query_cache_entries),
                          "--ann-nlist", str(args.ann_nlist)]
        if args.max_request_bytes:
            fwd += ["--max-request-bytes", str(args.max_request_bytes)]
        if args.max_result_bytes:
            fwd += ["--max-result-bytes", str(args.max_result_bytes)]
        if args.inventory_dir:
            fwd += ["--inventory-dir", args.inventory_dir]
        if args.tenant_quotas:
            fwd += ["--tenant-quotas", args.tenant_quotas]
        if args.shed:
            fwd += ["--shed"]
        if args.cache_dir:
            fwd += ["--cache-dir", args.cache_dir]
        if args.platform:
            fwd += ["--platform", args.platform]
        if args.fault_plan:
            fwd += ["--fault-plan", args.fault_plan]
        opts = RouterOptions(
            fleet_dir=args.state_dir, replicas=args.replicas,
            listen=args.listen, probe_interval=args.probe_interval,
            probe_deadline=args.probe_deadline,
            auth_token=_read_token(args.auth_token_file),
            read_deadline_s=args.read_deadline_s,
            max_request_bytes=args.max_request_bytes,
            metrics_jsonl=args.metrics_jsonl,
            sticky_deadline_s=args.sticky_deadline,
            inventory_budget_bytes=args.inventory_budget_bytes,
            max_result_bytes=args.max_result_bytes,
            min_replicas=args.min_replicas,
            max_replicas=args.max_replicas,
            warm_spares=args.warm_spares,
            warmup_job=args.warmup_job,
            scale_interval=args.scale_interval,
            standby=args.standby,
            lease_ttl_s=args.lease_ttl_s,
            join_spread=args.join_spread,
            remote_replicas=args.remote_replicas,
            serve_argv=tuple(fwd))
        return Router(opts).serve_forever()
    if not args.socket:
        build_serve_parser().error("daemon mode needs --socket")
    if args.supervise:
        from g2vec_tpu.resilience.supervisor import supervise_serve

        return supervise_serve(
            list(argv) if argv is not None else sys.argv[2:],
            retries=args.supervise_retries,
            backoff=args.supervise_backoff,
            metrics_jsonl=args.metrics_jsonl,
            state_dir=args.state_dir)
    if args.cache_dir:
        # Persistent-compile tier via env BEFORE any jax import, same
        # rationale as __main__.py's plain-run path.
        from g2vec_tpu.cache import resolve_cache_tiers

        xla_dir, _ = resolve_cache_tiers(args.cache_dir, None,
                                         walk_cache_enabled=False)
        if xla_dir:
            os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", xla_dir)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        import jax

        jax.config.update("jax_platforms", args.platform)
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=args.socket, state_dir=args.state_dir,
        queue_depth=args.queue_depth, max_join=args.max_join,
        job_retries=args.job_retries, cache_dir=args.cache_dir,
        metrics_jsonl=args.metrics_jsonl, fault_plan=args.fault_plan,
        listen=args.listen, auth_token=_read_token(args.auth_token_file),
        read_deadline_s=args.read_deadline_s,
        max_request_bytes=args.max_request_bytes,
        inventory_budget_bytes=args.inventory_budget_bytes,
        query_cache_entries=args.query_cache_entries,
        inventory_dir=args.inventory_dir,
        ann_nlist=args.ann_nlist,
        max_result_bytes=args.max_result_bytes,
        tenant_quotas=args.tenant_quotas, shed=args.shed)
    return ServeDaemon(opts).serve_forever()
