"""The ``g2vec serve`` subcommand: daemon, watchdog, and client modes.

Daemon::

    g2vec serve --socket /tmp/g2vec.sock --state-dir /tmp/g2vec-serve \\
        [--queue-depth 16] [--max-join 4] [--cache-dir DIR] \\
        [--metrics-jsonl F] [--platform cpu] [--supervise]

Client (same flag, a client op instead of --state-dir)::

    g2vec serve --socket /tmp/g2vec.sock --submit job.json [--tenant me] \\
        [--priority interactive|batch] [--deadline-s SECS]
    g2vec serve --socket /tmp/g2vec.sock --status | --ping | --shutdown
    g2vec serve --socket /tmp/g2vec.sock --cancel JOB_ID | --drain

``--submit`` streams the job's JSONL events to stdout and exits 0 on
``job_done``, 4 on ``rejected``, 5 on ``job_failed`` (or any other
terminal state), 6 when the daemon connection is lost mid-job (the job
is journaled — poll ``<state-dir>/results/<job_id>.json`` or resubmit
--status later). ``--drain`` asks the daemon to stop admitting,
checkpoint in-flight streaming jobs, and exit 0 with everything
unfinished journaled for the next start.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional


def build_serve_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="g2vec serve",
        description="Resident g2vec service: a long-lived daemon owning "
                    "the device and every warm cache, accepting streaming "
                    "job manifests over a local UNIX socket with admission "
                    "control and shape-bucket-aware scheduling.")
    p.add_argument("--socket", required=True, metavar="PATH",
                   help="UNIX socket path the daemon listens on (clients "
                        "connect here; curl --unix-socket works for "
                        "/status).")
    p.add_argument("--state-dir", default=None, metavar="DIR",
                   help="Daemon state root: jobs/ (journal of accepted, "
                        "unfinished jobs — re-queued on restart), "
                        "results/ (durable per-job terminal records), "
                        "spool/ (in-flight lane outputs before routing).")
    p.add_argument("--queue-depth", type=int, default=16, metavar="N",
                   help="Max queued jobs before admission rejects with a "
                        "structured queue_full error (default 16).")
    p.add_argument("--max-join", type=int, default=4, metavar="K",
                   help="Max shape-compatible jobs merged into one engine "
                        "batch per scheduling cycle (default 4).")
    p.add_argument("--job-retries", type=int, default=1, metavar="N",
                   help="In-process re-queues for a job whose batch failed "
                        "retryably (default 1).")
    p.add_argument("--cache-dir", type=str, default=None, metavar="DIR",
                   help="Persistent cache root (XLA compile + walk "
                        "artifacts) — what makes a supervised relaunch "
                        "warm-start instead of cold.")
    p.add_argument("--metrics-jsonl", type=str, default=None,
                   help="Daemon-lifetime JSONL metrics stream; every "
                        "job-scoped event carries job_id (and lane).")
    p.add_argument("--platform", type=str, default=None,
                   help="Force a jax platform (e.g. cpu) before first "
                        "device use.")
    p.add_argument("--fault-plan", type=str, default=None, metavar="SPEC",
                   help="Fault-injection spec for chaos drills "
                        "(resilience/faults.py grammar).")
    # watchdog
    p.add_argument("--supervise", action="store_true",
                   help="Run the daemon under the relaunch watchdog: a "
                        "crash/SIGKILL restarts it, the journal re-queues "
                        "in-flight jobs, --cache-dir restores warm state.")
    p.add_argument("--supervise-retries", type=int, default=3)
    p.add_argument("--supervise-backoff", type=float, default=1.0)
    # client ops
    p.add_argument("--submit", type=str, default=None, metavar="JOB.json",
                   help="Client mode: submit this job file and stream its "
                        "events to stdout ('-' reads stdin).")
    p.add_argument("--tenant", type=str, default="default",
                   help="Tenant name for --submit (fair-share unit).")
    p.add_argument("--priority", type=str, default=None,
                   choices=("interactive", "batch"),
                   help="Priority class for --submit: interactive pops "
                        "before batch; aging keeps batch from starving "
                        "(default batch).")
    p.add_argument("--deadline-s", type=float, default=None, metavar="S",
                   help="Wall-clock budget for --submit, measured from "
                        "submission (survives daemon restarts); an "
                        "overdue job terminates deadline_exceeded at the "
                        "next shard/chunk boundary.")
    p.add_argument("--status", action="store_true",
                   help="Client mode: print the daemon status JSON.")
    p.add_argument("--ping", action="store_true",
                   help="Client mode: liveness probe (exit 0 iff alive).")
    p.add_argument("--shutdown", action="store_true",
                   help="Client mode: stop the daemon after its current "
                        "batch; queued jobs stay journaled.")
    p.add_argument("--cancel", type=str, default=None, metavar="JOB_ID",
                   help="Client mode: cancel a queued (immediate) or "
                        "running (next boundary) job.")
    p.add_argument("--drain", action="store_true",
                   help="Client mode: graceful drain — stop admitting, "
                        "checkpoint in-flight streaming jobs, journal "
                        "everything unfinished, exit 0.")
    return p


def serve_main(argv: Optional[List[str]] = None) -> int:
    args = build_serve_parser().parse_args(argv)
    from g2vec_tpu.serve import client

    if args.status or args.ping or args.shutdown or args.submit \
            or args.cancel or args.drain:
        try:
            if args.status:
                print(json.dumps(client.status(args.socket), indent=1))
                return 0
            if args.ping:
                print(json.dumps(client.ping(args.socket)))
                return 0
            if args.shutdown:
                print(json.dumps(client.shutdown(args.socket)))
                return 0
            if args.cancel:
                ev = client.cancel(args.socket, args.cancel)
                print(json.dumps(ev))
                return 0 if ev.get("event") != "error" else 4
            if args.drain:
                print(json.dumps(client.drain(args.socket)))
                return 0
            src = sys.stdin if args.submit == "-" else open(args.submit)
            with src:
                job = json.load(src)
            try:
                events = client.submit_job(args.socket, job,
                                           tenant=args.tenant,
                                           priority=args.priority,
                                           deadline_s=args.deadline_s)
            except client.ServeConnectionLost as e:
                print(json.dumps({"event": "connection_lost",
                                  "job_id": e.job_id, "error": str(e)}))
                return 6
            for ev in events:
                print(json.dumps(ev))
            last = events[-1].get("event") if events else None
            return {"job_done": 0, "rejected": 4}.get(last, 5)
        except OSError as e:
            print(json.dumps({"event": "error",
                              "error": f"cannot reach daemon at "
                                       f"{args.socket}: {e}"}),
                  file=sys.stderr)
            return 3

    if not args.state_dir:
        build_serve_parser().error(
            "daemon mode needs --state-dir (or pass a client op: "
            "--submit/--status/--ping/--shutdown)")
    if args.supervise:
        from g2vec_tpu.resilience.supervisor import supervise_serve

        return supervise_serve(
            list(argv) if argv is not None else sys.argv[2:],
            retries=args.supervise_retries,
            backoff=args.supervise_backoff,
            metrics_jsonl=args.metrics_jsonl,
            state_dir=args.state_dir)
    if args.cache_dir:
        # Persistent-compile tier via env BEFORE any jax import, same
        # rationale as __main__.py's plain-run path.
        from g2vec_tpu.cache import resolve_cache_tiers

        xla_dir, _ = resolve_cache_tiers(args.cache_dir, None,
                                         walk_cache_enabled=False)
        if xla_dir:
            os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", xla_dir)
    if args.platform:
        os.environ["JAX_PLATFORMS"] = args.platform
        import jax

        jax.config.update("jax_platforms", args.platform)
    from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions

    opts = ServeOptions(
        socket_path=args.socket, state_dir=args.state_dir,
        queue_depth=args.queue_depth, max_join=args.max_join,
        job_retries=args.job_retries, cache_dir=args.cache_dir,
        metrics_jsonl=args.metrics_jsonl, fault_plan=args.fault_plan)
    return ServeDaemon(opts).serve_forever()
