"""Resident service mode — the ``g2vec serve`` warm-state job daemon.

- protocol.py — the newline-delimited-JSON wire format over a local UNIX
  stream socket (plus plain-HTTP ``GET /status`` on the same socket).
- daemon.py — :class:`ServeDaemon`: admission control, tenant-fair queue,
  shape-bucket-aware job joining, journaled crash recovery, per-job JSONL
  result streaming, all over ONE resident
  :class:`~g2vec_tpu.batch.engine.ResidentEngine`.
- client.py — the submit/status/shutdown client the CLI, bench, and tests
  speak.
- cli.py — the ``g2vec serve`` subcommand (daemon + client modes, and the
  ``--supervise`` watchdog entry).
"""
from g2vec_tpu.serve.daemon import ServeDaemon, ServeOptions  # noqa: F401
