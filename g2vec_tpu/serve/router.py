"""The serve fleet front door: one TCP listener over N daemon replicas.

The router is the piece that turns ``g2vec serve`` from a single-host
daemon into a fleet that survives replica death with zero lost and zero
duplicated jobs:

- **Placement** — a consistent-hash ring over the job's join key (the
  same :func:`~g2vec_tpu.config.serve_join_key` the daemon batches on),
  so shape-compatible jobs from different clients land on the SAME
  replica and still join one warm batch. 64 virtual nodes per replica
  keep the key movement on replica add/remove near the theoretical
  1/N minimum.
- **Health** — a per-replica probe loop (``status`` over TCP, with a
  deadline) drives the healthy → suspect → dead → rejoining machine in
  :class:`~g2vec_tpu.resilience.lifecycle.ReplicaHealth`; probes back
  off exponentially for unhealthy replicas.
- **Failover** — when a replica is declared dead the router *fences* it
  (SIGKILL via :class:`~g2vec_tpu.resilience.supervisor.ReplicaFleet`,
  so a slow-but-alive replica can never race a survivor), then walks
  its journal: entries with a durable result record are dropped (the
  PR 9 reconciliation), the rest have their streaming cursors copied to
  a survivor and are resubmitted there — with the ORIGINAL idempotency
  key, so the survivor's dedup table acks them exactly once even if the
  router itself dies and retries the whole failover. Only after the
  journal is empty is the replica relaunched; it rejoins the ring once
  consecutive probes pass with an empty journal.

The exactly-once argument, end to end: every routed job carries an
idem-key (client-supplied or router-minted); the job_id is DERIVED from
that key, so journal entries, cursor checkpoints, and result records
keep their names across replicas; any resubmission — client retry after
a lost ack, router failover, repeated failover after a router crash —
therefore either dedups against a live admission table, reconciles
against a result record, or resumes the same cursor. No path re-runs
completed work, no path drops acked work.

This module is deliberately **jax-free** (it imports config, protocol,
lifecycle, supervisor, metrics — never daemon/engine): a router process
boots in milliseconds and never competes with replicas for accelerator
or heap.
"""
from __future__ import annotations

import bisect
import dataclasses
import glob
import hashlib
import json
import os
import queue
import shutil
import socket
import sys
import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Tuple

from g2vec_tpu.config import G2VecConfig, config_from_job, serve_join_key
from g2vec_tpu.resilience.lifecycle import ReplicaHealth, ScalingPolicy
from g2vec_tpu.resilience.supervisor import ReplicaFleet, ReplicaSpec
from g2vec_tpu.serve import inventory, leader, protocol
from g2vec_tpu.utils.metrics import MetricsWriter

#: Token-gated ops: the mutators, plus ``query``/``fquery`` — reads,
#: but ones that expose tenant embeddings/scores, not just health
#: (probes stay open).
_AUTH_OPS = ("submit", "update", "cancel", "drain_replica", "shutdown",
             "query", "fquery")


def sanitize_client_submit(req: dict) -> dict:
    """Strip the fields a client must never control from a submit
    before relaying it: ``auth_token`` (the admission secret must not
    be journaled downstream), and the router-internal migration fields
    ``requeue``/``submitted_at``/``relay_token`` — forwarded untouched,
    any tenant holding the shared fleet token could bypass the
    per-tenant quota and deadline-shed gates and forward-date its own
    deadline clock. The daemon additionally refuses those fields
    without the replica's relay_token (defense in depth); stripping
    here keeps an honest client's stale field from degrading too.
    ``router_epoch`` is stripped for the same reason: the fencing
    epoch is the ROUTER's claim of leadership — a client-supplied one
    could advance a daemon's persisted watermark and lock the real
    leader out."""
    return {k: v for k, v in req.items()
            if k not in ("auth_token", "requeue", "submitted_at",
                         "relay_token", "router_epoch")}


# ---------------------------------------------------------------------------
# Consistent hashing
# ---------------------------------------------------------------------------

class HashRing:
    """Consistent-hash ring with virtual nodes. ``lookup`` walks
    clockwise past members the caller marks ineligible, so health is an
    overlay — the ring itself only changes on add/remove, which is what
    keeps key movement minimal."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = vnodes
        self._points: List[Tuple[int, str]] = []
        self._members: set = set()

    @staticmethod
    def _h(key: str) -> int:
        return int.from_bytes(
            hashlib.sha256(key.encode()).digest()[:8], "big")

    def add(self, name: str) -> None:
        if name in self._members:
            return
        self._members.add(name)
        for i in range(self.vnodes):
            bisect.insort(self._points, (self._h(f"{name}#{i}"), name))

    def remove(self, name: str) -> None:
        self._members.discard(name)
        self._points = [p for p in self._points if p[1] != name]

    def members(self) -> List[str]:
        return sorted(self._members)

    def lookup(self, key: str, eligible=None) -> Optional[str]:
        """Owner of ``key`` among ``eligible`` members (all, if None)."""
        if not self._points:
            return None
        ok = self._members if eligible is None \
            else (self._members & set(eligible))
        if not ok:
            return None
        i = bisect.bisect_right(self._points, (self._h(key), "￿"))
        for off in range(len(self._points)):
            _, name = self._points[(i + off) % len(self._points)]
            if name in ok:
                return name
        return None


# ---------------------------------------------------------------------------
# Router
# ---------------------------------------------------------------------------

@dataclasses.dataclass
class RouterOptions:
    #: Fleet root: ``<fleet_dir>/rN/{sock,state/,serve.log}`` per replica,
    #: plus ``router_addr`` / ``router.log`` / ``router-metrics.jsonl``.
    fleet_dir: str
    replicas: int = 2
    listen: str = "127.0.0.1:0"
    #: Probe cadence for healthy replicas; unhealthy ones back off
    #: exponentially from this base (ReplicaHealth.probe_interval).
    probe_interval: float = 0.5
    #: Socket deadline on one probe — a replica that cannot answer
    #: ``status`` within this is a failed probe.
    probe_deadline: float = 2.0
    suspect_after: int = 1
    dead_after: int = 3
    rejoin_after: int = 2
    #: Shared secret: required on mutating client ops AND forwarded on
    #: every replica request (replicas are started with the same token).
    auth_token: Optional[str] = None
    read_deadline_s: float = 30.0
    max_request_bytes: int = 0
    metrics_jsonl: Optional[str] = None
    #: Extra argv appended to every replica's ``g2vec serve`` command
    #: (cache dir, queue depth, fault plans for chaos drills, ...).
    serve_argv: Tuple[str, ...] = ()
    #: Grace before SIGKILL when fencing a dead-declared replica.
    fence_grace_s: float = 1.0
    vnodes: int = 64
    #: How long a keyed submit waits for an already-journaled key's home
    #: replica to recover (or its entry to migrate) before the submit is
    #: refused with ``retry_later`` — never ring-placed, which would run
    #: the job twice.
    sticky_deadline_s: float = 120.0
    #: Byte budget for the router's OWN mmap catalog — the failover read
    #: path that answers queries for bundles whose home replica is dead
    #: (the fleet's state dirs are co-located with the router).
    inventory_budget_bytes: int = 256 << 20
    #: Server-side cap on a relayed ``result`` response (see
    #: protocol.bound_record). 0 = protocol.MAX_LINE_BYTES.
    max_result_bytes: int = 0
    #: Elastic fleet bounds: the scaling controller may shrink the
    #: active (in-ring) set to ``min_replicas`` and grow it to
    #: ``max_replicas``. 0 = track ``replicas`` — with both at 0 the
    #: fleet is static and the controller never acts (the pre-elastic
    #: behavior, and the default).
    min_replicas: int = 0
    max_replicas: int = 0
    #: Warm-pool size: spare daemons kept launched (jax initialized,
    #: zero jobs) but OUT of the ring, so a scale-up is a ring add
    #: (~instant) instead of a cold daemon boot (tens of seconds). The
    #: pool refills in the background after each promotion while cold
    #: names remain.
    warm_spares: int = 0
    #: Canary job file (JSON payload) run through every spare right
    #: after it parks in the warm pool — OUT of the ring, result
    #: discarded. A daemon process is only process-warm at launch; the
    #: expensive part of its first real batch is jax init + tracing +
    #: the hot shapes' XLA compiles, and on a CPU-contended host that
    #: bill lands exactly when a surge is on. The canary moves it to
    #: the spare's idle time, so promotion is a ring add in fact, not
    #: just in mechanism. None disables pre-warming.
    warmup_job: Optional[str] = None
    #: Control-loop cadence: one /status sweep of the active set, one
    #: ScalingPolicy.observe per interval (also how often the /status
    #: fleet aggregate refreshes).
    scale_interval: float = 1.0
    #: ScalingPolicy thresholds (queued jobs per active replica) and
    #: the estimated-wait trip wire — see lifecycle.ScalingPolicy for
    #: the hysteresis/cooldown semantics.
    scale_up_queue: float = 4.0
    scale_down_queue: float = 0.5
    scale_up_wait_s: float = 8.0
    #: Seed for the controller's rng (victim choice on scale-down) —
    #: a chaos run with a fixed seed drains the same replicas every run.
    scale_seed: int = 0
    #: HA control plane (serve/leader.py). ``standby=True``: do not
    #: serve; watch ``<fleet_dir>/leader.json`` and take over with
    #: epoch+1 (adopting the live fleet) once the lease expires.
    #: ``lease_ttl_s > 0`` on a primary: acquire + renew the lease and
    #: stamp every mutating replica command with the fencing epoch.
    #: Both default OFF — a 1-router fleet never writes a lease and
    #: every command goes out epoch-less (byte-identical to PR 16).
    standby: bool = False
    lease_ttl_s: float = 0.0
    #: Replicas reached through a relay or on another host: their
    #: death can never be verified from here (SIGKILL proves nothing),
    #: so failover quarantines them with a fence marker + epoch bump
    #: instead, and probe adoption keeps the externally published
    #: ``tcp_addr`` instead of the daemon's self-reported listen addr.
    remote_replicas: bool = False
    #: Join-key salting: a keyed submit may land on the least-loaded
    #: of this many ring candidates for its join key, so a single-hot-
    #: shape flash crowd spreads to a promoted spare instead of
    #: pinning one replica. 1 = the pre-salting single-owner placement.
    join_spread: int = 1


class Router:
    """Health-checked, consistent-hashing front door over a ReplicaFleet.

    Durable state lives ONLY in the replicas' state dirs (journal,
    results, cursors, idem tables) — the router itself can be SIGKILLed
    and relaunched at any point: on boot it probes each replica socket
    and either *adopts* the live daemon (pid from its status) or runs
    the same failover it would run for a mid-flight death.
    """

    def __init__(self, opts: RouterOptions,
                 console: Callable[[str], None] = print):
        self.opts = opts
        self.console = console
        os.makedirs(opts.fleet_dir, exist_ok=True)
        self.metrics = MetricsWriter(
            opts.metrics_jsonl
            or os.path.join(opts.fleet_dir, "router-metrics.jsonl"),
            append=True)
        serve_argv = list(opts.serve_argv)
        if opts.auth_token is not None:
            tok_file = os.path.join(opts.fleet_dir, "auth_token")
            with open(tok_file, "w") as fh:
                fh.write(opts.auth_token)
            os.chmod(tok_file, 0o600)
            serve_argv += ["--auth-token-file", tok_file]
        #: Elastic bounds: 0 means "track --replicas" (static fleet).
        self._min = opts.min_replicas or opts.replicas
        self._max = opts.max_replicas or opts.replicas
        if not (1 <= self._min <= self._max):
            raise ValueError(f"need 1 <= --min-replicas <= "
                             f"--max-replicas, got {self._min}.."
                             f"{self._max}")
        if opts.warm_spares < 0:
            raise ValueError(f"--warm-spares must be >= 0, "
                             f"got {opts.warm_spares}")
        if opts.join_spread < 1:
            raise ValueError(f"--join-spread must be >= 1, "
                             f"got {opts.join_spread}")
        self._elastic = self._max > self._min
        n_initial = min(max(opts.replicas, self._min), self._max)
        # The fleet is SIZED up front (specs are cheap — directories and
        # names, no processes): active replicas + every name the
        # controller could ever scale into + the warm pool's headroom.
        # Which of those names actually run is the router's call.
        self.fleet = ReplicaFleet(opts.fleet_dir,
                                  self._max + opts.warm_spares,
                                  serve_argv=serve_argv, console=console)
        self.ring = HashRing(vnodes=opts.vnodes)
        self.health: Dict[str, ReplicaHealth] = {}
        #: The in-ring replica set — exactly the ring's membership (the
        #: health machine stays an eligibility OVERLAY on top of it).
        #: Scale-up adds a name here + to the ring; scale-down drains
        #: and demotes it to the warm pool.
        self._active: set = set(self.fleet.names()[:n_initial])  # guarded-by: _hlock
        #: Launched-but-ringless spares, promotion order = FIFO. A
        #: demoted replica rejoins this pool after its drain, so the
        #: pool can temporarily exceed warm_spares — promotions reuse
        #: warm processes before cold names either way.
        self._warm: List[str] = []              # guarded-by: _hlock
        for name in self.fleet.names():
            if name in self._active:
                self.ring.add(name)
            self.health[name] = ReplicaHealth(
                name, suspect_after=opts.suspect_after,
                dead_after=opts.dead_after,
                rejoin_after=opts.rejoin_after)
        #: The scale controller (lifecycle.ScalingPolicy): observe/act
        #: runs ONLY on the scale-loop thread, so the policy object
        #: itself needs no lock; its decisions mutate _active/_warm/ring
        #: under _hlock like everyone else.
        self._policy = ScalingPolicy(
            self._min, self._max, up_queue=opts.scale_up_queue,
            down_queue=opts.scale_down_queue,
            up_wait_s=opts.scale_up_wait_s, seed=opts.scale_seed)
        #: Fleet-wide admission/SLO aggregate (queued totals, per-tenant
        #: counters, service times) refreshed by the scale loop each
        #: interval — /status serves this cache instead of paying N
        #: replica round-trips per probe.
        self._fleet_stats: dict = {}            # guarded-by: _hlock
        #: Last successful per-replica depth sample, carried through a
        #: replica's death so the controller keeps seeing its journaled
        #: backlog as pressure. Scale-loop thread only — never shared.
        self._last_replica_stats: Dict[str, dict] = {}
        #: Scale-event ledger for /status: the last event plus counters.
        self._last_scale: Optional[dict] = None  # guarded-by: _hlock
        self._scale_events: List[dict] = []     # guarded-by: _hlock
        self.scale_ups = 0                      # guarded-by: _hlock
        self.scale_downs = 0                    # guarded-by: _hlock
        #: Serializes warm-pool refills (one background refill thread
        #: at a time; acquire is non-blocking — a running refill already
        #: converges the pool).
        self._refill_lock = threading.Lock()
        #: Cold names claimed for launch but not yet active/warm — keeps
        #: a concurrent scale-up and warm refill off the same spec.
        self._pending_cold: set = set()         # guarded-by: _hlock
        self._defaults = G2VecConfig()     # identical to the daemon's
        self._hlock = threading.RLock()
        #: One lock per replica: fence → migrate → relaunch must be
        #: atomic per replica, whether the probe loop, boot_fleet, or an
        #: admin drain initiates it — two of those interleaving would
        #: fence/launch the same ReplicaSpec concurrently (and SIGKILL
        #: the other's freshly relaunched process).
        self._rep_locks = {n: threading.Lock()
                           for n in self.fleet.names()}
        #: Replicas mid-``drain_replica``: the probe loop skips them (a
        #: draining replica flaps dead→rejoining→dead and would trigger
        #: a failover that migrates the journal the drain contractually
        #: leaves in place), and _failover refuses to run on them.
        self._admin_draining: set = set()       # guarded-by: _hlock
        self._stop = threading.Event()
        #: Routing table and counters: written by the probe loop's
        #: failovers AND per-connection relay threads, snapshotted by
        #: /status — all under _hlock (the lock-discipline checker
        #: enforces every mutation site).
        # guarded-by: _hlock
        self._assigned: Dict[str, str] = {}     # job_id -> replica name
        self._requeue_latencies: List[float] = []   # guarded-by: _hlock
        self.failovers = 0                      # guarded-by: _hlock
        self.jobs_routed = 0                    # guarded-by: _hlock
        #: Per-replica view of that replica's published bundles — the
        #: failover READ path: when a bundle's home replica is dead the
        #: router maps the bundle itself (shared filesystem) and
        #: answers with the exact same inventory.run_query the daemon
        #: uses, so reads survive replica death like writes do. Each
        #: catalog is internally locked; this dict is immutable after
        #: __init__.
        self._inv_local: Dict[str, inventory.InventoryCatalog] = {
            n: inventory.InventoryCatalog(
                [os.path.join(self.fleet.replica(n).state_dir,
                              "inventory")],
                budget_bytes=opts.inventory_budget_bytes)
            for n in self.fleet.names()}
        #: job_id -> replica name, populated on first lookup. Bundle
        #: placement is sticky (a job's bundle only ever appears on its
        #: home replica's disk) and bundles are never deleted, so a
        #: POSITIVE lookup stays valid forever; only misses pay the
        #: disk scan. Plain dict: entry writes are idempotent, so
        #: GIL-atomic get/setdefault need no extra lock.
        self._owner_cache: Dict[str, str] = {}
        #: The leadership lease — None when HA is off (the default):
        #: a 1-router fleet never writes leader.json, router_epoch
        #: stays 0, and every mutating command goes out epoch-less.
        #: The LeaderLease carries its own lock; held/epoch reads are
        #: GIL-atomic snapshots.
        self._lease: Optional[leader.LeaderLease] = None
        if opts.standby or opts.lease_ttl_s > 0:
            self._lease = leader.LeaderLease(
                opts.fleet_dir,
                ttl_s=opts.lease_ttl_s or leader.DEFAULT_TTL_S)
        #: Mutating commands a replica refused because our epoch was
        #: superseded — the router-side zombie tripwire for /status.
        self.stale_rejects = 0                  # guarded-by: _hlock
        if opts.remote_replicas:
            # No spec may ever be fenced by pid: the processes live
            # behind a relay / on another host, so local kill(2) proof
            # is unobtainable and quarantine is the only fence.
            for n in self.fleet.names():
                self.fleet.replica(n).local = False
        self.tcp_addr: Optional[Tuple[str, int]] = None
        self._t0 = time.time()

    @property
    def router_epoch(self) -> int:
        """The fencing epoch stamped on mutating replica commands;
        0 = no leadership machinery (every _request/_relay_to drops
        the field so the wire payload is byte-identical to PR 16)."""
        return self._lease.epoch if self._lease is not None else 0

    # ---- replica I/O ------------------------------------------------------

    def _replica_addr(self, name: str) -> Optional[str]:
        return self.fleet.replica(name).addr

    def _request(self, name: str, req: dict,
                 timeout: Optional[float] = None) -> dict:
        """One request / one response to a replica (status, result,
        cancel, drain — everything but the submit relay)."""
        addr = self._replica_addr(name)
        if not addr:
            raise ConnectionError(f"replica {name} has no address yet")
        out = dict(req)
        if self.opts.auth_token is not None:
            out.setdefault("auth_token", self.opts.auth_token)
        if not out.get("router_epoch"):
            # Epoch 0 / absent = no leadership machinery: drop the
            # field so HA-off wire payloads stay byte-identical.
            out.pop("router_epoch", None)
        sock = protocol.dial(addr, timeout=timeout
                             if timeout is not None else 10.0)
        try:
            f = sock.makefile("rwb")
            protocol.write_event(f, out)
            ev = protocol.read_event(f)
            if ev is None:
                raise ConnectionError(f"replica {name} closed the stream")
            if ev.get("error") == "stale_epoch":
                self._on_stale_epoch(name, out.get("op"), ev)
            return ev
        finally:
            try:
                sock.close()
            except OSError:
                pass

    def _on_stale_epoch(self, name: str, op, ev: dict) -> None:
        """A replica refused our mutating command because our fencing
        epoch was superseded: this router lost the lease and is a
        zombie. Count + emit; the lease loop handles re-election."""
        with self._hlock:
            self.stale_rejects += 1
        self.metrics.emit("stale_epoch", op=op, replica=name,
                          side="router",
                          got_epoch=ev.get("got_epoch"),
                          seen_epoch=ev.get("seen_epoch"))
        self.console(f"[router] {name} rejected {op!r}: our epoch "
                     f"{ev.get('got_epoch')} is stale (replica has "
                     f"seen {ev.get('seen_epoch')}) — leadership moved")

    def probe(self, name: str) -> Tuple[bool, int]:
        """One health probe: (reachable, journal_depth)."""
        try:
            st = self._request(name, {"op": "status"},
                               timeout=self.opts.probe_deadline)
            if st.get("event") != "status":
                return False, 0
            if st.get("fenced"):
                # Reachable but quarantined (its fence marker is still
                # down): a fenced replica rejects every admission, so
                # letting it rejoin the ring would bounce its whole key
                # range. It stays "dead" to the health machine until a
                # verified restart clears the marker.
                return False, int(st.get("journal_depth") or 0)
            pid = st.get("pid")
            spec = self.fleet.replica(name)
            if spec.pid is None and isinstance(pid, int):
                # Remote/relayed replicas keep the externally
                # published tcp_addr file: the daemon's self-reported
                # listen addr is its DIRECT socket, and adopting it
                # would silently route around the relay (and around
                # any partition injector sitting on it).
                self.fleet.adopt(
                    name, pid,
                    None if self.opts.remote_replicas
                    else st.get("listen"),
                    local=not self.opts.remote_replicas)
            return True, int(st.get("journal_depth") or 0)
        except (OSError, protocol.ProtocolError, ValueError):
            return False, 0

    # ---- placement --------------------------------------------------------

    def _join_key_str(self, payload: dict) -> str:
        """The placement key: the daemon's batching join key, stringified.
        Raises ValueError for payloads the replica would reject — router
        admission catches garbage before it costs a forward."""
        jobd = payload.get("job")
        if not isinstance(jobd, dict):
            raise ValueError("submit needs a 'job' object")
        base = dict(jobd)
        base.pop("variants", None)
        base.pop("seeds", None)
        cfg = config_from_job(base, self._defaults)
        return repr(serve_join_key(cfg))

    def _eligible(self) -> List[str]:
        with self._hlock:
            return [n for n, h in self.health.items() if h.in_ring]

    def _ring_lookup(self, key: str, eligible) -> Optional[str]:
        # The ring mutates on scale events now; lookups take the same
        # lock as add/remove so a bisect never reads a half-built list.
        with self._hlock:
            return self.ring.lookup(key, eligible=eligible)

    def _pick_salted(self, key: str, eligible) -> Optional[str]:
        """Salted placement: the ring owner of ``key`` plus up to
        ``join_spread - 1`` salted alternates, least-loaded wins (ties
        go to the primary, so spread 1 and a calm fleet reproduce the
        pre-salting placement exactly). Load = the scale loop's last
        queued+running sample plus our own in-flight assignments, so
        a flash crowd spreads within one scale interval instead of
        pinning the primary until its queue sample catches up."""
        with self._hlock:
            primary = self.ring.lookup(key, eligible=eligible)
            if primary is None or self.opts.join_spread <= 1:
                return primary
            cands = [primary]
            for i in range(1, self.opts.join_spread):
                alt = self.ring.lookup(f"{key}#salt{i}",
                                       eligible=eligible)
                if alt is not None and alt not in cands:
                    cands.append(alt)
            if len(cands) == 1:
                return primary
            per = self._fleet_stats.get("per_replica") or {}
            assigned: Dict[str, int] = {}
            for rep in self._assigned.values():
                assigned[rep] = assigned.get(rep, 0) + 1

            def load(n: str) -> Tuple[int, int]:
                st = per.get(n) or {}
                q = st.get("queued")
                r = st.get("running")
                sampled = (q if isinstance(q, int) else 0) \
                    + (r if isinstance(r, int) else 0)
                return (sampled + assigned.get(n, 0), cands.index(n))

            return min(cands, key=load)

    def pick_replica(self, payload: dict) -> Optional[str]:
        return self._pick_salted(self._join_key_str(payload),
                                 eligible=self._eligible())

    # ---- failover ---------------------------------------------------------

    def _dead_paths(self, name: str):
        spec = self.fleet.replica(name)
        return (os.path.join(spec.state_dir, "jobs"),
                os.path.join(spec.state_dir, "results"),
                os.path.join(spec.state_dir, "ckpt"))

    def _relay_token_of(self, name: str) -> Optional[str]:
        """A replica's migration secret (``<state>/relay_token``,
        minted by the daemon at boot): attached to journal-migration
        resubmits so the survivor honors ``requeue``/``submitted_at``.
        The router can read it because it co-hosts the fleet's state
        dirs — which is exactly the trust being proven. None (file not
        there yet / unreadable) degrades the resubmit to a normal
        gated submit, never blocks it."""
        try:
            with open(os.path.join(self.fleet.replica(name).state_dir,
                                   "relay_token")) as fh:
                return fh.read().strip() or None
        except OSError:
            return None

    def _failover(self, name: str, relaunch: bool = True) -> int:
        """Fence a dead replica, migrate its journal to survivors, then
        relaunch it. Returns the number of jobs re-queued. Serialized
        per replica via _rep_locks (the probe loop, boot_fleet, and
        drain_replica connection handlers all get here), and suppressed
        outright while an admin drain owns the replica — a maintenance
        drain's journal must NOT migrate."""
        with self._rep_locks[name]:
            with self._hlock:
                if name in self._admin_draining:
                    return 0
            return self._failover_locked(name, relaunch)

    def _failover_locked(self, name: str, relaunch: bool) -> int:
        died_at = time.monotonic()
        spec = self.fleet.replica(name)
        rc = self.fleet.fence(name, grace_s=self.opts.fence_grace_s)
        if rc is None and not spec.local:
            # UNVERIFIED death: the replica is merely unreachable — it
            # may be alive across an asymmetric partition, mid-batch on
            # the very journal we are about to migrate. Split-brain
            # guard: bump the fencing epoch (so the corpse's view of
            # the world is provably stale) and drop a quarantine
            # marker in its state dir BEFORE reading the journal; the
            # partitioned daemon sees the marker at its next shard
            # boundary, parks everything journaled, and stops
            # publishing. If we ourselves lost the lease (bump() == 0
            # while HA is on), we are the zombie — no fencing rights,
            # no migration; the real leader owns this corpse.
            if self._lease is not None:
                fence_epoch = self._lease.bump()
                if fence_epoch == 0:
                    self.console(f"[router] NOT migrating {name}: "
                                 f"lease lost (we are the zombie)")
                    return 0
            else:
                fence_epoch = 0     # marker presence alone quarantines
            leader.write_fence_marker(spec.state_dir, fence_epoch)
            self.metrics.emit("fenced", replica=name, epoch=fence_epoch)
            self.console(f"[router] quarantined {name} (unverified "
                         f"death, fence epoch {fence_epoch})")
        jobs_dir, results_dir, ckpt_dir = self._dead_paths(name)
        entries = []
        if os.path.isdir(jobs_dir):
            for fn in sorted(os.listdir(jobs_dir)):
                if fn.endswith(".json"):
                    try:
                        with open(os.path.join(jobs_dir, fn)) as fh:
                            entries.append(json.load(fh))
                    except (OSError, ValueError):
                        self.console(f"[router] unreadable journal "
                                     f"{name}/{fn}; leaving for the "
                                     f"replica's own recovery")
        requeued = 0
        for rec in sorted(entries,
                          key=lambda r: r.get("submitted_at", 0.0)):
            job_id = rec.get("job_id")
            payload = rec.get("payload")
            if not isinstance(job_id, str) or not isinstance(payload, dict):
                continue
            jpath = os.path.join(jobs_dir, f"{job_id}.json")
            if os.path.exists(os.path.join(results_dir,
                                           f"{job_id}.json")):
                # Died between result write and journal unlink: the job
                # FINISHED. Reconcile, never re-run (exactly-once).
                try:
                    os.unlink(jpath)
                except OSError:
                    pass
                self.metrics.emit("failover_reconciled", job_id=job_id,
                                  from_replica=name)
                continue
            dup_home = next(
                (n for n in self.fleet.names() if n != name
                 and os.path.exists(os.path.join(self._dead_paths(n)[0],
                                                 f"{job_id}.json"))),
                None)
            if dup_home is not None:
                # Double-journaled (a crash inside a previous failover's
                # resubmit-then-unlink window): the job already lives on
                # another replica. Dropping the dead copy — NOT
                # re-migrating it — is what keeps it exactly-once.
                try:
                    os.unlink(jpath)
                except OSError:
                    pass
                self.metrics.emit("failover_reconciled", job_id=job_id,
                                  from_replica=name, already_on=dup_home)
                continue
            try:
                target = self._ring_lookup(self._join_key_str(payload),
                                           eligible=[n for n in
                                                     self._eligible()
                                                     if n != name])
            except (ValueError, TypeError):
                target = None
            if target is None:
                # No survivor can take it — leave it journaled; the
                # relaunched replica re-queues it itself (PR 9 path).
                self.metrics.emit("failover_deferred", job_id=job_id,
                                  from_replica=name)
                continue
            tgt_ckpt = self._dead_paths(target)[2]
            for d in glob.glob(os.path.join(ckpt_dir, f"{job_id}.*")):
                dst = os.path.join(tgt_ckpt, os.path.basename(d))
                # Cursor migration: the survivor resumes mid-stream from
                # the dead replica's last durable checkpoint.
                shutil.copytree(d, dst, dirs_exist_ok=True)
            # requeue=True: this job was ALREADY admitted once (the
            # client holds an ack) — the survivor must skip its tenant
            # bucket and shed gate (PR 16: a chaos run showed a spike's
            # whole migrated journal bouncing off the survivor's
            # admission SLOs and dying of deadline_exceeded on the
            # corpse instead). submitted_at keeps the deadline clock
            # measuring from the ORIGINAL admission. The target's
            # relay_token is what makes the survivor believe either
            # field — clients can't set them (sanitize_client_submit
            # strips, the daemon verifies).
            out = dict(payload, op="submit", requeue=True,
                       router_epoch=self.router_epoch)
            tok = self._relay_token_of(target)
            if tok:
                out["relay_token"] = tok
            sa = rec.get("submitted_at")
            if isinstance(sa, (int, float)) and not isinstance(sa, bool):
                out["submitted_at"] = sa
            if not payload.get("idem_key"):
                # Keyless entry (submitted straight to the replica's
                # socket, no router): there is no key to derive the id
                # from, so pass the journaled job_id through explicitly
                # — otherwise the survivor mints a fresh serial id, the
                # migrated cursors (copied under the old id) are
                # orphaned, and the client's poll handle goes dark.
                out["job_id"] = job_id
            try:
                resp = self._request(target, out, timeout=30.0)
            except (OSError, protocol.ProtocolError) as e:
                self.metrics.emit("failover_error", job_id=job_id,
                                  from_replica=name, to_replica=target,
                                  error=str(e)[:200])
                continue           # journal entry stays; next pass retries
            if resp.get("event") != "accepted":
                self.metrics.emit("failover_error", job_id=job_id,
                                  from_replica=name, to_replica=target,
                                  error=str(resp)[:200])
                continue
            try:
                os.unlink(jpath)   # after the survivor journaled it —
            except OSError:        # a crash here double-journals, and the
                pass               # idem table dedups the double
            latency = time.monotonic() - died_at
            requeued += 1
            # One critical section for the whole failover record: a
            # /status racing these lines must never see the assignment
            # without the counter (or copy the latency list mid-append).
            with self._hlock:
                self._requeue_latencies.append(latency)
                self.failovers += 1
                self._assigned[job_id] = target
            self.metrics.emit("failover", job_id=job_id,
                              from_replica=name, to_replica=target,
                              deduped=bool(resp.get("deduped")),
                              latency_s=round(latency, 4))
            self.console(f"[router] failover {job_id}: {name} -> "
                         f"{target} ({latency:.2f}s after death)")
        if relaunch and spec.local and not self._stop.is_set():
            try:
                # launch() clears any fence marker on this state dir —
                # a fresh local daemon starts unquarantined.
                self.fleet.launch(name)
                self.metrics.emit("replica_relaunched", replica=name)
            except (RuntimeError, TimeoutError, OSError) as e:
                self.metrics.emit("replica_relaunch_failed", replica=name,
                                  error=str(e)[:200])
                self.console(f"[router] relaunch of {name} failed: {e}")
        # Non-local replicas are NOT relaunched (their supervisor owns
        # the process) and their fence marker stays: only a verified
        # restart on that state dir may lift the quarantine.
        return requeued

    # ---- probe loop -------------------------------------------------------

    def _probe_targets(self) -> List[str]:
        """Names worth probing: the active set plus the warm pool. Cold
        names (sized into the fleet but never launched) are skipped —
        probing them would declare them dead and fire pointless
        failover/relaunch cycles on processes that should not exist."""
        with self._hlock:
            return sorted(self._active) + list(self._warm)

    def _probe_loop(self) -> None:
        due: Dict[str, float] = {}
        while not self._stop.is_set():
            now = time.monotonic()
            for name in self._probe_targets():
                h = self.health[name]
                if now < due.get(name, 0.0):
                    continue
                with self._hlock:
                    if name in self._admin_draining:
                        # Intentionally down for maintenance: probing it
                        # would flap dead→rejoining→dead and race the
                        # drain's own fence+relaunch with a failover.
                        due[name] = time.monotonic() \
                            + self.opts.probe_interval
                        continue
                ok, jd = self.probe(name)
                with self._hlock:
                    trans = h.on_probe(ok, journal_depth=jd,
                                       now=time.time())
                    # A forward/query thread may have force_dead()ed the
                    # replica between two probes. Then on_probe sees an
                    # already-dead state and reports NO transition — but
                    # the corpse is real and nobody has fenced it. A
                    # failed probe of a dead, still-unrecovered replica
                    # must (re)trigger failover, or its journal is
                    # stranded and every sticky submit waits forever.
                    dead_unrecovered = (not ok and trans is None
                                        and h.state == "dead")
                due[name] = time.monotonic() \
                    + h.probe_interval(self.opts.probe_interval)
                if trans is not None:
                    self.metrics.emit("replica_health", replica=name,
                                      from_state=trans[0],
                                      to_state=trans[1],
                                      journal_depth=jd)
                    self.console(f"[router] {name}: {trans[0]} -> "
                                 f"{trans[1]} (journal {jd})")
                if (trans is not None and trans[1] == "dead") \
                        or dead_unrecovered:
                    self._failover(name)
            self._stop.wait(0.05)

    # ---- scaling ------------------------------------------------------

    def _collect_fleet_stats(self) -> dict:
        """One ``status`` sweep of the active set: the controller's
        input signal and the /status fleet aggregate, in one pass.
        Sums queue depths, averages observed service times, and merges
        the per-tenant admission ledgers replica-side shedding keeps."""
        with self._hlock:
            targets = sorted(self._active)
        # Prune carryover for names that left the active set, so a
        # demoted replica's last queue depth can't haunt the signal.
        for gone in set(self._last_replica_stats) - set(targets):
            del self._last_replica_stats[gone]
        queued = running = reachable = 0
        svc: List[float] = []
        tenants: Dict[str, Dict[str, int]] = {}
        per_replica: Dict[str, dict] = {}
        for name in targets:
            try:
                st = self._request(name, {"op": "status"},
                                   timeout=self.opts.probe_deadline)
            except (OSError, protocol.ProtocolError, ValueError):
                st = None
            if st is None or st.get("event") != "status":
                # Mid-death blind spot: a SIGKILLed replica answers
                # nothing while its journaled jobs still exist. Carrying
                # its last-known depth keeps the controller under
                # pressure through the outage instead of reading the
                # dead air as an idle fleet.
                last = self._last_replica_stats.get(name)
                if last:
                    queued += last["queued"]
                    running += last["running"]
                    per_replica[name] = {**last, "unreachable": True}
                continue
            reachable += 1
            q = int(st.get("queued") or 0)
            # "running" is a list of in-flight job ids in the daemon's
            # status; older builds reported a bare count. Accept both.
            rv = st.get("running")
            r = len(rv) if isinstance(rv, (list, tuple)) else int(rv or 0)
            queued += q
            running += r
            s = st.get("service_time_s")
            if isinstance(s, (int, float)):
                svc.append(float(s))
            for t, c in (st.get("tenants") or {}).items():
                if isinstance(c, dict):
                    agg = tenants.setdefault(t, {})
                    for k, v in c.items():
                        if isinstance(v, int):
                            agg[k] = agg.get(k, 0) + v
            per_replica[name] = {"queued": q, "running": r,
                                 "jobs_done": st.get("jobs_done"),
                                 "service_time_s": s}
            self._last_replica_stats[name] = {"queued": q, "running": r}
        service = sum(svc) / len(svc) if svc else None
        wait_est = (queued * service / max(1, reachable)) \
            if service is not None else None
        return {"sampled_at": round(time.time(), 3),
                "replicas_reached": reachable,
                "queued": queued, "running": running,
                "service_time_s": (round(service, 4)
                                   if service is not None else None),
                "est_wait_s": (round(wait_est, 4)
                               if wait_est is not None else None),
                "tenants": tenants, "per_replica": per_replica}

    def _scale_loop(self) -> None:
        """The control loop: one stats sweep + (if elastic) one policy
        tick per scale_interval. Runs for static fleets too — the
        sweep is what keeps the /status fleet aggregate fresh."""
        while not self._stop.is_set():
            t0 = time.monotonic()
            stats = self._collect_fleet_stats()
            with self._hlock:
                self._fleet_stats = stats
                active_n = len(self._active)
            if self._elastic:
                decision = self._policy.observe(
                    stats["queued"], active_n,
                    est_wait_s=stats.get("est_wait_s"))
                if decision == "up":
                    self._scale_up()
                elif decision == "down":
                    self._scale_down()
                else:
                    # Warm-pool refill waits for CALM (pressure below
                    # the up thresholds): a daemon boot + canary is
                    # real CPU, and spending it while the active set
                    # is fighting a surge slows the exact replicas the
                    # refill is supposed to back up. The pool refills
                    # as soon as the surge passes; until then the
                    # hole only delays the NEXT promotion.
                    pressure = stats["queued"] / max(1, active_n)
                    wait = stats.get("est_wait_s")
                    if (pressure < self.opts.scale_up_queue
                            and (wait is None
                                 or wait < self.opts.scale_up_wait_s)):
                        self._ensure_warm()
            self._stop.wait(max(0.05, self.opts.scale_interval
                                - (time.monotonic() - t0)))

    def _next_cold(self) -> Optional[str]:
        """Claim the first never-launched fleet name (not active, not
        warm, not mid-launch by another thread). The claim lives in
        ``_pending_cold`` until the caller moves the name into
        active/warm or releases it on launch failure."""
        with self._hlock:
            busy = self._active | set(self._warm) | self._pending_cold
            for name in self.fleet.names():
                if name not in busy and not self.fleet.alive(name):
                    self._pending_cold.add(name)
                    return name
        return None

    def _claim_warm(self) -> Tuple[Optional[str], bool]:
        """The claim half of a scale-up in ONE critical section:
        capacity check + warm-pool pop. Returns (spare_or_None,
        capacity_available). The commit (ring/active add) happens
        after the launch, which cannot run under _hlock; the split is
        race-free because the scale-loop thread is the only caller
        that grows the active set."""
        with self._hlock:
            if len(self._active) >= self._max:
                return None, False
            return (self._warm.pop(0) if self._warm else None), True

    def _scale_up(self) -> None:
        """Add one replica to the ring: promote a warm spare (a ring
        add — near-instant) when the pool has one, else pay a cold
        daemon boot. The warm-pool refill is NOT kicked here — the
        scale loop refills once pressure reads calm again."""
        t0 = time.monotonic()
        name, capacity = self._claim_warm()
        if not capacity:
            return
        from_warm = name is not None
        if from_warm:
            self.metrics.emit("warm_spare", replica=name,
                              outcome="promoted")
        else:
            name = self._next_cold()
            if name is None:
                return
            try:
                with self._rep_locks[name]:
                    self.fleet.launch(name)
            except (RuntimeError, TimeoutError, OSError) as e:
                with self._hlock:
                    self._pending_cold.discard(name)
                self.metrics.emit("replica_relaunch_failed",
                                  replica=name, error=str(e)[:200])
                return
        reaction = time.monotonic() - t0
        with self._hlock:
            self._pending_cold.discard(name)
            self.ring.add(name)
            self._active.add(name)
            self.scale_ups += 1
            active_n = len(self._active)
            ev = {"kind": "scale_up", "replica": name,
                  "from_warm": from_warm,
                  "reaction_s": round(reaction, 4),
                  "active": active_n, "at": round(time.time(), 3)}
            self._last_scale = ev
            self._scale_events.append(ev)
        self.metrics.emit("scale_up", replica=name, from_warm=from_warm,
                          reaction_s=round(reaction, 4), active=active_n)
        self.console(f"[router] scale-up: +{name} "
                     f"({'warm' if from_warm else 'cold'}, "
                     f"{reaction:.2f}s, active={active_n})")
        # No refill here: a scale-up means the fleet is under pressure,
        # and the refill boot would compete with it — the scale loop
        # refills the pool once the pressure reading comes back calm.

    def _scale_down(self) -> None:
        """Remove one replica from the ring and drain it gracefully in
        the background (the drain can take minutes; the control loop
        must not stall behind it). The ring removal happens HERE, so
        no new placements land on the victim from this point on."""
        with self._hlock:
            candidates = [n for n in self._active
                          if n not in self._admin_draining]
            if len(self._active) <= self._min or not candidates:
                return
            victim = self._policy.choose_victim(candidates)
            self._admin_draining.add(victim)
            self.health[victim].force_dead(now=time.time())
            self.ring.remove(victim)
            self._active.discard(victim)
            active_n = len(self._active)
        threading.Thread(target=self._demote, args=(victim, active_n),
                         name="g2v-router-demote", daemon=True).start()

    def _demote(self, victim: str, active_n: int) -> None:
        """Graceful scale-down, off the control loop: drain → fence →
        relaunch. The fresh daemon re-queues its own journal OUT of
        the ring and finishes those jobs (the PR 9 recovery path), so
        a scale-down never loses work — then the replica parks in the
        warm pool, first in line for the next scale-up."""
        rc = None
        try:
            with self._rep_locks[victim]:
                try:
                    self._request(victim,
                                  {"op": "drain",
                                   "router_epoch": self.router_epoch},
                                  timeout=10.0)
                except (OSError, protocol.ProtocolError):
                    pass
                rc = self.fleet.fence(victim, grace_s=120.0)
                self.metrics.emit("replica_drained", replica=victim,
                                  rc=rc)
                if self._stop.is_set():
                    return
                try:
                    self.fleet.launch(victim)
                except (RuntimeError, TimeoutError, OSError) as e:
                    self.metrics.emit("replica_relaunch_failed",
                                      replica=victim,
                                      error=str(e)[:200])
                    return
        finally:
            with self._hlock:
                self._admin_draining.discard(victim)
        with self._hlock:
            self._warm.append(victim)
            self.scale_downs += 1
            ev = {"kind": "scale_down", "replica": victim, "rc": rc,
                  "active": active_n, "at": round(time.time(), 3)}
            self._last_scale = ev
            self._scale_events.append(ev)
        self.metrics.emit("scale_down", replica=victim,
                          active=active_n, rc=rc)
        self.metrics.emit("warm_spare", replica=victim,
                          outcome="demoted")
        self.console(f"[router] scale-down: -{victim} (drained, "
                     f"rc={rc}, active={active_n})")
        # The drain relaunched the daemon, so the parked spare is a
        # fresh (cold) process — re-warm it for the next promotion.
        self._warm_up(victim)

    def _warm_deficit(self) -> bool:
        """Does the warm pool need another spare? A stale True only
        overfills the pool by one (promotions drain it first — the
        documented, harmless direction)."""
        with self._hlock:
            return len(self._warm) < self.opts.warm_spares

    def _add_warm(self, name: str) -> None:
        with self._hlock:
            self._pending_cold.discard(name)
            self._warm.append(name)

    def _warmup_req(self, name: str, job: dict) -> dict:
        """The canary submit for one spare. ``idem_key`` (the protocol
        field — see protocol.SUBMIT_KEYS) is boot-scoped: stable within
        one daemon boot, so a re-warm of an already-warm process dedups
        to an instant re-ack instead of re-running the canary; a fresh
        boot gets a fresh key and warms once."""
        boots = self.fleet.replica(name).boots
        req = {"op": "submit", "job": job, "tenant": "_warmup",
               "idem_key": f"warmup-{name}-b{boots}",
               "router_epoch": self.router_epoch}
        if not req.get("router_epoch"):
            req.pop("router_epoch", None)     # HA off: byte-compat
        if self.opts.auth_token is not None:
            req["auth_token"] = self.opts.auth_token
        return req

    def _warm_up(self, name: str) -> None:
        """Pre-warm a parked spare with the operator's canary job
        (``--warmup-job``), submitted straight to the OUT-of-ring
        spare and run to completion. A freshly launched daemon is
        only *process*-warm: its first real batch still pays jax
        init, tracing, and the hot shapes' XLA compiles, and that
        bill comes due exactly when a surge promotes it (the 1-core
        chaos rig measured a promoted-but-cold spare stalling its
        whole queue ~15 s doing this). The canary is an ordinary
        journaled job against the spare's own state dir with a
        boot-scoped idem key — every fresh process warms once, an
        already-warm process dedups to an instant re-ack, and a
        failure only costs warmth, never the pool slot. Spares are
        promotable mid-warmup: the canary is just a queued job."""
        path = self.opts.warmup_job
        if not path:
            return
        t0 = time.monotonic()
        try:
            with open(path) as fh:
                job = json.load(fh)
            req = self._warmup_req(name, job)
            addr = self._replica_addr(name)
            if not addr:
                raise ConnectionError(f"spare {name} has no address")
            sock = protocol.dial(addr, timeout=10.0)
            try:
                sock.settimeout(600.0)
                f = sock.makefile("rwb")
                protocol.write_event(f, req)
                ev = protocol.read_event(f)
                if ev is None or ev.get("event") != "accepted":
                    raise RuntimeError(f"canary not accepted: "
                                       f"{(ev or {}).get('event')!r} "
                                       f"{(ev or {}).get('error', '')}")
                # Drain the stream: the daemon closes it after the
                # terminal event, so EOF == canary finished.
                while protocol.read_event(f) is not None:
                    pass
            finally:
                try:
                    sock.close()
                except OSError:
                    pass
        except (OSError, ValueError, RuntimeError,
                protocol.ProtocolError) as e:
            self.metrics.emit("warm_spare", replica=name,
                              outcome="warmup_failed",
                              error=str(e)[:200])
            self.console(f"[router] warm-up of {name} failed: {e}")
        else:
            dt = time.monotonic() - t0
            self.metrics.emit("warm_spare", replica=name,
                              outcome="warmed",
                              warmup_s=round(dt, 3))
            self.console(f"[router] spare {name} warmed ({dt:.1f}s)")

    def _ensure_warm(self) -> None:
        """Refill the warm pool in the background while cold names
        remain. Non-blocking: if a refill thread is already running it
        will converge the pool on its own."""
        if self.opts.warm_spares <= 0 or self._stop.is_set():
            return
        if not self._refill_lock.acquire(blocking=False):
            return

        def _refill():
            try:
                while not self._stop.is_set():
                    if not self._warm_deficit():
                        return
                    name = self._next_cold()
                    if name is None:
                        return
                    try:
                        with self._rep_locks[name]:
                            self.fleet.launch(name)
                    except (RuntimeError, TimeoutError, OSError) as e:
                        with self._hlock:
                            self._pending_cold.discard(name)
                        self.metrics.emit("replica_relaunch_failed",
                                          replica=name,
                                          error=str(e)[:200])
                        return
                    self._add_warm(name)
                    self.metrics.emit("warm_spare", replica=name,
                                      outcome="launched")
                    self.console(f"[router] warm spare {name} ready")
                    # Warm INSIDE the refill loop, deliberately: on a
                    # CPU-shared host two concurrent daemon boots slow
                    # each other (and the active set) more than a
                    # sequential boot→warm→boot chain does.
                    self._warm_up(name)
            finally:
                self._refill_lock.release()

        threading.Thread(target=_refill, name="g2v-router-warm",
                         daemon=True).start()

    # ---- ops --------------------------------------------------------------

    def status(self) -> dict:
        """The one-probe fleet view: per-replica health + role
        (active / warm / cold), ring membership, the scale-event
        ledger, and the scale loop's cached admission aggregate
        (queue totals, per-tenant shed/goodput counters) — answering
        "is the fleet healthy" without N replica round-trips."""
        with self._hlock:
            reps = {}
            for name, h in self.health.items():
                spec = self.fleet.replica(name)
                role = ("active" if name in self._active
                        else "warm" if name in self._warm else "cold")
                reps[name] = dict(h.snapshot(), addr=spec.addr,
                                  pid=spec.pid, boots=spec.boots,
                                  role=role,
                                  assigned=sum(
                                      1 for r in self._assigned.values()
                                      if r == name))
            lats = sorted(self._requeue_latencies)
            jobs_routed, failovers = self.jobs_routed, self.failovers
            active = sorted(self._active)
            warm = list(self._warm)
            ring_members = self.ring.members()
            draining = sorted(self._admin_draining)
            last_scale = dict(self._last_scale) \
                if self._last_scale else None
            scale_ups, scale_downs = self.scale_ups, self.scale_downs
            fleet_stats = dict(self._fleet_stats)
            stale_rejects = self.stale_rejects
        p99 = lats[min(len(lats) - 1,
                       int(0.99 * len(lats)))] if lats else None
        if self._lease is not None:
            leader_view = {"enabled": True, "held": self._lease.held,
                           "epoch": self._lease.epoch,
                           "holder": self._lease.holder,
                           "standby": self.opts.standby}
        else:
            leader_view = {"enabled": False}
        return {"event": "status", "role": "router", "pid": os.getpid(),
                "leader": leader_view,
                "stale_rejects": stale_rejects,
                "uptime_s": round(time.time() - self._t0, 1),
                "listen": (f"{self.tcp_addr[0]}:{self.tcp_addr[1]}"
                           if self.tcp_addr else None),
                "fleet_dir": self.opts.fleet_dir,
                "replicas": reps,
                "active": active,
                "ring": ring_members,
                "warm_pool": warm,
                "warm_pool_size": len(warm),
                "admin_draining": draining,
                "autoscale": {"elastic": self._elastic,
                              "min_replicas": self._min,
                              "max_replicas": self._max,
                              "warm_spares": self.opts.warm_spares,
                              "up_queue": self.opts.scale_up_queue,
                              "down_queue": self.opts.scale_down_queue,
                              "up_wait_s": self.opts.scale_up_wait_s,
                              "interval_s": self.opts.scale_interval},
                "last_scale_event": last_scale,
                "scale_ups": scale_ups,
                "scale_downs": scale_downs,
                "fleet": fleet_stats,
                "jobs_routed": jobs_routed,
                "failovers": failovers,
                "requeue_latency_p99_s": (round(p99, 4)
                                          if p99 is not None else None),
                "requeue_latencies_s": [round(v, 4) for v in lats]}

    def _read_result_any(self, job_id: str) -> Optional[dict]:
        """The durable result record from ANY replica's results dir —
        the fleet is co-located with the router, so the read path skips
        the network (and works while a replica is down)."""
        for name in self.fleet.names():
            path = os.path.join(self._dead_paths(name)[1],
                                f"{job_id}.json")
            try:
                with open(path) as fh:
                    return json.load(fh)
            except (OSError, ValueError):
                continue
        return None

    def _journaled_anywhere(self, job_id: str) -> bool:
        return self._journal_owner(job_id) is not None

    def _journal_owner(self, job_id: str) -> Optional[str]:
        """The replica whose journal holds ``job_id``, or None. Three
        stat calls — cheap enough to consult on every keyed submit."""
        for n in self.fleet.names():
            if os.path.exists(os.path.join(self._dead_paths(n)[0],
                                           f"{job_id}.json")):
                return n
        return None

    def handle_result(self, job_id: str) -> dict:
        rec = self._read_result_any(job_id)
        if rec is not None:
            with self._hlock:
                self._assigned.pop(job_id, None)
            return rec
        return {"event": "pending", "job_id": job_id,
                "journaled": self._journaled_anywhere(job_id)}

    def handle_cancel(self, job_id: str) -> dict:
        """Broadcast: after a failover the client's idea of where the
        job lives is stale, so ask every reachable replica."""
        answers = []
        for name in self.fleet.names():
            try:
                resp = self._request(
                    name, {"op": "cancel", "job_id": job_id,
                           "router_epoch": self.router_epoch},
                    timeout=5.0)
            except (OSError, protocol.ProtocolError):
                continue
            answers.append(dict(resp, replica=name))
            if resp.get("event") in ("cancelled", "cancelling"):
                return dict(resp, replica=name)
        return {"event": "error", "error": f"job {job_id!r} not found on "
                                           f"any reachable replica",
                "answers": answers}

    def handle_drain_replica(self, name: str) -> dict:
        """Synchronous graceful drain of one replica: forward ``drain``,
        wait for the process to exit 0, relaunch it. The journal entries
        it checkpoints re-queue on its OWN relaunch (no migration — this
        is maintenance, not failure). The _admin_draining flag keeps the
        probe loop away for the duration (a half-drained replica answers
        some probes and fails others, which would otherwise declare it
        dead and fire a concurrent, journal-migrating failover), and the
        per-replica lock waits out any failover already in flight before
        touching the process."""
        if name not in self.health:
            return {"event": "error",
                    "error": f"unknown replica {name!r}"}
        with self._hlock:
            if name not in self._active:
                # Warm spares hold zero jobs and cold names hold no
                # process — "draining" either is at best a no-op and at
                # worst a fence/relaunch on a spec the scale controller
                # owns.
                role = "warm" if name in self._warm else "cold"
                return {"event": "error",
                        "error": f"replica {name!r} is not active "
                                 f"(role: {role}); only in-ring "
                                 f"replicas can be drained"}
            if name in self._admin_draining:
                return {"event": "error",
                        "error": f"replica {name!r} is already draining"}
            self._admin_draining.add(name)
            # Out of the ring immediately — no new placements land here.
            self.health[name].force_dead(now=time.time())
        try:
            with self._rep_locks[name]:
                try:
                    resp = self._request(
                        name, {"op": "drain",
                               "router_epoch": self.router_epoch},
                        timeout=10.0)
                except (OSError, protocol.ProtocolError) as e:
                    resp = {"event": "error", "error": str(e)[:200]}
                rc = self.fleet.fence(name, grace_s=120.0)  # graceful
                self.metrics.emit("replica_drained", replica=name, rc=rc)
                try:
                    self.fleet.launch(name)
                except (RuntimeError, TimeoutError, OSError) as e:
                    return {"event": "drained", "replica": name,
                            "rc": rc,
                            "relaunch_error": str(e)[:200],
                            "drain_response": resp}
                return {"event": "drained", "replica": name, "rc": rc,
                        "drain_response": resp}
        finally:
            with self._hlock:
                self._admin_draining.discard(name)

    # ---- query plane ------------------------------------------------------

    def _bundle_owner(self, job_id: str) -> Optional[str]:
        """The replica whose inventory holds a bundle for ``job_id``,
        or None. A disk scan — the same co-located-state trick as
        _journal_owner, so it works whether the owner is alive or not.
        The scan runs at most once per job_id: positive results are
        cached forever (see _owner_cache), which keeps the per-query
        hot path to a dict hit instead of O(replicas) directory walks;
        not-found stays a fresh scan so a bundle published after a
        miss is picked up."""
        owner = self._owner_cache.get(job_id)
        if owner is not None:
            return owner
        for name in self.fleet.names():
            known = inventory.scan_bundles(self._inv_local[name].roots)
            if job_id in known or any(k.startswith(job_id + "/")
                                      for k in known):
                return self._owner_cache.setdefault(job_id, name)
        return None

    def handle_query(self, qreq: dict) -> dict:
        """Route a read to the bundle's home replica (whose mmap + query
        caches are warm for it); answer locally from the shared state
        dirs when that replica is dead — reads survive failover like
        writes do. ``list`` fans out over alive replicas and merges in
        a disk scan of the dead ones'."""
        q = qreq.get("q")
        t0 = time.time()
        if q == "list":
            merged: Dict[str, dict] = {}
            for name in self.fleet.names():
                if not self.fleet.alive(name):
                    continue
                try:
                    resp = self._request(
                        name, {"op": "query", "q": "list"}, timeout=5.0)
                except (OSError, protocol.ProtocolError):
                    continue
                for ent in resp.get("bundles") or []:
                    if isinstance(ent, dict) and ent.get("bundle"):
                        merged.setdefault(ent["bundle"],
                                          dict(ent, replica=name))
            for name in self.fleet.names():
                if self.fleet.alive(name):
                    continue
                for ent in self._inv_local[name].listing():
                    merged.setdefault(ent["bundle"],
                                      dict(ent, replica=name,
                                           replica_down=True))
            self.metrics.emit("query", q="list", cache="none",
                              ms=round((time.time() - t0) * 1e3, 3))
            return {"event": "query_result", "q": "list",
                    "bundles": [merged[k] for k in sorted(merged)]}
        job_id = qreq.get("job_id")
        if not isinstance(job_id, str) or not job_id:
            return {"event": "error", "error": "bad_query",
                    "detail": "query needs a 'job_id' string"}
        owner = self._bundle_owner(job_id)
        if owner is None:
            return {"event": "error", "error": "not_found",
                    "job_id": job_id,
                    "detail": f"no bundle for job {job_id!r} on any "
                              f"replica"}
        if self.fleet.alive(owner):
            try:
                resp = self._request(owner, dict(qreq), timeout=10.0)
                self.metrics.emit(
                    "query", q=q, cache="forwarded", served_by=owner,
                    ms=round((time.time() - t0) * 1e3, 3))
                return dict(resp, replica=owner)
            except (OSError, protocol.ProtocolError):
                # Fall through to the local read; let the probe loop
                # confirm the death on its own cadence.
                with self._hlock:
                    self.health[owner].force_dead(now=time.time())
        cat = self._inv_local[owner]
        key, err = inventory.resolve_bundle_key(
            inventory.scan_bundles(cat.roots), job_id,
            qreq.get("variant"))
        if err is not None:
            return err
        gene = qreq.get("gene")
        if gene is not None and not isinstance(gene, str):
            return {"event": "error", "error": "bad_query",
                    "detail": f"'gene' must be a string, got {gene!r}"}
        k = qreq.get("k", 10)
        if not isinstance(k, int) or isinstance(k, bool):
            return {"event": "error", "error": "bad_query",
                    "detail": f"'k' must be an int, got {k!r}"}
        mode = qreq.get("mode", "approx")
        if mode not in inventory.QUERY_MODES:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'mode' must be one of "
                              f"{inventory.QUERY_MODES}, got {mode!r}"}
        nprobe = qreq.get("nprobe", 0)
        if not isinstance(nprobe, int) or isinstance(nprobe, bool) \
                or nprobe < 0:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'nprobe' must be a non-negative int, "
                              f"got {nprobe!r}"}
        try:
            resp = inventory.run_query(cat, q, key, gene=gene, k=k,
                                       mode=mode, nprobe=nprobe)
        except inventory.InventoryError as e:
            self.metrics.emit("query", q=q, cache="router_local",
                              served_by="router", error=e.code,
                              ms=round((time.time() - t0) * 1e3, 3))
            return {"event": "error", "error": e.code,
                    "detail": e.detail, "job_id": job_id, "bundle": key}
        self.metrics.emit("query", q=q, cache="router_local",
                          served_by="router",
                          ms=round((time.time() - t0) * 1e3, 3))
        return dict(resp, event="query_result", served_by="router")

    def handle_fquery(self, fqreq: dict) -> dict:
        """Federated cross-bundle read: scatter the sub-op to every
        ALIVE replica (each answers over its own bundles via
        daemon.handle_fquery), answer DEAD replicas' bundles from their
        shared state dirs exactly like ``list`` does, and merge the
        partials into one ranked list. Every partial carries
        ``served_by`` (and ``replica_down`` for failover reads), and
        ``bundle_overlap`` partials carry ``recall_mode`` — so a caller
        can see per bundle whether the answer came from a live owner or
        a disk read, approximately or exactly."""
        t0 = time.time()
        fq = fqreq.get("fq")
        if fq not in inventory.FQUERY_SUBOPS:
            return {"event": "error", "error": "bad_query",
                    "detail": f"unknown fquery sub-op {fq!r}; expected "
                              f"one of {inventory.FQUERY_SUBOPS}"}
        gene = fqreq.get("gene")
        if not isinstance(gene, str) or not gene:
            return {"event": "error", "error": "bad_query",
                    "detail": "fquery needs a 'gene' string"}
        k = fqreq.get("k", 50)
        if not isinstance(k, int) or isinstance(k, bool):
            return {"event": "error", "error": "bad_query",
                    "detail": f"'k' must be an int, got {k!r}"}
        mode = fqreq.get("mode", "approx")
        if mode not in inventory.QUERY_MODES:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'mode' must be one of "
                              f"{inventory.QUERY_MODES}, got {mode!r}"}
        nprobe = fqreq.get("nprobe", 0)
        if not isinstance(nprobe, int) or isinstance(nprobe, bool) \
                or nprobe < 0:
            return {"event": "error", "error": "bad_query",
                    "detail": f"'nprobe' must be a non-negative int, "
                              f"got {nprobe!r}"}
        ref_genes = fqreq.get("ref_genes")
        if ref_genes is not None and not (
                isinstance(ref_genes, list)
                and all(isinstance(g, str) for g in ref_genes)):
            return {"event": "error", "error": "bad_query",
                    "detail": "'ref_genes' must be a list of strings"}
        if fq == "bundle_overlap" and not ref_genes:
            # Resolve the reference neighbor set ONCE through the
            # normal routed read (home replica if alive, shared disk if
            # not), then forward it verbatim to every replica — all
            # partials must score against the same reference.
            job_id = fqreq.get("job_id")
            if not isinstance(job_id, str) or not job_id:
                return {"event": "error", "error": "bad_query",
                        "detail": "bundle_overlap needs 'ref_genes' or "
                                  "a reference 'job_id'"}
            ref = self.handle_query({
                "op": "query", "q": "neighbors", "job_id": job_id,
                "variant": fqreq.get("variant"), "gene": gene, "k": k,
                "mode": mode, "nprobe": nprobe,
                "auth_token": fqreq.get("auth_token")})
            if ref.get("event") != "query_result":
                return ref
            ref_genes = ref.get("neighbors")
        partials: List[dict] = []
        for name in self.fleet.names():
            forwarded = False
            if self.fleet.alive(name):
                try:
                    resp = self._request(
                        name, dict(fqreq, ref_genes=ref_genes),
                        timeout=10.0)
                    forwarded = True
                    if resp.get("event") == "fquery_result":
                        for part in resp.get("bundles") or []:
                            if isinstance(part, dict):
                                partials.append(dict(part,
                                                     served_by=name))
                except (OSError, protocol.ProtocolError):
                    # Fall through to the shared-disk read; the probe
                    # loop confirms the death on its own cadence.
                    with self._hlock:
                        self.health[name].force_dead(now=time.time())
            if forwarded:
                continue
            try:
                local = inventory.run_fquery(
                    self._inv_local[name], fq, gene, k=k, mode=mode,
                    nprobe=nprobe, ref_genes=ref_genes)
            except inventory.InventoryError:
                continue
            partials.extend(dict(p, served_by="router",
                                 replica_down=True) for p in local)
        merged = inventory.merge_fquery(fq, partials)
        self.metrics.emit(
            "fquery", fq=fq, ms=round((time.time() - t0) * 1e3, 3),
            bundles=len(merged),
            replica_down=sum(1 for p in merged
                             if p.get("replica_down")))
        return {"event": "fquery_result", "fq": fq, "gene": gene,
                "k": k, "mode": mode, "bundles": merged,
                "ref_genes": (ref_genes if fq == "bundle_overlap"
                              else None)}

    # ---- submit relay -----------------------------------------------------

    def _relay_submit(self, f, req: dict) -> None:
        payload = sanitize_client_submit(req)
        if not payload.get("idem_key"):
            # Router-minted key: even a client that never heard of idem
            # keys gets exactly-once failover semantics.
            payload["idem_key"] = f"r-{uuid.uuid4().hex}"
        try:
            self._join_key_str(payload)     # router-side admission check
        except (ValueError, TypeError) as e:
            protocol.write_event(f, {"event": "rejected",
                                     "error": "bad_job",
                                     "detail": str(e)[:500]})
            return
        jid = protocol.idem_job_id(payload["idem_key"])
        # Sticky exactly-once routing: a key this fleet has already seen
        # MUST resolve to its existing home, never to a fresh ring
        # placement. The idem dedup table is per-replica, so routing a
        # retried key to a DIFFERENT replica than the (alive) one that
        # journaled it would run the job twice — the ring answers where
        # a NEW key goes; the journals answer where an old one lives.
        # Rescan-in-a-loop because the home can be mid-migration (its
        # replica dead, the probe loop failing it over): the journal
        # entry moves to a survivor, or the result record appears.
        sticky_deadline = time.monotonic() + self.opts.sticky_deadline_s
        last_beat = time.monotonic()
        while time.monotonic() < sticky_deadline:
            rec = self._read_result_any(jid)
            if rec is not None:
                # Already finished somewhere: ack + stream the durable
                # record, exactly like a daemon-side dedup hit.
                protocol.write_event(f, {"event": "accepted",
                                         "job_id": jid, "deduped": True})
                protocol.write_event(f, rec)
                return
            owner = self._journal_owner(jid)
            if owner is None:
                break                      # fresh key -> ring placement
            if self.fleet.alive(owner) \
                    and self._relay_to(f, owner, payload):
                return
            # Home unreachable (dead or dying): never fall through to a
            # successor while its journal entry exists — wait for the
            # probe loop's fence+migrate to move it, then rescan.
            if time.monotonic() - last_beat > 5.0:
                protocol.write_event(f, {"event": "failover_wait",
                                         "job_id": jid, "stale": owner})
                last_beat = time.monotonic()
            time.sleep(0.25)
        else:
            # Sticky deadline expired with the key's journal entry still
            # on an unrecovered replica (relaunch failing over and over).
            # Ring-placing it now would hand the key to a survivor whose
            # idem table has never seen it — the duplicate run the whole
            # sticky scan exists to prevent. Refuse instead; the same
            # idem_key retried later dedups or resumes wherever the
            # entry finally lands.
            rec = self._read_result_any(jid)
            if rec is not None:
                protocol.write_event(f, {"event": "accepted",
                                         "job_id": jid, "deduped": True})
                protocol.write_event(f, rec)
                return
            owner = self._journal_owner(jid)
            if owner is not None:
                self.metrics.emit("submit_retry_later", job_id=jid,
                                  journal_owner=owner)
                protocol.write_event(
                    f, {"event": "rejected", "error": "retry_later",
                        "job_id": jid,
                        "detail": f"job is journaled on unrecovered "
                                  f"replica {owner}; resubmit with the "
                                  f"same idem_key once the fleet heals"})
                return
        tried: List[str] = []
        for _ in range(max(1, len(self.fleet.names()))):
            # Salted placement (join_spread > 1): a hot join-key flash
            # crowd spreads across a bounded candidate set instead of
            # pinning one replica while a promoted spare idles.
            target = self._pick_salted(
                self._join_key_str(payload),
                eligible=[n for n in self._eligible() if n not in tried])
            if target is None:
                break
            tried.append(target)
            if self._relay_to(f, target, payload):
                return
        protocol.write_event(
            f, {"event": "rejected", "error": "no_replicas",
                "detail": f"no healthy replica reachable "
                          f"(tried {tried or 'none'})"})

    def _relay_update(self, f, req: dict) -> None:
        """Sticky-route an ``update`` to the TARGET bundle's home
        replica — the generation pointer must have exactly one writer,
        and that writer must be the replica whose inventory root holds
        the bundle (the daemon republishes in place). A retried key
        whose update is already journaled goes back to its journal
        owner (idem dedup lives there); a finished one streams the
        durable record. No ring fallback: an update has exactly one
        legal destination, and relaying it elsewhere would fork the
        bundle's generation history — if the home is down, the client
        gets a structured ``retry_later`` and the same idem_key dedups
        or runs after failover/relaunch."""
        ureq = sanitize_client_submit(req)
        if not ureq.get("idem_key"):
            # Router-minted key: updates are idempotency-keyed by
            # contract (the daemon rejects keyless ones).
            ureq["idem_key"] = f"r-{uuid.uuid4().hex}"
        target = ureq.get("job_id")
        if not isinstance(target, str) or not target:
            protocol.write_event(
                f, {"event": "rejected", "error": "bad_job",
                    "detail": "update needs a 'job_id' string naming "
                              "the target bundle"})
            return
        jid = protocol.idem_job_id(ureq["idem_key"])
        rec = self._read_result_any(jid)
        if rec is not None:
            protocol.write_event(f, {"event": "accepted",
                                     "job_id": jid, "deduped": True})
            protocol.write_event(f, rec)
            return
        owner = self._journal_owner(jid) or self._bundle_owner(target)
        if owner is None:
            protocol.write_event(
                f, {"event": "rejected", "error": "not_found",
                    "job_id": target,
                    "detail": f"no bundle for job {target!r} on any "
                              f"replica"})
            return
        if not self.fleet.alive(owner) \
                or not self._relay_to(f, owner, ureq, op="update"):
            self.metrics.emit("update_retry_later", job_id=jid,
                              bundle_owner=owner)
            protocol.write_event(
                f, {"event": "rejected", "error": "retry_later",
                    "job_id": jid,
                    "detail": f"bundle home {owner} is unreachable; "
                              f"retry with the same idem_key once the "
                              f"replica relaunches"})
        return

    def _relay_to(self, f, target: str, payload: dict,
                  op: str = "submit") -> bool:
        """Forward one submit/update to ``target`` and relay its event
        stream. Returns False if the replica was unreachable BEFORE
        acking (safe to try the next ring successor — nothing was
        accepted)."""
        out = dict(payload, op=op,
                   router_epoch=self.router_epoch)
        if not out.get("router_epoch"):
            out.pop("router_epoch", None)     # HA off: byte-compat
        if self.opts.auth_token is not None:
            out["auth_token"] = self.opts.auth_token
        addr = self._replica_addr(target)
        if not addr:
            return False
        try:
            sock = protocol.dial(addr, timeout=10.0)
        except OSError:
            with self._hlock:
                self.health[target].force_dead(now=time.time())
            return False
        rf = sock.makefile("rwb")
        try:
            try:
                protocol.write_event(rf, out)
                first = protocol.read_event(rf)
            except (OSError, protocol.ProtocolError):
                first = None
            if first is None:
                return False               # died pre-ack: retry elsewhere
            if first.get("error") == "stale_epoch":
                # WE are the zombie: a newer leader exists. Surface the
                # reject to the client rather than spraying the stale
                # submit at ring successors (each would reject it too).
                self._on_stale_epoch(target, op, first)
                protocol.write_event(f, first)
                return True
            job_id = first.get("job_id")
            if first.get("event") == "accepted" and job_id:
                # Relay threads run concurrently: the count and the
                # assignment move together under _hlock.
                with self._hlock:
                    self.jobs_routed += 1
                    self._assigned[job_id] = target
                first = dict(first, replica=target)
                self.metrics.emit("job_routed", job_id=job_id,
                                  replica=target,
                                  deduped=bool(first.get("deduped")))
            protocol.write_event(f, first)
            if first.get("event") != "accepted":
                return True
            sock.settimeout(None)          # a batch can run for minutes
            terminal = False
            while True:
                try:
                    ev = protocol.read_event(rf)
                except (OSError, protocol.ProtocolError):
                    ev = None
                if ev is None:
                    break
                protocol.write_event(f, ev)
                if ev.get("event", "").startswith("job_") \
                        and ev.get("event") != "job_state":
                    terminal = ev.get("event") in (
                        "job_done", "job_failed", "job_cancelled",
                        "job_deadline_exceeded")
            if terminal:
                with self._hlock:
                    self._assigned.pop(job_id, None)
                return True
            # Stream died after the ack with no terminal event — the
            # replica (or its connection) is gone. The job is journaled
            # there; the probe loop will fail it over. Hold the client
            # and poll the durable record instead of dropping them.
            protocol.write_event(f, {"event": "stream_lost",
                                     "job_id": job_id,
                                     "replica": target,
                                     "note": "replica connection lost "
                                             "after ack; awaiting "
                                             "failover result"})
            self._await_result(f, job_id)
            return True
        finally:
            try:
                rf.close()
                sock.close()
            except OSError:
                pass

    def _await_result(self, f, job_id: str) -> None:
        """Poll the fleet's durable records until the failed-over job
        lands, streaming keepalives so a dead client ends the loop."""
        last_beat = time.monotonic()
        while not self._stop.is_set():
            rec = self._read_result_any(job_id)
            if rec is not None:
                protocol.write_event(f, rec)
                with self._hlock:
                    self._assigned.pop(job_id, None)
                return
            if time.monotonic() - last_beat > 5.0:
                # Raises to the caller when the client hung up.
                protocol.write_event(f, {"event": "failover_wait",
                                         "job_id": job_id})
                last_beat = time.monotonic()
            time.sleep(0.2)

    # ---- front-end --------------------------------------------------------

    def _handle_conn(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(self.opts.read_deadline_s)
        except OSError:
            pass
        max_bytes = self.opts.max_request_bytes or protocol.MAX_LINE_BYTES
        f = conn.makefile("rwb")
        try:
            try:
                first = f.readline(max_bytes + 1)
            except socket.timeout:
                return
            if not first:
                return
            if len(first) > max_bytes and not first.endswith(b"\n"):
                protocol.write_event(
                    f, {"event": "error", "error": "oversized_request",
                        "detail": f"request line exceeds the "
                                  f"{max_bytes}-byte bound"})
                return
            if first.startswith(b"GET "):
                self._serve_http(f, first)
                return
            try:
                req = json.loads(first)
                if not isinstance(req, dict):
                    raise ValueError("request must be a JSON object")
            except ValueError as e:
                protocol.write_event(f, {"event": "error",
                                         "error": f"bad request: {e}"})
                return
            op = req.get("op")
            if self.opts.auth_token is not None and op in _AUTH_OPS \
                    and req.get("auth_token") != self.opts.auth_token:
                self.metrics.emit("auth_rejected", op=op)
                protocol.write_event(
                    f, {"event": "rejected", "error": "unauthorized",
                        "detail": f"op {op!r} requires a valid "
                                  f"'auth_token' on this listener"})
                return
            if op == "submit":
                self._relay_submit(f, req)
            elif op == "update":
                self._relay_update(f, req)
            elif op == "status":
                protocol.write_event(f, self.status())
            elif op == "ping":
                protocol.write_event(f, {"event": "pong", "role": "router",
                                         "pid": os.getpid()})
            elif op == "result":
                rreq = req
                job_id = rreq.get("job_id")
                if not isinstance(job_id, str) or not job_id:
                    protocol.write_event(
                        f, {"event": "error",
                            "error": "result needs a 'job_id' string"})
                else:
                    resp = self.handle_result(job_id)
                    if resp.get("event") != "pending":
                        resp = protocol.bound_record(
                            resp, rreq.get("fields"),
                            rreq.get("max_bytes"),
                            self.opts.max_result_bytes
                            or protocol.MAX_LINE_BYTES)
                    protocol.write_event(f, resp)
            elif op == "query":
                qreq = req
                protocol.write_event(f, self.handle_query(qreq))
            elif op == "fquery":
                fqreq = req
                protocol.write_event(f, self.handle_fquery(fqreq))
            elif op == "cancel":
                job_id = req.get("job_id")
                if not isinstance(job_id, str) or not job_id:
                    protocol.write_event(
                        f, {"event": "error",
                            "error": "cancel needs a 'job_id' string"})
                else:
                    protocol.write_event(f, self.handle_cancel(job_id))
            elif op == "drain_replica":
                name = req.get("replica")
                if not isinstance(name, str) or not name:
                    protocol.write_event(
                        f, {"event": "error",
                            "error": "drain_replica needs a 'replica' "
                                     "string"})
                else:
                    protocol.write_event(f,
                                         self.handle_drain_replica(name))
            elif op == "shutdown":
                protocol.write_event(
                    f, {"event": "shutting_down",
                        "note": "replicas get SIGTERM (graceful drain); "
                                "journaled jobs re-queue on next start"})
                self._stop.set()
            else:
                protocol.write_event(f, {"event": "error",
                                         "error": f"unknown op {op!r}"})
        except (BrokenPipeError, ConnectionResetError, OSError):
            pass
        finally:
            try:
                f.close()
                conn.close()
            except OSError:
                pass

    def _serve_http(self, f, first: bytes) -> None:
        parts = first.split()
        path = parts[1].decode("latin-1") if len(parts) >= 2 else "/"
        if path in ("/status", "/status/"):
            body = json.dumps(self.status()).encode()
            head = b"HTTP/1.0 200 OK\r\n"
        else:
            body = json.dumps({"error": f"unknown path {path!r}; "
                                        f"try /status"}).encode()
            head = b"HTTP/1.0 404 Not Found\r\n"
        f.write(head + b"Content-Type: application/json\r\n"
                + f"Content-Length: {len(body)}\r\n\r\n".encode() + body)
        f.flush()

    # ---- lifecycle --------------------------------------------------------

    def boot_fleet(self) -> None:
        """Launch or adopt the ACTIVE replicas (the fleet is sized for
        the elastic maximum plus warm headroom — which names run is
        decided here and by the scale controller, not by the spec
        count). A dead active replica with a journal gets the full
        failover treatment AFTER the survivors are up, so its jobs
        migrate instead of waiting for its relaunch. Journals stranded
        on names OUTSIDE the active set (a previous run with wider
        bounds) migrate to the survivors without a relaunch. Ends by
        kicking the warm-pool fill."""
        with self._hlock:
            targets = sorted(self._active)
        live, dead = [], []
        for name in targets:
            spec = self.fleet.replica(name)
            addr_file = os.path.join(spec.state_dir, "tcp_addr")
            if os.path.exists(addr_file):
                with open(addr_file) as fh:
                    spec.addr = fh.read().strip()
                ok, jd = self.probe(name)
                if ok:
                    live.append(name)
                    self.metrics.emit("replica_adopted", replica=name,
                                      pid=spec.pid, journal_depth=jd)
                    self.console(f"[router] adopted live replica {name} "
                                 f"(pid {spec.pid})")
                    continue
            dead.append(name)
        for name in dead:
            jobs_dir = self._dead_paths(name)[0]
            depth = len(glob.glob(os.path.join(jobs_dir, "*.json"))) \
                if os.path.isdir(jobs_dir) else 0
            if depth and live:
                # Survivors exist: migrate, then relaunch (inside).
                with self._hlock:
                    self.health[name].force_dead(now=time.time())
                self._failover(name)
            elif not self.opts.remote_replicas:
                # Remote fleets are adopted, never launched: the daemons
                # live on other hosts and a local Popen would just fork
                # a replica nobody asked for.
                self.fleet.launch(name)
            live.append(name)
        for name in self.fleet.names():
            if name in targets:
                continue
            jobs_dir = self._dead_paths(name)[0]
            depth = len(glob.glob(os.path.join(jobs_dir, "*.json"))) \
                if os.path.isdir(jobs_dir) else 0
            if depth and live:
                with self._hlock:
                    self.health[name].force_dead(now=time.time())
                self._failover(name, relaunch=False)
        self._ensure_warm()

    def _lease_loop(self) -> None:
        """Renew the leadership lease at ttl/3.  On loss the router
        KEEPS serving — reads stay correct, and every mutating command
        it still emits carries its now-stale epoch, which daemons
        reject (``stale_epoch``).  The loop keeps trying to re-acquire:
        if the usurper dies in turn, this router resumes leadership
        with a fresh, higher epoch."""
        assert self._lease is not None
        interval = max(0.2, self._lease.ttl_s / 3.0)
        while not self._stop.wait(interval):
            if self._lease.held:
                if not self._lease.renew():
                    self.console(
                        f"[router] LOST leadership lease (epoch "
                        f"{self._lease.epoch} superseded) — serving "
                        f"reads only; mutations will be fenced")
            elif self._lease.acquire():
                # Re-elected (the usurper died or released).
                self.metrics.emit("leader_elected",
                                  epoch=self._lease.epoch,
                                  holder=self._lease.holder,
                                  standby=self.opts.standby)
                self.console(f"[router] re-acquired leadership lease "
                             f"(epoch {self._lease.epoch})")

    def serve_forever(self) -> int:
        import signal

        # Same GIL-handoff tuning as the daemon's serve loop: relay
        # threads, the probe loop, and router-local failover reads all
        # share this interpreter, and a forwarded query's wall includes
        # every GIL hold on the relay path.
        sys.setswitchinterval(1e-3)

        def _on_sigterm(*_):
            self._stop.set()

        try:
            signal.signal(signal.SIGTERM, _on_sigterm)
        except ValueError:
            pass
        if self._lease is not None:
            # Leadership gates EVERYTHING below: a standby must not
            # boot replicas, bind, or publish router_addr/router.pid
            # until it actually holds the lease — the active router's
            # clients are still reading those files.
            t_wait = time.time()
            if self.opts.standby:
                self.console(f"[router] standby: watching lease in "
                             f"{self.opts.fleet_dir} as "
                             f"{self._lease.holder}")
                if not leader.wait_for_leadership(
                        self._lease, stop=self._stop):
                    self.console("[router] standby stopped before "
                                 "taking over")
                    self.metrics.close()
                    return 0
                takeover_s = round(time.time() - t_wait, 3)
                self.metrics.emit("leader_elected",
                                  epoch=self._lease.epoch,
                                  holder=self._lease.holder,
                                  standby=True, takeover_s=takeover_s)
                self.console(f"[router] standby took over: epoch "
                             f"{self._lease.epoch} after {takeover_s}s")
            else:
                if not self._lease.acquire():
                    st, _ = self._lease.peek()
                    self.console(
                        f"[router] lease in {self.opts.fleet_dir} is "
                        f"held by "
                        f"{st.holder if st else 'unknown'} — start "
                        f"with --standby to wait for it")
                    self.metrics.close()
                    return 1
                self.metrics.emit("leader_elected",
                                  epoch=self._lease.epoch,
                                  holder=self._lease.holder,
                                  standby=False)
            renewer = threading.Thread(target=self._lease_loop,
                                       name="g2v-router-lease",
                                       daemon=True)
            renewer.start()
        self.boot_fleet()
        host, port = protocol.parse_addr(self.opts.listen)
        srv = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        srv.bind((host, port))
        srv.listen(64)
        srv.settimeout(0.25)
        self.tcp_addr = srv.getsockname()[:2]
        addr_file = os.path.join(self.opts.fleet_dir, "router_addr")
        with open(addr_file + ".tmp", "w") as fh:
            fh.write(f"{self.tcp_addr[0]}:{self.tcp_addr[1]}\n")
        os.replace(addr_file + ".tmp", addr_file)
        with open(os.path.join(self.opts.fleet_dir, "router.pid"),
                  "w") as fh:
            fh.write(str(os.getpid()))
        prober = threading.Thread(target=self._probe_loop,
                                  name="g2v-router-probe", daemon=True)
        prober.start()
        scaler = threading.Thread(target=self._scale_loop,
                                  name="g2v-router-scale", daemon=True)
        scaler.start()
        with self._hlock:
            active_n = len(self._active)
        self.metrics.emit("router_start", pid=os.getpid(),
                          listen=f"{self.tcp_addr[0]}:{self.tcp_addr[1]}",
                          replicas=self.fleet.names())
        self.console(f"[router] fronting {active_n} of "
                     f"{len(self.fleet.names())} replica(s) on "
                     f"{self.tcp_addr[0]}:{self.tcp_addr[1]} "
                     f"(fleet {self.opts.fleet_dir}"
                     f"{', elastic' if self._elastic else ''})")
        try:
            while not self._stop.is_set():
                try:
                    conn, _ = srv.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(target=self._handle_conn, args=(conn,),
                                 name="g2v-router-conn",
                                 daemon=True).start()
        finally:
            srv.close()
            prober.join(timeout=5.0)
            scaler.join(timeout=5.0)
            self.fleet.stop_all(grace_s=60.0)
            if self._lease is not None:
                # Clean exit: drop the lease so a standby takes over
                # immediately instead of waiting out the ttl.
                self._lease.release()
            self.metrics.emit("router_stop", jobs_routed=self.jobs_routed,
                              failovers=self.failovers)
            self.metrics.close()
            self.console(f"[router] stopped ({self.jobs_routed} job(s) "
                         f"routed, {self.failovers} failover(s))")
        return 0
